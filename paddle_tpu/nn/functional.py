"""nn.functional (ref: python/paddle/nn/functional/*).

All ops are jnp/lax-level functions dispatched through the autograd tape via
apply_op, so they work both eagerly and under jit. Convolutions and pooling
lower to lax.conv_general_dilated / lax.reduce_window which XLA maps onto the
TPU MXU / vector unit.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np

from .. import framework
from ..autograd import apply_op
from ..framework import next_rng_key
from ..tensor import Tensor, to_tensor

__all__ = [
    # activations
    "relu", "relu6", "relu_", "gelu", "silu", "swish", "sigmoid", "tanh",
    "softmax", "log_softmax", "leaky_relu", "prelu", "elu", "selu", "celu",
    "glu", "hardswish", "hardsigmoid", "hardtanh", "hardshrink", "mish",
    "softplus", "softshrink", "softsign", "tanhshrink", "thresholded_relu",
    "maxout", "rrelu", "gumbel_softmax",
    # linear/conv
    "linear", "conv1d", "conv2d", "conv3d", "conv1d_transpose",
    "conv2d_transpose", "conv3d_transpose", "embedding",
    # pooling
    "avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d", "max_pool2d",
    "max_pool3d", "adaptive_avg_pool1d", "adaptive_avg_pool2d",
    "adaptive_avg_pool3d", "adaptive_max_pool1d", "adaptive_max_pool2d",
    "lp_pool1d", "lp_pool2d",
    # norm
    "batch_norm", "layer_norm", "group_norm", "instance_norm", "rms_norm",
    "local_response_norm", "normalize",
    # dropout & regularization
    "dropout", "dropout2d", "dropout3d", "alpha_dropout",
    # vision
    "interpolate", "upsample", "pixel_shuffle", "pixel_unshuffle",
    "channel_shuffle", "pad", "unfold", "fold", "affine_grid", "grid_sample",
    # loss
    "cross_entropy", "softmax_with_cross_entropy", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "mse_loss", "l1_loss",
    "smooth_l1_loss", "nll_loss", "kl_div", "margin_ranking_loss",
    "cosine_embedding_loss", "hinge_embedding_loss", "triplet_margin_loss",
    "poisson_nll_loss", "huber_loss", "sigmoid_focal_loss", "dice_loss",
    "log_loss", "square_error_cost", "ctc_loss", "label_smooth",
    # attention & misc
    "scaled_dot_product_attention", "one_hot", "cosine_similarity",
    "pairwise_distance", "linear_dtype_guard", "sequence_mask", "temporal_shift",
    "gaussian_nll_loss", "soft_margin_loss", "multi_label_soft_margin_loss",
    "multi_margin_loss", "triplet_margin_with_distance_loss", "zeropad2d",
    "max_unpool2d",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


# ---------------------------------------------------------------------------
# activations
# ---------------------------------------------------------------------------
def relu(x, name=None):
    return apply_op(jax.nn.relu, _t(x))


def relu_(x, name=None):
    return x._inplace(relu(x))


def relu6(x, name=None):
    return apply_op(jax.nn.relu6, _t(x))


def gelu(x, approximate=False, name=None):
    return apply_op(lambda a: jax.nn.gelu(a, approximate=approximate), _t(x))


def silu(x, name=None):
    return apply_op(jax.nn.silu, _t(x))


def swish(x, name=None):
    return silu(x)


def sigmoid(x, name=None):
    return apply_op(jax.nn.sigmoid, _t(x))


def tanh(x, name=None):
    return apply_op(jnp.tanh, _t(x))


def softmax(x, axis=-1, dtype=None, name=None):
    dt = framework.convert_dtype(dtype)
    def f(a):
        if dt is not None:
            a = a.astype(dt)
        return jax.nn.softmax(a, axis=axis)
    return apply_op(f, _t(x))


def log_softmax(x, axis=-1, dtype=None, name=None):
    dt = framework.convert_dtype(dtype)
    def f(a):
        if dt is not None:
            a = a.astype(dt)
        return jax.nn.log_softmax(a, axis=axis)
    return apply_op(f, _t(x))


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply_op(lambda a: jax.nn.leaky_relu(a, negative_slope), _t(x))


def prelu(x, weight, data_format="NCHW", name=None):
    def f(a, w):
        if w.size > 1:
            shape = [1] * a.ndim
            ch_axis = 1 if data_format[1] == "C" else a.ndim - 1
            shape[ch_axis] = w.size
            w = w.reshape(shape)
        return jnp.where(a >= 0, a, w * a)
    return apply_op(f, _t(x), _t(weight))


def elu(x, alpha=1.0, name=None):
    return apply_op(lambda a: jax.nn.elu(a, alpha), _t(x))


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply_op(lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)), _t(x))


def celu(x, alpha=1.0, name=None):
    return apply_op(lambda a: jax.nn.celu(a, alpha), _t(x))


def glu(x, axis=-1, name=None):
    return apply_op(lambda a: jax.nn.glu(a, axis=axis), _t(x))


def hardswish(x, name=None):
    return apply_op(jax.nn.hard_swish, _t(x))


def hardsigmoid(x, slope=1.0 / 6, offset=0.5, name=None):
    return apply_op(lambda a: jnp.clip(slope * a + offset, 0.0, 1.0), _t(x))


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply_op(lambda a: jnp.clip(a, min, max), _t(x))


def hardshrink(x, threshold=0.5, name=None):
    return apply_op(lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0), _t(x))


def mish(x, name=None):
    return apply_op(lambda a: a * jnp.tanh(jax.nn.softplus(a)), _t(x))


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply_op(
        lambda a: jnp.where(beta * a > threshold, a,
                            jax.nn.softplus(beta * a) / beta), _t(x))


def softshrink(x, threshold=0.5, name=None):
    return apply_op(
        lambda a: jnp.where(a > threshold, a - threshold,
                            jnp.where(a < -threshold, a + threshold, 0.0)), _t(x))


def softsign(x, name=None):
    return apply_op(jax.nn.soft_sign, _t(x))


def tanhshrink(x, name=None):
    return apply_op(lambda a: a - jnp.tanh(a), _t(x))


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return apply_op(lambda a: jnp.where(a > threshold, a, value), _t(x))


def maxout(x, groups, axis=1, name=None):
    def f(a):
        ax = axis % a.ndim
        c = a.shape[ax]
        new_shape = a.shape[:ax] + (c // groups, groups) + a.shape[ax + 1:]
        return jnp.max(a.reshape(new_shape), axis=ax + 1)
    return apply_op(f, _t(x))


def rrelu(x, lower=1.0 / 8, upper=1.0 / 3, training=True, name=None):
    if training:
        key = next_rng_key()
        def f(a):
            slope = jax.random.uniform(key, a.shape, minval=lower, maxval=upper,
                                       dtype=jnp.float32).astype(a.dtype)
            return jnp.where(a >= 0, a, slope * a)
        return apply_op(f, _t(x))
    mid = (lower + upper) / 2
    return leaky_relu(x, mid)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    key = next_rng_key()
    def f(a):
        g = jax.random.gumbel(key, a.shape, dtype=a.dtype)
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            onehot = jnp.zeros_like(y)
            onehot = jnp.put_along_axis(onehot, idx, 1.0, axis=axis, inplace=False)
            y = onehot + y - jax.lax.stop_gradient(y)
        return y
    return apply_op(f, _t(x))


# ---------------------------------------------------------------------------
# linear / conv / embedding
# ---------------------------------------------------------------------------
def linear(x, weight, bias=None, name=None):
    """Reference weight layout: [in_features, out_features]."""
    if bias is None:
        return apply_op(lambda a, w: a @ w, _t(x), _t(weight))
    return apply_op(lambda a, w, b: a @ w + b, _t(x), _t(weight), _t(bias))


def linear_dtype_guard(x):
    return x


def _norm_tuple(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    v = tuple(int(i) for i in v)
    return v if len(v) == n else v * n


def _conv_padding(padding, n, kernel, dilation):
    """Paddle padding spec -> lax padding list of (lo, hi) per spatial dim."""
    if isinstance(padding, str):
        return padding.upper()  # 'SAME' | 'VALID'
    if isinstance(padding, (int, np.integer)):
        return [(int(padding), int(padding))] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, (int, np.integer)) for p in padding):
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    # list of pairs
    return [tuple(int(q) for q in p) for p in padding]


def _conv(x, weight, bias, stride, padding, dilation, groups, n,
          data_format, transpose=False, output_padding=0,
          weight_format="OIHW"):
    stride = _norm_tuple(stride, n)
    dilation = _norm_tuple(dilation, n)
    channel_last = data_format.endswith("C")
    spatial = "DHW"[-n:] if n > 1 else "W"
    if channel_last:
        lhs_spec = "N" + spatial + "C"
    else:
        lhs_spec = "NC" + spatial
    if weight_format == "HWIO":
        # TPU-native channels-last kernels: [*k, in/g, out]. No per-step
        # transpose between the stored Parameter and what the conv
        # consumes (see layers_conv.to_channels_last / docs/performance).
        if transpose:
            raise ValueError("weight_format='HWIO' is not supported for "
                             "transpose convs (kept NCHW-path only)")
        rhs_spec = spatial + "IO"
        kernel = tuple(weight.shape[:n])
    elif weight_format == "OIHW":
        rhs_spec = "OI" + spatial
        kernel = tuple(weight.shape[2:])
    else:
        raise ValueError(f"unknown weight_format {weight_format!r} "
                         "(OIHW | HWIO)")
    out_spec = lhs_spec
    dn = jax.lax.conv_dimension_numbers(
        tuple(x.shape), tuple(weight.shape), (lhs_spec, rhs_spec, out_spec))
    pad = _conv_padding(padding, n, kernel, dilation)

    def f(a, w, *b):
        if not transpose:
            out = jax.lax.conv_general_dilated(
                a, w, window_strides=stride, padding=pad,
                rhs_dilation=dilation, dimension_numbers=dn,
                feature_group_count=groups,
                preferred_element_type=jnp.float32 if a.dtype == jnp.float32 else None)
        else:
            # conv_transpose: gradient of conv w.r.t. input. weight layout in
            # the reference is [in_c, out_c/groups, *k].
            opad = _norm_tuple(output_padding, n)
            pads = pad
            if isinstance(pads, str):
                raise ValueError("string padding unsupported for transpose conv")
            k_eff = [(kernel[i] - 1) * dilation[i] + 1 for i in range(n)]
            tpad = [(k_eff[i] - 1 - pads[i][0],
                     k_eff[i] - 1 - pads[i][1] + opad[i]) for i in range(n)]
            w_t = jnp.swapaxes(w, 0, 1)  # [out_c/g, in_c, *k]
            w_t = jnp.flip(w_t, axis=tuple(range(2, 2 + n)))
            if groups > 1:
                # [in_c, out_c/g, *k] -> grouped: in_c = g * (in_c/g)
                icg = a.shape[1 if not channel_last else -1] // groups
                ws = w.reshape((groups, icg) + w.shape[1:])
                w_t = jnp.concatenate(
                    [jnp.flip(jnp.swapaxes(ws[g], 0, 1), axis=tuple(range(2, 2 + n)))
                     for g in range(groups)], axis=0)
            out = jax.lax.conv_general_dilated(
                a, w_t, window_strides=(1,) * n, padding=tpad,
                lhs_dilation=stride, rhs_dilation=dilation,
                dimension_numbers=dn, feature_group_count=groups)
        if b:
            bias_shape = [1] * out.ndim
            bias_shape[1 if not channel_last else -1] = b[0].shape[0]
            out = out + b[0].reshape(bias_shape)
        return out

    args = (_t(x), _t(weight)) + ((_t(bias),) if bias is not None else ())
    return apply_op(f, *args)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None, weight_format="OIHW"):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1,
                 data_format, weight_format=weight_format)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None, weight_format="OIHW"):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 data_format, weight_format=weight_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None, weight_format="OIHW"):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3,
                 data_format, weight_format=weight_format)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1,
                 data_format, transpose=True, output_padding=output_padding)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 data_format, transpose=True, output_padding=output_padding)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3,
                 data_format, transpose=True, output_padding=output_padding)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    def f(i, w):
        out = jnp.take(w, i, axis=0)
        if padding_idx is not None:
            mask = (i == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out
    return apply_op(f, _t(x), _t(weight))


# ---------------------------------------------------------------------------
# pooling
# ---------------------------------------------------------------------------
def _pool(x, kernel_size, stride, padding, n, reducer, init, data_format,
          ceil_mode=False, exclusive=True, count_include_pad=False,
          return_mask=False):
    k = _norm_tuple(kernel_size, n)
    s = _norm_tuple(stride if stride is not None else kernel_size, n)
    channel_last = data_format.endswith("C")
    sp_off = 1 if channel_last else 2
    pad = _conv_padding(padding, n, k, (1,) * n)
    if not isinstance(pad, str) and ceil_mode:
        # extend the high pad so the last partial window is kept
        pad = list(pad)
        for d in range(n):
            size = x.shape[sp_off + d] + pad[d][0] + pad[d][1]
            rem = (size - k[d]) % s[d]
            if rem:
                pad[d] = (pad[d][0], pad[d][1] + (s[d] - rem))
    if channel_last:
        dims = (1,) + k + (1,)
        strides = (1,) + s + (1,)
    else:
        dims = (1, 1) + k
        strides = (1, 1) + s
    if isinstance(pad, str):
        pad_cfg = pad
    else:
        if channel_last:
            pad_cfg = [(0, 0)] + list(pad) + [(0, 0)]
        else:
            pad_cfg = [(0, 0), (0, 0)] + list(pad)

    def f(a):
        if reducer == "max":
            neg = -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating) else \
                jnp.iinfo(a.dtype).min
            return jax.lax.reduce_window(a, neg, jax.lax.max, dims, strides,
                                         pad_cfg)
        ones = jnp.ones_like(a)
        summed = jax.lax.reduce_window(a, 0.0, jax.lax.add, dims, strides, pad_cfg)
        if count_include_pad:
            denom = float(np.prod(k))
            return summed / denom
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, dims, strides, pad_cfg)
        return summed / counts

    out = apply_op(f, _t(x))
    if not return_mask:
        return out

    # argmax indices (flattened over the window's spatial positions, like
    # the reference's mask output). reduce_window over a packed value+index
    # monoid: encode index in the fractional ordering by a lexicographic max
    # on (value, -index) pairs via two passes.
    def idx_f(a):
        flat_sp = [a.shape[sp_off + d] for d in range(n)]
        # linear index of each element within its spatial volume
        lin = jnp.arange(int(np.prod(flat_sp)), dtype=jnp.int32).reshape(flat_sp)
        shape = [1] * a.ndim
        for d in range(n):
            shape[sp_off + d] = flat_sp[d]
        lin = jnp.broadcast_to(lin.reshape(shape), a.shape)
        neg = -jnp.inf
        def reducer2(p, c):
            pv, pi = p
            cv, ci = c
            take_c = (cv > pv) | ((cv == pv) & (ci < pi))
            return (jnp.where(take_c, cv, pv), jnp.where(take_c, ci, pi))
        vals, idxs = jax.lax.reduce_window(
            (a.astype(jnp.float32), lin), (jnp.float32(neg), jnp.int32(-1)),
            reducer2, dims, strides, pad_cfg)
        return idxs.astype(jnp.int64)
    mask = apply_op(idx_f, _t(x), differentiable=False)
    return out, mask


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, kernel_size, stride, padding, 1, "max", None, data_format,
                 ceil_mode=ceil_mode, return_mask=return_mask)


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, "max", None, data_format,
                 ceil_mode=ceil_mode, return_mask=return_mask)


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, "max", None, data_format,
                 ceil_mode=ceil_mode, return_mask=return_mask)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, kernel_size, stride, padding, 1, "avg", None, data_format,
                 ceil_mode=ceil_mode, count_include_pad=not exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 2, "avg", None, data_format,
                 ceil_mode=ceil_mode, count_include_pad=not exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 3, "avg", None, data_format,
                 ceil_mode=ceil_mode, count_include_pad=not exclusive)


def _adaptive_pool(x, output_size, n, mode, data_format):
    out_sz = _norm_tuple(output_size, n)

    def f(a):
        channel_last = data_format.endswith("C")
        sp_off = 1 if channel_last else 2
        out = a
        for d in range(n):
            axis = sp_off + d
            in_len = out.shape[axis]
            o = out_sz[d]
            if o is None:
                continue
            if in_len % o == 0:
                k = in_len // o
                shape = out.shape[:axis] + (o, k) + out.shape[axis + 1:]
                r = out.reshape(shape)
                out = jnp.max(r, axis=axis + 1) if mode == "max" else jnp.mean(r, axis=axis + 1)
            else:
                # generic: gather windows with per-output start/end
                starts = (np.arange(o) * in_len) // o
                ends = ((np.arange(o) + 1) * in_len + o - 1) // o
                pieces = []
                for s_, e_ in zip(starts, ends):
                    seg = jax.lax.slice_in_dim(out, int(s_), int(e_), axis=axis)
                    red = jnp.max(seg, axis=axis, keepdims=True) if mode == "max" \
                        else jnp.mean(seg, axis=axis, keepdims=True)
                    pieces.append(red)
                out = jnp.concatenate(pieces, axis=axis)
        return out

    return apply_op(f, _t(x))


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, "avg", "NCL")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, "avg", data_format)


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, "avg", data_format)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 1, "max", "NCL")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_pool(x, output_size, 2, "max", "NCHW")


def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCL", name=None):
    p = float(norm_type)
    xp = apply_op(lambda a: jnp.abs(a) ** p, _t(x))
    pooled = _pool(xp, kernel_size, stride, padding, 1, "avg", None,
                   data_format, count_include_pad=True)
    k = _norm_tuple(kernel_size, 1)
    return apply_op(lambda a: (a * float(np.prod(k))) ** (1.0 / p), pooled)


def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW", name=None):
    p = float(norm_type)
    xp = apply_op(lambda a: jnp.abs(a) ** p, _t(x))
    pooled = _pool(xp, kernel_size, stride, padding, 2, "avg", None,
                   data_format, count_include_pad=True)
    k = _norm_tuple(kernel_size, 2)
    return apply_op(lambda a: (a * float(np.prod(k))) ** (1.0 / p), pooled)


# ---------------------------------------------------------------------------
# normalization
# ---------------------------------------------------------------------------
def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", use_global_stats=None, name=None):
    """Functional batchnorm. When training, returns output computed with
    batch stats and *updates the running stat tensors in place* (so the
    functional_call buffer collection picks the new values up)."""
    ch_axis = 1 if not data_format.endswith("C") else -1

    rm, rv = _t(running_mean), _t(running_var)
    use_batch = training and not use_global_stats

    x_t = _t(x)
    reduce_axes = tuple(i for i in range(x_t.ndim) if i != ch_axis % x_t.ndim)

    if use_batch:
        mean = apply_op(lambda a: jnp.mean(a, axis=reduce_axes), x_t)
        var = apply_op(lambda a: jnp.var(a, axis=reduce_axes), x_t)
        # running stat update (reference: momentum * running + (1-m) * batch)
        n = float(np.prod([x_t.shape[i] for i in reduce_axes]))
        unbiased = var * (n / max(n - 1.0, 1.0))
        rm._inplace(rm * momentum + mean.detach() * (1.0 - momentum))
        rv._inplace(rv * momentum + unbiased.detach() * (1.0 - momentum))
    else:
        mean, var = rm, rv

    def f(a, m, v, *wb):
        shape = [1] * a.ndim
        shape[ch_axis] = a.shape[ch_axis]
        inv = jax.lax.rsqrt(v.reshape(shape) + epsilon)
        out = (a - m.reshape(shape)) * inv
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape); i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out

    args = [x_t, mean, var]
    if weight is not None:
        args.append(_t(weight))
    if bias is not None:
        args.append(_t(bias))
    return apply_op(f, *args)


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    if isinstance(normalized_shape, (int, np.integer)):
        normalized_shape = (int(normalized_shape),)
    nd = len(tuple(normalized_shape))

    def f(a, *wb):
        axes = tuple(range(a.ndim - nd, a.ndim))
        mean = jnp.mean(a, axis=axes, keepdims=True)
        var = jnp.var(a, axis=axes, keepdims=True)
        out = (a - mean) * jax.lax.rsqrt(var + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i]; i += 1
        if bias is not None:
            out = out + wb[i]
        return out

    args = [_t(x)]
    if weight is not None:
        args.append(_t(weight))
    if bias is not None:
        args.append(_t(bias))
    return apply_op(f, *args)


def rms_norm(x, weight=None, epsilon=1e-6, axis=-1, name=None):
    def f(a, *w):
        ms = jnp.mean(jnp.square(a.astype(jnp.float32)), axis=axis, keepdims=True)
        out = (a.astype(jnp.float32) * jax.lax.rsqrt(ms + epsilon)).astype(a.dtype)
        if w:
            out = out * w[0]
        return out
    args = [_t(x)] + ([_t(weight)] if weight is not None else [])
    return apply_op(f, *args)


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    channel_last = data_format.endswith("C")

    def f(a, *wb):
        if channel_last:
            a_m = jnp.moveaxis(a, -1, 1)
        else:
            a_m = a
        n, c = a_m.shape[0], a_m.shape[1]
        g = num_groups
        r = a_m.reshape((n, g, c // g) + a_m.shape[2:])
        axes = tuple(range(2, r.ndim))
        mean = jnp.mean(r, axis=axes, keepdims=True)
        var = jnp.var(r, axis=axes, keepdims=True)
        out = ((r - mean) * jax.lax.rsqrt(var + epsilon)).reshape(a_m.shape)
        shape = [1] * a_m.ndim
        shape[1] = c
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape); i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out

    args = [_t(x)]
    if weight is not None:
        args.append(_t(weight))
    if bias is not None:
        args.append(_t(bias))
    return apply_op(f, *args)


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    def f(a, *wb):
        axes = tuple(range(2, a.ndim))
        mean = jnp.mean(a, axis=axes, keepdims=True)
        var = jnp.var(a, axis=axes, keepdims=True)
        out = (a - mean) * jax.lax.rsqrt(var + eps)
        shape = [1, a.shape[1]] + [1] * (a.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape); i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out
    args = [_t(x)]
    if weight is not None:
        args.append(_t(weight))
    if bias is not None:
        args.append(_t(bias))
    return apply_op(f, *args)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def f(a):
        sq = jnp.square(a)
        ch_axis = 1 if not data_format.endswith("C") else a.ndim - 1
        half = size // 2
        pad_width = [(0, 0)] * a.ndim
        pad_width[ch_axis] = (half, size - half - 1)
        padded = jnp.pad(sq, pad_width)
        windows = [jax.lax.slice_in_dim(padded, i, i + a.shape[ch_axis], axis=ch_axis)
                   for i in range(size)]
        s = sum(windows)
        return a / (k + alpha / size * s) ** beta
    return apply_op(f, _t(x))


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def f(a):
        n = jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return a / jnp.maximum(n, epsilon)
    return apply_op(f, _t(x))


# ---------------------------------------------------------------------------
# dropout
# ---------------------------------------------------------------------------
def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    if not training or p == 0.0:
        if p != 0.0 and mode == "downscale_in_infer":
            # ref semantics: no upscale in train => scale by keep-prob at infer
            return apply_op(lambda a: a * (1.0 - p), _t(x))
        return _t(x)
    key = next_rng_key()

    def f(a):
        if axis is None:
            shape = a.shape
        else:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            shape = tuple(a.shape[i] if i in [ax % a.ndim for ax in axes] else 1
                          for i in range(a.ndim))
        keep = jax.random.bernoulli(key, 1.0 - p, shape)
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype)
        return jnp.where(keep, a, 0.0).astype(a.dtype)

    return apply_op(f, _t(x))


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axes = [0, 1] if not data_format.endswith("C") else [0, 3]
    return dropout(x, p=p, axis=axes, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axes = [0, 1] if not data_format.endswith("C") else [0, 4]
    return dropout(x, p=p, axis=axes, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return _t(x)
    key = next_rng_key()

    def f(a):
        alpha = 1.6732632423543772
        scale = 1.0507009873554805
        alpha_p = -alpha * scale
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        q = 1.0 - p
        a_coef = (q + alpha_p ** 2 * q * p) ** -0.5
        b_coef = -a_coef * alpha_p * p
        return a_coef * jnp.where(keep, a, alpha_p) + b_coef

    return apply_op(f, _t(x))


# ---------------------------------------------------------------------------
# vision ops
# ---------------------------------------------------------------------------
@functools.lru_cache(maxsize=256)
def _resize_weight_matrix(in_len, out_len, kind, align_corners,
                          align_mode=0):
    """[out_len, in_len] numpy weights reproducing the reference
    resampling exactly: nearest (legacy floor(i*scale)), linear/cubic
    (half-pixel when align_corners=False and align_mode=0, asymmetric
    i*scale when align_mode=1, corner-aligned when align_corners=True;
    cubic is Keys a=-0.75 with border replicate), and area (adaptive
    mean over [floor(i*s), ceil((i+1)*s)) windows)."""
    W = np.zeros((out_len, in_len), np.float32)
    scale = in_len / out_len
    if kind == "nearest":
        if align_corners and out_len > 1:
            # reference rounds ties UP (static_cast<int>(ratio*i + .5)),
            # not numpy's ties-to-even
            src = np.floor(np.arange(out_len) * ((in_len - 1)
                           / (out_len - 1)) + 0.5).astype(np.int64)
        else:
            src = np.floor(np.arange(out_len) * scale).astype(np.int64)
        W[np.arange(out_len), np.clip(src, 0, in_len - 1)] = 1.0
        return W
    if kind == "area":
        # INTEGER window bounds (the reference's adaptive-pool formula);
        # float floor/ceil drifts for e.g. in=21,out=19 and silently
        # breaks the weights' sum-to-1
        for i in range(out_len):
            lo = (i * in_len) // out_len
            hi = -((-(i + 1) * in_len) // out_len)     # ceil-div
            W[i, lo:hi] = 1.0 / (hi - lo)
        return W
    # continuous source positions for linear/cubic
    i = np.arange(out_len, dtype=np.float64)
    if align_corners:
        src = i * ((in_len - 1) / (out_len - 1)) if out_len > 1 \
            else np.zeros((1,))
    elif align_mode == 1 and kind == "linear":
        # align_mode only affects the linear family in the reference;
        # bicubic always samples half-pixel
        src = i * scale
    else:
        src = (i + 0.5) * scale - 0.5
    if kind == "linear":
        src = np.clip(src, 0, in_len - 1)
        lo = np.floor(src).astype(np.int64)
        hi = np.minimum(lo + 1, in_len - 1)
        w = src - lo
        np.add.at(W, (np.arange(out_len), lo), (1.0 - w))
        np.add.at(W, (np.arange(out_len), hi), w)
        return W
    # cubic: Keys kernel a=-0.75, 4 taps, border replicate (weights from
    # UNCLAMPED distances accumulated into clamped indices — torch/paddle)
    a = -0.75

    def k(t):
        t = np.abs(t)
        return np.where(
            t <= 1, ((a + 2) * t - (a + 3)) * t * t + 1,
            np.where(t < 2, (((t - 5) * t + 8) * t - 4) * a, 0.0))
    lo = np.floor(src).astype(np.int64)
    for tap in (-1, 0, 1, 2):
        idx = lo + tap
        wt = k(src - idx)
        np.add.at(W, (np.arange(out_len), np.clip(idx, 0, in_len - 1)),
                  wt)
    return W


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    channel_last = data_format.endswith("C")
    x_t = _t(x)
    nsp = x_t.ndim - 2
    sp_shape = x_t.shape[1:-1] if channel_last else x_t.shape[2:]
    if size is not None:
        if isinstance(size, Tensor):
            size = [int(v) for v in np.asarray(size._value)]
        out_sp = tuple(int(s) for s in (size if isinstance(size, (list, tuple)) else [size] * nsp))
    else:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * nsp
        out_sp = tuple(int(math.floor(s * f)) for s, f in zip(sp_shape, sf))

    base = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
            "trilinear": "linear", "bicubic": "cubic", "area": "area"}
    if mode not in base:
        raise ValueError(f"unknown interpolate mode {mode!r}")
    kind = base[mode]
    sp_axes = (list(range(1, 1 + nsp)) if channel_last
               else list(range(2, 2 + nsp)))
    # exact reference sampling as ONE static [out, in] weight matrix per
    # spatial axis (separable for every supported mode) — a matmul per
    # axis, which is both bit-exact vs the reference formulas and what
    # the MXU wants; jax.image.resize is NOT used (its antialiased
    # downscale and half-pixel nearest diverge from paddle/torch)
    mats = [_resize_weight_matrix(int(sp_shape[d]), int(out_sp[d]), kind,
                                  align_corners, align_mode)
            for d in range(nsp)]

    def f(a):
        out = a
        for d, ax in enumerate(sp_axes):
            W = jnp.asarray(mats[d], jnp.float32)      # [out, in]
            moved = jnp.tensordot(out.astype(jnp.float32), W,
                                  axes=[[ax], [1]])    # axis -> last
            out = jnp.moveaxis(moved, -1, ax)
        return out.astype(a.dtype)

    return apply_op(f, x_t)


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c // (r * r), r, r, h, w)
            a = a.transpose(0, 1, 4, 2, 5, 3)
            return a.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = a.shape
        a = a.reshape(n, h, w, r, r, c // (r * r))
        a = a.transpose(0, 1, 3, 2, 4, 5)
        return a.reshape(n, h * r, w * r, c // (r * r))

    return apply_op(f, _t(x))


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c, h // r, r, w // r, r)
            a = a.transpose(0, 1, 3, 5, 2, 4)
            return a.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = a.shape
        a = a.reshape(n, h // r, r, w // r, r, c)
        a = a.transpose(0, 1, 3, 2, 4, 5)
        return a.reshape(n, h // r, w // r, c * r * r)

    return apply_op(f, _t(x))


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def f(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, groups, c // groups, h, w)
            a = a.transpose(0, 2, 1, 3, 4)
            return a.reshape(n, c, h, w)
        n, h, w, c = a.shape
        a = a.reshape(n, h, w, groups, c // groups)
        a = a.transpose(0, 1, 2, 4, 3)
        return a.reshape(n, h, w, c)
    return apply_op(f, _t(x))


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    from ..tensor_ops.manip import pad as _pad
    return _pad(x, pad, mode=mode, value=value, data_format=data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (ref: F.unfold). x: [N, C, H, W] -> [N, C*kh*kw, L]."""
    k = _norm_tuple(kernel_sizes, 2)
    s = _norm_tuple(strides, 2)
    d = _norm_tuple(dilations, 2)
    p = _conv_padding(paddings, 2, k, d)

    def f(a):
        n, c, h, w = a.shape
        a_p = jnp.pad(a, [(0, 0), (0, 0), p[0], p[1]])
        hp, wp = a_p.shape[2], a_p.shape[3]
        oh = (hp - (d[0] * (k[0] - 1) + 1)) // s[0] + 1
        ow = (wp - (d[1] * (k[1] - 1) + 1)) // s[1] + 1
        patches = []
        for i in range(k[0]):
            for j in range(k[1]):
                sub = a_p[:, :, i * d[0]: i * d[0] + oh * s[0]: s[0],
                          j * d[1]: j * d[1] + ow * s[1]: s[1]]
                patches.append(sub)
        out = jnp.stack(patches, axis=2)  # [N, C, kh*kw, oh, ow]
        return out.reshape(n, c * k[0] * k[1], oh * ow)

    return apply_op(f, _t(x))


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    """col2im (ref: F.fold)."""
    out_sz = _norm_tuple(output_sizes, 2)
    k = _norm_tuple(kernel_sizes, 2)
    s = _norm_tuple(strides, 2)
    d = _norm_tuple(dilations, 2)
    p = _conv_padding(paddings, 2, k, d)

    def f(a):
        n, ckk, L = a.shape
        c = ckk // (k[0] * k[1])
        hp = out_sz[0] + p[0][0] + p[0][1]
        wp = out_sz[1] + p[1][0] + p[1][1]
        oh = (hp - (d[0] * (k[0] - 1) + 1)) // s[0] + 1
        ow = (wp - (d[1] * (k[1] - 1) + 1)) // s[1] + 1
        a_r = a.reshape(n, c, k[0], k[1], oh, ow)
        out = jnp.zeros((n, c, hp, wp), dtype=a.dtype)
        for i in range(k[0]):
            for j in range(k[1]):
                out = out.at[:, :, i * d[0]: i * d[0] + oh * s[0]: s[0],
                             j * d[1]: j * d[1] + ow * s[1]: s[1]].add(a_r[:, :, i, j])
        return out[:, :, p[0][0]: p[0][0] + out_sz[0], p[1][0]: p[1][0] + out_sz[1]]

    return apply_op(f, _t(x))


def affine_grid(theta, out_shape, align_corners=True, name=None):
    def f(th):
        n, _, h, w = [int(v) for v in
                      (out_shape.tolist() if isinstance(out_shape, Tensor) else out_shape)]
        if align_corners:
            ys = jnp.linspace(-1.0, 1.0, h)
            xs = jnp.linspace(-1.0, 1.0, w)
        else:
            ys = (jnp.arange(h) * 2 + 1) / h - 1
            xs = (jnp.arange(w) * 2 + 1) / w - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)  # [h, w, 3]
        return jnp.einsum("hwk,nck->nhwc", base, th)
    return apply_op(f, _t(theta))


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError(f"unknown padding_mode {padding_mode!r}")
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"unknown mode {mode!r} (bilinear | nearest)")

    def f(a, g):
        n, c, h, w = a.shape
        gx, gy = g[..., 0], g[..., 1]
        if align_corners:
            fx = (gx + 1) * (w - 1) / 2
            fy = (gy + 1) * (h - 1) / 2
        else:
            fx = ((gx + 1) * w - 1) / 2
            fy = ((gy + 1) * h - 1) / 2

        if padding_mode == "reflection":
            # reflect the CONTINUOUS coordinate about the sample-area
            # edges (reference semantics differ by align_corners), then
            # proceed as border within bounds
            def reflect(v, size):
                if align_corners:
                    if size == 1:
                        return jnp.zeros_like(v)
                    span = 2.0 * (size - 1)
                    v = jnp.abs(jnp.mod(v, span))
                    return jnp.where(v > size - 1, span - v, v)
                span = 2.0 * size
                v = jnp.mod(v + 0.5, span)
                v = jnp.abs(v)
                v = jnp.where(v > size, span - v, v)
                return jnp.clip(v - 0.5, 0, size - 1)
            fx = reflect(fx, w)
            fy = reflect(fy, h)

        def sample(ix, iy):
            ixc = jnp.clip(ix, 0, w - 1)
            iyc = jnp.clip(iy, 0, h - 1)
            valid = (ix >= 0) & (ix <= w - 1) & (iy >= 0) & (iy <= h - 1)
            vals = a[jnp.arange(n)[:, None, None], :, iyc, ixc]  # [n, gh, gw, c]
            if padding_mode == "zeros":
                vals = jnp.where(valid[..., None], vals, 0.0)
            return vals

        if mode == "nearest":
            out = sample(jnp.round(fx).astype(jnp.int32),
                         jnp.round(fy).astype(jnp.int32))
        else:
            x0 = jnp.floor(fx).astype(jnp.int32)
            y0 = jnp.floor(fy).astype(jnp.int32)
            x1, y1 = x0 + 1, y0 + 1
            wx = fx - x0
            wy = fy - y0
            v00 = sample(x0, y0)
            v01 = sample(x1, y0)
            v10 = sample(x0, y1)
            v11 = sample(x1, y1)
            out = (v00 * ((1 - wx) * (1 - wy))[..., None]
                   + v01 * (wx * (1 - wy))[..., None]
                   + v10 * ((1 - wx) * wy)[..., None]
                   + v11 * (wx * wy)[..., None])
        return jnp.moveaxis(out, -1, 1)

    return apply_op(f, _t(x), _t(grid))


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    def f(a):
        nt, c, h, w = a.shape
        n = nt // seg_num
        r = a.reshape(n, seg_num, c, h, w)
        fold_c = int(c * shift_ratio)
        left = jnp.concatenate([r[:, 1:, :fold_c], jnp.zeros_like(r[:, :1, :fold_c])], axis=1)
        right = jnp.concatenate([jnp.zeros_like(r[:, :1, fold_c:2 * fold_c]),
                                 r[:, :-1, fold_c:2 * fold_c]], axis=1)
        rest = r[:, :, 2 * fold_c:]
        return jnp.concatenate([left, right, rest], axis=2).reshape(nt, c, h, w)
    return apply_op(f, _t(x))


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------
def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    """ref: F.cross_entropy (python/paddle/nn/functional/loss.py)."""
    def f(logits, lab, *w):
        if use_softmax:
            logp = jax.nn.log_softmax(logits, axis=axis)
        else:
            logp = jnp.log(jnp.maximum(logits, 1e-30))
        n_cls = logits.shape[axis]
        if soft_label:
            soft = lab
            if label_smoothing > 0:
                soft = soft * (1 - label_smoothing) + label_smoothing / n_cls
            loss = -jnp.sum(soft * logp, axis=axis)
            if w:
                cls_w = jnp.sum(soft * w[0], axis=axis)
                loss = loss * cls_w
            return _reduce(loss, reduction)
        lab_i = lab.astype(jnp.int32)
        squeeze = False
        if lab_i.ndim == logp.ndim and lab_i.shape[axis] == 1:
            lab_i = jnp.squeeze(lab_i, axis=axis)
            squeeze = True
        valid = lab_i != ignore_index
        lab_safe = jnp.where(valid, lab_i, 0)
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(lab_safe, axis), axis=axis)
        picked = jnp.squeeze(picked, axis=axis)
        if label_smoothing > 0:
            smooth_loss = -jnp.mean(logp, axis=axis)
            loss = -(1 - label_smoothing) * picked + label_smoothing * smooth_loss
        else:
            loss = -picked
        if w:
            loss = loss * jnp.take(w[0], lab_safe)
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            if w:
                denom = jnp.sum(jnp.where(valid, jnp.take(w[0], lab_safe), 0.0))
            else:
                denom = jnp.sum(valid.astype(loss.dtype))
            return jnp.sum(loss) / jnp.maximum(denom, 1e-12)
        return _reduce(loss, reduction)

    args = [_t(input), _t(label)]
    if weight is not None:
        args.append(_t(weight))
    return apply_op(f, *args)


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    loss = loss.unsqueeze(axis) if not soft_label else loss
    if return_softmax:
        return loss, softmax(logits, axis=axis)
    return loss


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def f(p, y, *w):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)
    args = [_t(input), _t(label)]
    if weight is not None:
        args.append(_t(weight))
    return apply_op(f, *args)


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    def f(z, y, *rest):
        i = 0
        w = pw = None
        if weight is not None:
            w = rest[i]; i += 1
        if pos_weight is not None:
            pw = rest[i]
        log_sig = jax.nn.log_sigmoid(z)
        log_sig_neg = jax.nn.log_sigmoid(-z)
        if pw is not None:
            loss = -(pw * y * log_sig + (1 - y) * log_sig_neg)
        else:
            loss = -(y * log_sig + (1 - y) * log_sig_neg)
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)
    args = [_t(logit), _t(label)]
    if weight is not None:
        args.append(_t(weight))
    if pos_weight is not None:
        args.append(_t(pos_weight))
    return apply_op(f, *args)


def mse_loss(input, label, reduction="mean", name=None):
    return apply_op(lambda a, b: _reduce(jnp.square(a - b), reduction),
                    _t(input), _t(label))


def l1_loss(input, label, reduction="mean", name=None):
    return apply_op(lambda a, b: _reduce(jnp.abs(a - b), reduction),
                    _t(input), _t(label))


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def f(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        return _reduce(loss, reduction)
    return apply_op(f, _t(input), _t(label))


def huber_loss(input, label, delta=1.0, reduction="mean", name=None):
    def f(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d <= delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(loss, reduction)
    return apply_op(f, _t(input), _t(label))


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    def f(logp, lab, *w):
        lab_i = lab.astype(jnp.int32)
        valid = lab_i != ignore_index
        lab_safe = jnp.where(valid, lab_i, 0)
        if logp.ndim > 2:
            # [N, C, d1...] -> move C last
            lp = jnp.moveaxis(logp, 1, -1)
        else:
            lp = logp
        picked = jnp.take_along_axis(lp, lab_safe[..., None], axis=-1)[..., 0]
        loss = -picked
        if w:
            loss = loss * jnp.take(w[0], lab_safe)
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            denom = jnp.sum(jnp.where(
                valid, jnp.take(w[0], lab_safe) if w else 1.0, 0.0))
            return jnp.sum(loss) / jnp.maximum(denom, 1e-12)
        return _reduce(loss, reduction)
    args = [_t(input), _t(label)]
    if weight is not None:
        args.append(_t(weight))
    return apply_op(f, *args)


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def f(lp, y):
        if log_target:
            loss = jnp.exp(y) * (y - lp)
        else:
            loss = y * (jnp.log(jnp.maximum(y, 1e-30)) - lp)
        if reduction == "batchmean":
            return jnp.sum(loss) / lp.shape[0]
        return _reduce(loss, reduction)
    return apply_op(f, _t(input), _t(label))


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    return apply_op(
        lambda a, b, y: _reduce(jnp.maximum(0.0, -y * (a - b) + margin), reduction),
        _t(input), _t(other), _t(label))


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean",
                          name=None):
    def f(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)
    return apply_op(f, _t(input1), _t(input2), _t(label))


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def f(a, y):
        loss = jnp.where(y == 1, a, jnp.maximum(0.0, margin - a))
        return _reduce(loss, reduction)
    return apply_op(f, _t(input), _t(label))


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def f(a, pos, neg):
        dp = jnp.sum(jnp.abs(a - pos) ** p, axis=-1) ** (1 / p)
        dn = jnp.sum(jnp.abs(a - neg) ** p, axis=-1) ** (1 / p)
        if swap:
            dsw = jnp.sum(jnp.abs(pos - neg) ** p, axis=-1) ** (1 / p)
            dn = jnp.minimum(dn, dsw)
        return _reduce(jnp.maximum(0.0, dp - dn + margin), reduction)
    return apply_op(f, _t(input), _t(positive), _t(negative))


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    def f(a, y):
        if log_input:
            loss = jnp.exp(a) - y * a
        else:
            loss = a - y * jnp.log(a + epsilon)
        if full:
            stirling = y * jnp.log(jnp.maximum(y, 1.0)) - y + \
                0.5 * jnp.log(2 * jnp.pi * jnp.maximum(y, 1.0))
            loss = loss + jnp.where(y > 1, stirling, 0.0)
        return _reduce(loss, reduction)
    return apply_op(f, _t(input), _t(label))


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def f(z, y, *nrm):
        p = jax.nn.sigmoid(z)
        ce = -(y * jax.nn.log_sigmoid(z) + (1 - y) * jax.nn.log_sigmoid(-z))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if nrm:
            loss = loss / nrm[0]
        return _reduce(loss, reduction)
    args = [_t(logit), _t(label)]
    if normalizer is not None:
        args.append(_t(normalizer))
    return apply_op(f, *args)


def dice_loss(input, label, epsilon=1e-5, name=None):
    def f(p, y):
        yoh = jax.nn.one_hot(y.astype(jnp.int32)[..., 0], p.shape[-1], dtype=p.dtype)
        reduce_dims = tuple(range(1, p.ndim))
        inter = jnp.sum(p * yoh, axis=reduce_dims)
        union = jnp.sum(p, axis=reduce_dims) + jnp.sum(yoh, axis=reduce_dims)
        dice = (2 * inter + epsilon) / (union + epsilon)
        return jnp.mean(1 - dice)
    return apply_op(f, _t(input), _t(label))


def log_loss(input, label, epsilon=1e-4, name=None):
    return apply_op(
        lambda p, y: -(y * jnp.log(p + epsilon) + (1 - y) * jnp.log(1 - p + epsilon)),
        _t(input), _t(label))


def square_error_cost(input, label):
    return apply_op(lambda a, b: jnp.square(a - b), _t(input), _t(label))


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via optax's implementation (log-domain forward algorithm)."""
    import optax
    def f(lp, lab, il, ll):
        # optax expects [B, T, C] logits and paddings
        logits = jnp.transpose(lp, (1, 0, 2)) if lp.ndim == 3 else lp
        b, t, _ = logits.shape
        logit_pad = (jnp.arange(t)[None, :] >= il[:, None]).astype(jnp.float32)
        lab_pad = (jnp.arange(lab.shape[1])[None, :] >= ll[:, None]).astype(jnp.float32)
        per_seq = optax.ctc_loss(logits, logit_pad, lab.astype(jnp.int32),
                                 lab_pad, blank_id=blank)
        if reduction == "mean":
            return jnp.mean(per_seq / jnp.maximum(ll.astype(per_seq.dtype), 1.0))
        return _reduce(per_seq, reduction)
    return apply_op(f, _t(log_probs), _t(labels), _t(input_lengths), _t(label_lengths))


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def f(y, *pd):
        n = y.shape[-1]
        if pd:
            return (1 - epsilon) * y + epsilon * pd[0]
        return (1 - epsilon) * y + epsilon / n
    args = [_t(label)]
    if prior_dist is not None:
        args.append(_t(prior_dist))
    return apply_op(f, *args)


# ---------------------------------------------------------------------------
# attention & misc
# ---------------------------------------------------------------------------
def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, use_flash=True, name=None):
    """ref: F.scaled_dot_product_attention — [B, S, H, D] layout.

    Routes to the Pallas TPU flash-attention kernel when shapes allow;
    otherwise the jnp reference path (still XLA-fused on TPU).
    `use_flash=False` forces the jnp path (tpu-native extension, consumed
    by GPTConfig.use_flash_attention).
    """
    from ..ops import flash_attention_available, flash_attention
    q, k, v = _t(query), _t(key), _t(value)
    eff_drop = float(dropout_p) if (dropout_p and training) else 0.0
    if (use_flash
            and flash_attention_available(q.shape, k.shape, attn_mask,
                                          eff_drop)
            and training is not None):
        if eff_drop:
            # in-kernel dropout: seed folds from the step's rng stream so
            # every step (and every jitted-step invocation) gets fresh masks
            seed = jax.random.randint(next_rng_key(), (), 0, 2 ** 31 - 1,
                                      dtype=jnp.int32)
            return apply_op(
                lambda qq, kk, vv, sd: flash_attention(
                    qq, kk, vv, causal=is_causal, dropout_p=eff_drop,
                    dropout_seed=sd),
                q, k, v, _t(seed))
        return apply_op(
            lambda qq, kk, vv: flash_attention(qq, kk, vv, causal=is_causal),
            q, k, v)

    drop_key = next_rng_key() if (dropout_p > 0 and training) else None

    def f(qq, kk, vv, *m):
        # [B, S, H, D] -> [B, H, S, D]
        qq, kk, vv = (jnp.swapaxes(a, 1, 2) for a in (qq, kk, vv))
        scale = 1.0 / math.sqrt(qq.shape[-1])
        logits = jnp.einsum("bhqd,bhkd->bhqk", qq, kk) * scale
        if is_causal:
            s_q, s_k = logits.shape[-2], logits.shape[-1]
            mask = jnp.tril(jnp.ones((s_q, s_k), dtype=bool), k=s_k - s_q)
            logits = jnp.where(mask, logits, -jnp.inf)
        if m:
            mm = m[0]
            if mm.dtype == jnp.bool_:
                logits = jnp.where(mm, logits, -jnp.inf)
            else:
                logits = logits + mm
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(qq.dtype)
        if drop_key is not None:
            keep = jax.random.bernoulli(drop_key, 1 - dropout_p, probs.shape)
            probs = jnp.where(keep, probs / (1 - dropout_p), 0.0)
        out = jnp.einsum("bhqk,bhkd->bhqd", probs, vv)
        return jnp.swapaxes(out, 1, 2)

    args = [q, k, v]
    if attn_mask is not None:
        args.append(_t(attn_mask))
    return apply_op(f, *args)


def one_hot(x, num_classes, name=None):
    return apply_op(
        lambda i: jax.nn.one_hot(i.astype(jnp.int32), num_classes,
                                 dtype=framework.get_default_dtype()),
        _t(x), differentiable=False)


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def f(a, b):
        num = jnp.sum(a * b, axis=axis)
        den = jnp.linalg.norm(a, axis=axis) * jnp.linalg.norm(b, axis=axis)
        return num / jnp.maximum(den, eps)
    return apply_op(f, _t(x1), _t(x2))


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    def f(a, b):
        d = a - b + epsilon
        return jnp.sum(jnp.abs(d) ** p, axis=-1, keepdims=keepdim) ** (1.0 / p)
    return apply_op(f, _t(x), _t(y))


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    def f(lens):
        m = maxlen if maxlen is not None else int(jnp.max(lens))
        return (jnp.arange(m)[None, :] < lens[..., None]).astype(
            framework.convert_dtype(dtype))
    return apply_op(f, _t(x), differentiable=False)


# ---------------------------------------------------------------------------
# long-tail losses / ops (ref: python/paddle/nn/functional/loss.py,
# common.py) — round-2 API sweep additions
# ---------------------------------------------------------------------------
def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    """ref: F.gaussian_nll_loss."""
    import math

    def f(mu, y, var):
        var = jnp.maximum(var, epsilon)
        out = 0.5 * (jnp.log(var) + (y - mu) ** 2 / var)
        if full:
            out = out + 0.5 * math.log(2 * math.pi)
        return _reduce(out, reduction)
    return apply_op(f, _t(input), _t(label), _t(variance))


def soft_margin_loss(input, label, reduction="mean", name=None):
    """ref: F.soft_margin_loss — log(1 + exp(-y * x))."""
    def f(x, y):
        return _reduce(jax.nn.softplus(-y * x), reduction)
    return apply_op(f, _t(input), _t(label))


def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean", name=None):
    """ref: F.multi_label_soft_margin_loss."""
    args = [_t(input), _t(label)]
    if weight is not None:
        args.append(_t(weight))

    def f(x, y, *w):
        per = -(y * jax.nn.log_sigmoid(x)
                + (1 - y) * jax.nn.log_sigmoid(-x))
        if w:
            per = per * w[0]
        return _reduce(jnp.mean(per, -1), reduction)
    return apply_op(f, *args)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    """ref: F.multi_margin_loss (hinge over classes)."""
    args = [_t(input), _t(label)]
    if weight is not None:
        args.append(_t(weight))

    def f(x, y, *w):
        n, c = x.shape
        yi = y.astype(jnp.int32)
        xy = jnp.take_along_axis(x, yi[:, None], 1)       # [N,1]
        m = jnp.maximum(0.0, margin - xy + x) ** p
        if w:
            m = m * w[0][yi][:, None]
        onehot = jax.nn.one_hot(yi, c, dtype=x.dtype)
        per = jnp.sum(m * (1 - onehot), -1) / c
        return _reduce(per, reduction)
    return apply_op(f, *args)


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    """ref: F.triplet_margin_with_distance_loss."""
    dist = distance_function

    def f(a, p, n):
        if dist is None:
            def d(u, v):
                return jnp.sqrt(jnp.sum((u - v) ** 2, -1) + 1e-12)
        else:
            def d(u, v):
                r = dist(Tensor(u), Tensor(v))
                return r._value if isinstance(r, Tensor) else r
        dp = d(a, p)
        dn = d(a, n)
        if swap:
            dn = jnp.minimum(dn, d(p, n))
        return _reduce(jnp.maximum(0.0, dp - dn + margin), reduction)
    return apply_op(f, _t(input), _t(positive), _t(negative))


def zeropad2d(x, padding, data_format="NCHW", name=None):
    """ref: F.zeropad2d — padding [left, right, top, bottom]."""
    l, r, t_, b = [int(v) for v in padding]

    def f(a):
        if data_format == "NCHW":
            cfg = [(0, 0), (0, 0), (t_, b), (l, r)]
        else:
            cfg = [(0, 0), (t_, b), (l, r), (0, 0)]
        return jnp.pad(a, cfg)
    return apply_op(f, _t(x))


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    """ref: F.max_unpool2d — scatter pooled values back to the positions
    recorded by max_pool2d(return_mask=True). Static-shape scatter."""
    ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) \
        else tuple(kernel_size)
    st = ks if stride is None else (
        (stride, stride) if isinstance(stride, int) else tuple(stride))
    pd = (padding, padding) if isinstance(padding, int) else tuple(padding)

    def f(v, idx):
        n, c, h, w = v.shape
        if output_size is not None:
            oh, ow = [int(s) for s in output_size[-2:]]
        else:
            oh = (h - 1) * st[0] - 2 * pd[0] + ks[0]
            ow = (w - 1) * st[1] - 2 * pd[1] + ks[1]
        flat = jnp.zeros((n, c, oh * ow), v.dtype)
        ii = idx.reshape(n, c, h * w).astype(jnp.int32)
        # duplicate indices (stride < kernel) all carry the SAME source
        # value (the element that is max of several windows), so
        # scatter-SET is deterministic and matches the reference; add
        # would multiply-count it
        flat = jax.vmap(jax.vmap(
            lambda f_, i_, s_: f_.at[i_].set(s_)))(flat, ii,
                                                   v.reshape(n, c, h * w))
        return flat.reshape(n, c, oh, ow)
    return apply_op(f, _t(x), _t(indices))


def bilinear(x1, x2, weight, bias=None, name=None):
    """ref: F.bilinear — out[k] = x1 @ W[k] @ x2 (+ b[k])."""
    args = [_t(x1), _t(x2), _t(weight)]
    if bias is not None:
        args.append(_t(bias))

    def f(a, b, w, *bb):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        return out + bb[0] if bb else out
    return apply_op(f, *args)


def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    """ref: F.fractional_max_pool2d — functional mirror of
    nn.FractionalMaxPool2D (stateless; draws boundaries per call)."""
    from .layers_extra import FractionalMaxPool2D
    layer = FractionalMaxPool2D(output_size, kernel_size=kernel_size,
                                random_u=random_u, return_mask=return_mask)
    return layer(x)


def feature_alpha_dropout(x, p=0.5, training=True, name=None):
    """ref: F.feature_alpha_dropout — channel-wise alpha dropout."""
    from .layers_extra import FeatureAlphaDropout
    layer = FeatureAlphaDropout(p)
    layer.train() if training else layer.eval()
    return layer(x)


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """ref: F.npair_loss (Sohn 2016): softmax CE over anchor-positive
    similarities + l2 on the embeddings."""
    def f(a, p, y):
        sim = a @ p.T                                # [B, B]
        y = y.reshape(-1)
        same = (y[:, None] == y[None, :]).astype(sim.dtype)
        tgt = same / jnp.sum(same, -1, keepdims=True)
        logp = jax.nn.log_softmax(sim, axis=-1)
        ce = -jnp.mean(jnp.sum(tgt * logp, -1))
        # reference scaling: l2loss = (mean||a||^2 + mean||p||^2) * 0.25
        reg = l2_reg * 0.25 * (jnp.mean(jnp.sum(a * a, -1))
                               + jnp.mean(jnp.sum(p * p, -1)))
        return ce + reg
    return apply_op(f, _t(anchor), _t(positive), _t(labels))


__all__ += ["bilinear", "fractional_max_pool2d", "feature_alpha_dropout",
            "npair_loss"]
