"""Weight initializers (ref: python/paddle/nn/initializer/*).

Initializers are callables (shape, dtype) -> jax.Array drawing from the
global generator, so `paddle_tpu.seed(n)` makes init deterministic exactly
like the reference's global seed.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from .. import framework
from ..framework import next_rng_key
from ..tensor import Tensor

__all__ = [
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Orthogonal", "Dirac", "calculate_gain", "ParamAttr",
]


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, dtype=dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        return self.mean + self.std * jax.random.normal(
            next_rng_key(), shape, dtype=jnp.float32).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        z = jax.random.truncated_normal(
            next_rng_key(), self.a, self.b, shape, dtype=jnp.float32)
        return (self.mean + self.std * z).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        return jax.random.uniform(next_rng_key(), shape, dtype=jnp.float32,
                                  minval=self.low, maxval=self.high).astype(dtype)


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        # Linear weight is [in, out] in the reference layout
        return shape[0], shape[1]
    # conv: [out_c, in_c, *k]
    rf = int(np.prod(shape[2:]))
    return shape[1] * rf, shape[0] * rf


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return std * jax.random.normal(next_rng_key(), shape,
                                       dtype=jnp.float32).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(next_rng_key(), shape, dtype=jnp.float32,
                                  minval=-limit, maxval=limit).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return std * jax.random.normal(next_rng_key(), shape,
                                       dtype=jnp.float32).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(next_rng_key(), shape, dtype=jnp.float32,
                                  minval=-limit, maxval=limit).astype(dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        arr = self.value._value if isinstance(self.value, Tensor) \
            else jnp.asarray(np.asarray(self.value))
        return arr.reshape(shape).astype(dtype)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        flat = jax.random.normal(next_rng_key(), (max(rows, cols), min(rows, cols)),
                                 dtype=jnp.float32)
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(dtype)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype):
        out = np.zeros(shape, dtype=np.float32)
        oc, ic = shape[0], shape[1]
        mins = min(oc // self.groups, ic)
        centers = tuple(s // 2 for s in shape[2:])
        for g in range(self.groups):
            for i in range(mins):
                out[(g * (oc // self.groups) + i, i) + centers] = 1.0
        return jnp.asarray(out).astype(dtype)


def calculate_gain(nonlinearity, param=None):
    if nonlinearity in ("sigmoid", "linear", "conv1d", "conv2d", "conv3d"):
        return 1.0
    if nonlinearity == "tanh":
        return 5.0 / 3.0
    if nonlinearity == "relu":
        return math.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a ** 2))
    if nonlinearity == "selu":
        return 3.0 / 4.0
    raise ValueError(f"unknown nonlinearity {nonlinearity}")


class ParamAttr:
    """ref: paddle.ParamAttr."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=False,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip


def _resolve_attr(attr, default_initializer, is_bias=False):
    """Resolve (initializer, name, trainable) from a ParamAttr / bool / str."""
    if attr is False:
        raise ValueError("attr=False means no parameter; caller must handle")
    init, name, trainable = None, None, True
    if isinstance(attr, ParamAttr):
        init = attr.initializer
        name = attr.name
        trainable = attr.trainable
    elif isinstance(attr, str):
        name = attr
    elif isinstance(attr, Initializer):
        init = attr
    if init is None:
        # reference precedence: explicit initializer > GLOBAL initializer
        # (fires for bare attrs and ParamAttr(name=...) alike) > layer
        # default > built-in default
        init = _GLOBAL_INIT["bias" if is_bias else "weight"]
    if init is None:
        init = default_initializer
    if init is None:
        init = Constant(0.0) if is_bias else XavierUniform()
    return init, name, trainable


_GLOBAL_INIT = {"weight": None, "bias": None}


def set_global_initializer(weight_init, bias_init=None):
    """ref: paddle.nn.initializer.set_global_initializer — default
    initializers used by create_parameter when no attr is given. Pass
    (None, None) to restore the built-in defaults."""
    _GLOBAL_INIT["weight"] = weight_init
    _GLOBAL_INIT["bias"] = bias_init
