"""`nn.Layer` module system (ref: python/paddle/nn/layer/layers.py).

The reference Layer is an eager module over the C++ autograd; here Layer is a
*dual-mode* module:

- eager: `layer(x)` runs jnp ops immediately, parameters are `Parameter`
  tensors, the eager tape records for `loss.backward()`.
- functional (the perf path): `functional_call(layer, state, *args, rng=...)`
  temporarily swaps the layer's parameters/buffers for the entries of a state
  pytree and runs forward. Because jit traces once, this gives a *pure*
  function of (state, inputs, rng) that XLA compiles — the moral equivalent
  of the reference's @to_static program construction, without an AST pass.
"""
from __future__ import annotations

import contextlib
from collections import OrderedDict
from typing import Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import framework
from ..tensor import Tensor


class Parameter(Tensor):
    """Trainable tensor (ref: paddle.base.framework.EagerParamBase).

    `sharding_spec` carries an optional jax PartitionSpec placement
    (ref: the reference's DistAttr on a dist tensor) consumed by
    `paddle_tpu.distributed.shard_model`.
    """
    __slots__ = ("trainable", "optimize_attr", "regularizer", "need_clip",
                 "sharding_spec")

    def __init__(self, value, trainable=True, name=None):
        super().__init__(value, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True
        self.sharding_spec = None

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


jax.tree_util.register_pytree_node(
    Parameter,
    lambda p: ((p._value,), (p.trainable,)),
    lambda aux, c: Parameter(c[0], trainable=aux[0]),
)

_name_counters = {}


def _unique_name(prefix):
    n = _name_counters.get(prefix, 0)
    _name_counters[prefix] = n + 1
    return f"{prefix}_{n}"


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        object.__setattr__(self, "_parameters", OrderedDict())
        object.__setattr__(self, "_buffers", OrderedDict())
        object.__setattr__(self, "_non_persistable_buffer_names", set())
        object.__setattr__(self, "_sub_layers", OrderedDict())
        object.__setattr__(self, "_forward_pre_hooks", OrderedDict())
        object.__setattr__(self, "_forward_post_hooks", OrderedDict())
        self.training = True
        self._dtype = framework.convert_dtype(dtype)
        self._name = _unique_name(name_scope or type(self).__name__.lower())

    # -- registration -------------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            layers[name] = value
            self.__dict__.pop(name, None)
        elif params is not None and name in params:
            if value is None:
                del params[name]
            else:
                params[name] = value
        elif layers is not None and name in layers:
            if value is None:
                del layers[name]
            else:
                layers[name] = value
        elif buffers is not None and name in buffers:
            if value is None:
                del buffers[name]
                object.__setattr__(self, name, None)
            else:
                buffers[name] = value if isinstance(value, Tensor) else Tensor(value)
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def add_parameter(self, name, parameter):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor if (isinstance(tensor, Tensor) or tensor is None) \
            else Tensor(tensor)
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        """ref: Layer.create_parameter — initializer from ParamAttr or the
        layer default (Xavier-uniform weights / zeros bias like the
        reference's defaults for most layers)."""
        from .initializer import Constant, XavierUniform, _resolve_attr
        dtype = framework.convert_dtype(dtype) or self._dtype
        init, name, trainable = _resolve_attr(attr, default_initializer,
                                              is_bias=is_bias)
        arr = init(tuple(int(s) for s in shape), dtype)
        return Parameter(arr, trainable=trainable, name=name)

    # -- traversal ----------------------------------------------------------
    def named_sublayers(self, prefix="", include_self=False) \
            -> Iterator[Tuple[str, "Layer"]]:
        if include_self:
            yield prefix, self
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            p = f"{prefix}.{name}" if prefix else name
            yield from sub.named_sublayers(prefix=p, include_self=True)

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self):
        return list(self._sub_layers.values())

    def named_children(self):
        return list(self._sub_layers.items())

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for lp, layer in self.named_sublayers(prefix=prefix, include_self=True):
            if not include_sublayers and layer is not self:
                continue
            for name, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                full = f"{lp}.{name}" if lp else name
                if p.name is None:
                    # structured path doubles as the reference's param name
                    # (used by apply_decay_param_fun / optimizer state keys)
                    p.name = full
                yield full, p

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for lp, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for name, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{lp}.{name}" if lp else name), b

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers()]

    # -- state dict ---------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        out = destination if destination is not None else OrderedDict()
        for n, p in self.named_parameters(prefix=structured_name_prefix):
            out[n] = p
        for lp, layer in self.named_sublayers(
                prefix=structured_name_prefix, include_self=True):
            for name, b in layer._buffers.items():
                if b is None or name in layer._non_persistable_buffer_names:
                    continue
                out[f"{lp}.{name}" if lp else name] = b
        return out

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k not in own:
                unexpected.append(k)
                continue
            arr = v._value if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
            tgt = own[k]
            if tuple(arr.shape) != tuple(tgt._value.shape):
                raise ValueError(
                    f"shape mismatch for {k}: {arr.shape} vs {tgt._value.shape}")
            tgt._value = arr.astype(tgt._value.dtype)
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    load_dict = set_state_dict
    set_dict = set_state_dict

    # -- mode / dtype -------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    def apply(self, fn):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dt = framework.convert_dtype(dtype)
            for p in self.parameters():
                if jnp.issubdtype(p._value.dtype, jnp.floating):
                    p._value = p._value.astype(dt)
            for b in self.buffers():
                if jnp.issubdtype(b._value.dtype, jnp.floating):
                    b._value = b._value.astype(dt)
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    # -- hooks --------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        h = _HookRemoveHelper(self._forward_pre_hooks, hook)
        return h

    def register_forward_post_hook(self, hook):
        h = _HookRemoveHelper(self._forward_post_hooks, hook)
        return h

    # -- call ---------------------------------------------------------------
    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks.values():
            r = hook(self, args)
            if r is not None:
                args = r if isinstance(r, tuple) else (r,)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_post_hooks.values():
            r = hook(self, args, out)
            if r is not None:
                out = r
        return out

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            sub_repr = "\n  ".join(sub_repr)
            lines.append(f"({name}): {sub_repr}")
        body = ",\n  ".join(([extra] if extra else []) + lines)
        if body:
            return f"{type(self).__name__}(\n  {body}\n)" if lines else \
                f"{type(self).__name__}({extra})"
        return f"{type(self).__name__}()"

    # -- functional state access (TPU perf path) ---------------------------
    def raw_state(self):
        """(params, buffers) as flat name->jax.Array dicts."""
        params = {n: p._value for n, p in self.named_parameters()}
        buffers = {}
        for lp, layer in self.named_sublayers(include_self=True):
            for name, b in layer._buffers.items():
                if b is None:
                    continue
                buffers[f"{lp}.{name}" if lp else name] = b._value
        return params, buffers

    def load_raw_state(self, params=None, buffers=None):
        """Write arrays back into the live Parameter/buffer tensors."""
        if params:
            for n, p in self.named_parameters():
                if n in params:
                    p._value = params[n]
        if buffers:
            idx = {}
            for lp, layer in self.named_sublayers(include_self=True):
                for name, b in layer._buffers.items():
                    if b is not None:
                        idx[f"{lp}.{name}" if lp else name] = b
            for n, v in buffers.items():
                if n in idx:
                    idx[n]._value = v


class _HookRemoveHelper:
    _next_id = 0

    def __init__(self, hooks, hook):
        self._hooks = hooks
        self._id = _HookRemoveHelper._next_id
        _HookRemoveHelper._next_id += 1
        hooks[self._id] = hook

    def remove(self):
        self._hooks.pop(self._id, None)


@contextlib.contextmanager
def _swapped_state(layer: Layer, params=None, buffers=None):
    saved = []
    try:
        if params:
            for n, p in layer.named_parameters():
                if n in params:
                    saved.append((p, p._value))
                    v = params[n]
                    p._value = v._value if isinstance(v, Tensor) else v
        buffer_objs = {}
        if buffers is not None:
            for lp, sub in layer.named_sublayers(include_self=True):
                for name, b in sub._buffers.items():
                    if b is None:
                        continue
                    full = f"{lp}.{name}" if lp else name
                    buffer_objs[full] = b
                    if full in buffers:
                        saved.append((b, b._value))
                        v = buffers[full]
                        b._value = v._value if isinstance(v, Tensor) else v
        yield buffer_objs
    finally:
        for t, old in saved:
            t._value = old


def functional_call(layer: Layer, params, buffers, *args, rng=None,
                    mutable=False, **kwargs):
    """Run `layer` as a pure function of (params, buffers, rng, *args).

    Returns (out, new_buffers) when mutable=True (e.g. BatchNorm running
    stats updated during the traced step) else just out.
    """
    with _swapped_state(layer, params, buffers) as buffer_objs:
        if rng is not None:
            with framework.rng_scope(rng):
                out = layer(*args, **kwargs)
        else:
            out = layer(*args, **kwargs)
        if mutable:
            new_buffers = {n: b._value for n, b in buffer_objs.items()}
            return out, new_buffers
    return out
