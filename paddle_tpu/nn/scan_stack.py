"""Scan-over-layers: a stack of L identical blocks stored as stacked
[L, ...] parameters and applied with ONE lax.scan.

TPU-native rationale: XLA traces/compiles the scan body once, so the
program is O(1 block) instead of O(L) — at gpt3-1.3B (24 layers, remat)
the unrolled HLO was large enough to kill the axon tunnel's
remote-compile RPC (BENCHLOG r4). Storage is stacked from construction
(no in-trace jnp.stack copy: ~5 GB transient at 1.3B). ref parity: the
reference unrolls CUDA blocks under fleet recompute; this is the
XLA-idiom equivalent (cf. flax nn.scan-style public decoders).

Used by GPT (`GPTConfig.scan_layers`), BERT/ERNIE
(`BertConfig.scan_layers`). The block forward contract is
`block(x, *invariants)` -> same-shaped x; blocks must be structurally
identical.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tensor import Tensor
from .layer import Layer, Parameter, functional_call

__all__ = ["ScannedLayerStack", "flat_name", "stack_layer_state",
           "unstack_layer_state"]


def flat_name(dotted):
    """'attn.q_proj.weight' -> 'attn__q_proj__weight' (parameter-store
    keys may not contain dots: named_parameters joins scopes with '.')."""
    return dotted.replace(".", "__")


class ScannedLayerStack(Layer):
    """L structurally-identical blocks as stacked params + one lax.scan.

    `blocks`: freshly-constructed per-layer blocks (their initial values
    are stacked; the first becomes the traced template, its own arrays
    freed). `has_dropout`: draw one rng key at trace level and feed a
    per-layer split through the scan xs — the body traces ONCE, so a
    trace-time counter would reuse a single dropout mask across layers.
    `recompute`: jax.checkpoint around the body (remat-scan: O(1-block)
    activation memory AND program size).
    """

    def __init__(self, blocks, has_dropout=False, recompute=False):
        super().__init__()
        self.num_layers = len(blocks)
        self.has_dropout = has_dropout
        self.recompute = recompute
        template = blocks[0]
        buf_names = [n for n, _ in template.named_buffers()]
        if buf_names:
            # functional_call below feeds an empty buffers dict — a block
            # with registered buffers (BatchNorm-style running stats)
            # would silently run with default values instead of its own
            raise ValueError(
                "ScannedLayerStack blocks may not register buffers "
                f"(found {buf_names}); stack such state as a Parameter "
                "with trainable=False, or keep the model unrolled "
                "(scan_layers=False)")
        self._pnames = [n for n, _ in template.named_parameters()]
        for n in self._pnames:
            refs = [dict(b.named_parameters())[n] for b in blocks]
            p = Parameter(jnp.stack([r._value for r in refs]),
                          trainable=refs[0].trainable)
            spec = getattr(refs[0], "sharding_spec", None)
            if spec is not None:
                from jax.sharding import PartitionSpec
                p.sharding_spec = PartitionSpec(None, *spec)
            self.add_parameter(flat_name(n), p)
        # the template is NOT a sublayer (object.__setattr__ skips
        # registration): its params must not appear in state_dict /
        # parameters(). Values are freed to scalar placeholders — the
        # scan body swaps real slices in before any forward runs.
        for _, p in template.named_parameters():
            p._value = jnp.zeros((), p.dtype)
        object.__setattr__(self, "_template", template)

    def forward(self, x, *invariants):
        from ..autograd import in_jax_trace, is_grad_enabled
        xa = x._value if isinstance(x, Tensor) else x
        traced = in_jax_trace((xa,))
        if not traced and self.training and is_grad_enabled():
            raise RuntimeError(
                "scan_layers=True trains through the jitted Engine/"
                "Model path only (the eager tape cannot see through "
                "lax.scan). Use Engine.train_batch / Model.fit, wrap "
                "the step in paddle_tpu.jit.to_static, or build the "
                "model with scan_layers=False for eager training.")
        if self.has_dropout and self.training:
            from .. import framework
            keys = jax.random.split(framework.next_rng_key(),
                                    self.num_layers)
        else:
            keys = None
        stacked = {n: self._parameters[flat_name(n)]._value
                   for n in self._pnames}
        template = self._template

        def body(carry, per_layer):
            sliced, key = per_layer
            out = functional_call(template, sliced, {}, Tensor(carry),
                                  *invariants, rng=key)
            return (out._value if isinstance(out, Tensor) else out), None

        if self.recompute and self.training and traced:
            body = jax.checkpoint(body)
        y, _ = jax.lax.scan(body, xa, (stacked, keys))
        return Tensor(y, stop_gradient=not is_grad_enabled())


def stack_layer_state(state_dict, num_layers, prefix="h."):
    """Convert per-layer checkpoint keys ('h.3.attn.q_proj.weight') to
    the stacked layout ('h.attn__q_proj__weight' with a [L, ...] leading
    dim). Non-layer (or already-stacked) keys pass through. For loading
    unrolled .pdparams into a scan_layers=True model; inverse:
    unstack_layer_state."""
    import numpy as np
    per_layer, rest = {}, {}
    for k, v in state_dict.items():
        if k.startswith(prefix) and "." in k[len(prefix):]:
            idx, dotted = k[len(prefix):].split(".", 1)
            if idx.isdigit():
                per_layer.setdefault(dotted, {})[int(idx)] = v
                continue
        rest[k] = v
    for dotted, by_idx in per_layer.items():
        missing = set(range(num_layers)) - set(by_idx)
        if missing:
            raise ValueError(f"layer state for '{dotted}' missing "
                             f"indices {sorted(missing)}")
        arrs = [by_idx[i]._value if isinstance(by_idx[i], Tensor)
                else np.asarray(by_idx[i]) for i in range(num_layers)]
        rest[prefix + flat_name(dotted)] = np.stack(arrs)
    return rest


def unstack_layer_state(state_dict, num_layers, prefix="h."):
    """Inverse of stack_layer_state: stacked keys back to per-layer."""
    import numpy as np
    out = {}
    for k, v in state_dict.items():
        if k.startswith(prefix) and "__" in k[len(prefix):]:
            dotted = k[len(prefix):].replace("__", ".")
            arr = v._value if isinstance(v, Tensor) else np.asarray(v)
            if arr.shape[0] != num_layers:
                raise ValueError(
                    f"stacked leaf '{k}' has leading dim {arr.shape[0]}"
                    f" != num_layers {num_layers}")
            for i in range(num_layers):
                out[f"{prefix}{i}.{dotted}"] = arr[i]
        else:
            out[k] = v
    return out
