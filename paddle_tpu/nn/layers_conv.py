"""Conv layers (ref: python/paddle/nn/layer/conv.py).

Weight layouts match the reference: Conv [out_c, in_c/groups, *k],
ConvTranspose [in_c, out_c/groups, *k]. Default initializer matches the
reference conv default (Xavier-uniform over fan computed from the kernel).

TPU-native channels-last mode: ``conv.to_channels_last()`` (or the
module-level :func:`to_channels_last` on a whole tree) re-stores the
kernel HWIO ([*k, in_c/groups, out_c]) and switches the op to the
channel-last data_format, so a network that transposes its input ONCE at
entry runs every conv in the layout the TPU conv units want — no per-op
relayout, no per-step weight transpose. Init parity: the weight is drawn
in the reference OIHW layout first and transposed, so seeded runs match
the NCHW build exactly (modulo layout).
"""
from __future__ import annotations

import numpy as np

from . import functional as F
from .initializer import XavierUniform
from .layer import Layer

_CHANNELS_LAST_FMT = {1: "NLC", 2: "NHWC", 3: "NDHWC"}


def _ntuple(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    return tuple(int(i) for i in v)


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, n, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 transpose=False, output_padding=0):
        super().__init__()
        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = _ntuple(kernel_size, n)
        self._stride = _ntuple(stride, n)
        self._padding = padding
        self._dilation = _ntuple(dilation, n)
        self._groups = groups
        self._data_format = data_format
        self._n = n
        self._transpose = transpose
        self._output_padding = output_padding
        self._padding_mode = padding_mode
        self._weight_format = "OIHW"

        if transpose:
            w_shape = (in_channels, out_channels // groups) + self._kernel_size
        else:
            w_shape = (out_channels, in_channels // groups) + self._kernel_size
        self.weight = self.create_parameter(w_shape, attr=weight_attr,
                                            default_initializer=None if weight_attr
                                            else XavierUniform())
        if bias_attr is False:
            self.bias = None
            self._parameters["bias"] = None
        else:
            self.bias = self.create_parameter((out_channels,), attr=bias_attr,
                                              is_bias=True)

    def to_channels_last(self):
        """Switch to the TPU-native channels-last layout: data_format
        becomes N*C and the weight Parameter is re-stored HWIO
        ([*k, in_c/groups, out_c]) in place. Idempotent; transpose convs
        are not supported (they keep the reference path)."""
        if self._transpose:
            raise ValueError(
                "to_channels_last: transpose convs keep the reference "
                "NCHW path (HWIO kernels are wired for forward convs "
                "only)")
        if self._weight_format != "HWIO":
            import jax.numpy as jnp
            perm = tuple(range(2, 2 + self._n)) + (1, 0)
            self.weight._value = jnp.transpose(self.weight._value, perm)
            self._weight_format = "HWIO"
        self._data_format = _CHANNELS_LAST_FMT[self._n]
        return self

    def extra_repr(self):
        return (f"{self._in_channels}, {self._out_channels}, "
                f"kernel_size={self._kernel_size}, stride={self._stride}")


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format,
                        weight_format=self._weight_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format,
                        weight_format=self._weight_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format,
                        weight_format=self._weight_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation, output_size,
                                  self._data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, 2, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation, output_size,
                                  self._data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation, output_size,
                                  self._data_format)


def to_channels_last(layer):
    """Convert a module tree IN PLACE to the TPU-native channels-last
    layout: forward convs get HWIO kernels + N*C data_format, BatchNorms
    normalize the trailing axis, pooling layers window the middle axes.
    The caller owns the single entry/exit transpose (the point: ONE
    boundary relayout instead of one per op). Returns (layer, n_converted).
    """
    from .layers_norm import _BatchNormBase
    from .layers_pooling import (AdaptiveAvgPool2D, AdaptiveAvgPool3D,
                                 _Pool)
    n = 0
    for _, sub in layer.named_sublayers(include_self=True):
        if isinstance(sub, _ConvNd) and not sub._transpose:
            sub.to_channels_last()
            n += 1
        elif isinstance(sub, _BatchNormBase):
            sub.to_channels_last()
            n += 1
        elif isinstance(sub, _Pool):
            fmt = sub._kw.get("data_format")
            if fmt and not fmt.endswith("C"):
                sub._kw["data_format"] = _CHANNELS_LAST_FMT[
                    len(fmt) - 2]
                n += 1
        elif isinstance(sub, (AdaptiveAvgPool2D, AdaptiveAvgPool3D)):
            if not sub._data_format.endswith("C"):
                sub._data_format = _CHANNELS_LAST_FMT[
                    len(sub._data_format) - 2]
                n += 1
    return layer, n
