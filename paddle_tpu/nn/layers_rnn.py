"""Recurrent layers (ref: python/paddle/nn/layer/rnn.py).

The recurrence runs under jax.lax.scan so the whole sequence compiles to one
fused XLA while-loop instead of a Python loop of kernel launches (the
reference relies on cuDNN RNN kernels for the same reason).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd import apply_op
from ..tensor import Tensor
from . import functional as F
from .initializer import Uniform
from .layer import Layer


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        b = batch_ref.shape[batch_dim_idx]
        from ..tensor_ops.creation import full
        return full([b, self.hidden_size], init_value)


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter((hidden_size, input_size),
                                               weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter((hidden_size, hidden_size),
                                               weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter((hidden_size,), bias_ih_attr,
                                             is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter((hidden_size,), bias_hh_attr,
                                             is_bias=True, default_initializer=init)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def f(x, h, wi, wh, bi, bh):
            out = act(x @ wi.T + bi + h @ wh.T + bh)
            return out
        h = apply_op(f, inputs, states, self.weight_ih, self.weight_hh,
                     self.bias_ih, self.bias_hh)
        return h, h


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 proj_size=0, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter((4 * hidden_size, input_size),
                                               weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter((4 * hidden_size, hidden_size),
                                               weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter((4 * hidden_size,), bias_ih_attr,
                                             is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter((4 * hidden_size,), bias_hh_attr,
                                             is_bias=True, default_initializer=init)

    def forward(self, inputs, states=None):
        if states is None:
            h = self.get_initial_states(inputs)
            c = self.get_initial_states(inputs)
        else:
            h, c = states

        def f(x, hh, cc, wi, wh, bi, bh):
            gates = x @ wi.T + bi + hh @ wh.T + bh
            i, fgt, g, o = jnp.split(gates, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            fgt = jax.nn.sigmoid(fgt)
            g = jnp.tanh(g)
            o = jax.nn.sigmoid(o)
            c_new = fgt * cc + i * g
            h_new = o * jnp.tanh(c_new)
            return h_new, c_new
        h_new, c_new = apply_op(f, inputs, h, c, self.weight_ih,
                                self.weight_hh, self.bias_ih, self.bias_hh)
        return h_new, (h_new, c_new)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter((3 * hidden_size, input_size),
                                               weight_ih_attr, default_initializer=init)
        self.weight_hh = self.create_parameter((3 * hidden_size, hidden_size),
                                               weight_hh_attr, default_initializer=init)
        self.bias_ih = self.create_parameter((3 * hidden_size,), bias_ih_attr,
                                             is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter((3 * hidden_size,), bias_hh_attr,
                                             is_bias=True, default_initializer=init)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def f(x, h, wi, wh, bi, bh):
            gi = x @ wi.T + bi
            gh = h @ wh.T + bh
            i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
            h_r, h_z, h_n = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(i_r + h_r)
            z = jax.nn.sigmoid(i_z + h_z)
            n = jnp.tanh(i_n + r * h_n)
            return (1 - z) * n + z * h
        h = apply_op(f, inputs, states, self.weight_ih, self.weight_hh,
                     self.bias_ih, self.bias_hh)
        return h, h


class _RNNBase(Layer):
    """Multi-layer (bi)directional RNN driven by lax.scan."""

    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirect = direction in ("bidirect", "bidirectional")
        num_dir = 2 if self.bidirect else 1
        self.num_directions = num_dir
        gate_mult = {"LSTM": 4, "GRU": 3, "RNN_TANH": 1, "RNN_RELU": 1}[mode]
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        for layer in range(num_layers):
            for d in range(num_dir):
                in_sz = input_size if layer == 0 else hidden_size * num_dir
                sfx = f"_reverse" if d == 1 else ""
                self.add_parameter(
                    f"weight_ih_l{layer}{sfx}",
                    self.create_parameter((gate_mult * hidden_size, in_sz),
                                          weight_ih_attr, default_initializer=init))
                self.add_parameter(
                    f"weight_hh_l{layer}{sfx}",
                    self.create_parameter((gate_mult * hidden_size, hidden_size),
                                          weight_hh_attr, default_initializer=init))
                self.add_parameter(
                    f"bias_ih_l{layer}{sfx}",
                    self.create_parameter((gate_mult * hidden_size,),
                                          bias_ih_attr, is_bias=True,
                                          default_initializer=init))
                self.add_parameter(
                    f"bias_hh_l{layer}{sfx}",
                    self.create_parameter((gate_mult * hidden_size,),
                                          bias_hh_attr, is_bias=True,
                                          default_initializer=init))

    def _cell_step(self, mode):
        if mode == "LSTM":
            def step(carry, x, wi, wh, bi, bh):
                h, c = carry
                gates = x @ wi.T + bi + h @ wh.T + bh
                i, f, g, o = jnp.split(gates, 4, axis=-1)
                i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
                g = jnp.tanh(g)
                c2 = f * c + i * g
                h2 = o * jnp.tanh(c2)
                return (h2, c2), h2
        elif mode == "GRU":
            def step(carry, x, wi, wh, bi, bh):
                h = carry
                gi = x @ wi.T + bi
                gh = h @ wh.T + bh
                i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
                h_r, h_z, h_n = jnp.split(gh, 3, axis=-1)
                r = jax.nn.sigmoid(i_r + h_r)
                z = jax.nn.sigmoid(i_z + h_z)
                n = jnp.tanh(i_n + r * h_n)
                h2 = (1 - z) * n + z * h
                return h2, h2
        else:
            act = jnp.tanh if mode == "RNN_TANH" else jax.nn.relu

            def step(carry, x, wi, wh, bi, bh):
                h = carry
                h2 = act(x @ wi.T + bi + h @ wh.T + bh)
                return h2, h2
        return step

    def forward(self, inputs, initial_states=None, sequence_length=None):
        mode = self.mode
        nl, nd, hs = self.num_layers, self.num_directions, self.hidden_size
        time_major = self.time_major
        is_lstm = mode == "LSTM"
        step = self._cell_step(mode)

        weights = []
        for layer in range(nl):
            for d in range(nd):
                sfx = "_reverse" if d == 1 else ""
                weights += [getattr(self, f"weight_ih_l{layer}{sfx}"),
                            getattr(self, f"weight_hh_l{layer}{sfx}"),
                            getattr(self, f"bias_ih_l{layer}{sfx}"),
                            getattr(self, f"bias_hh_l{layer}{sfx}")]

        init_args = []
        if initial_states is not None:
            if is_lstm:
                init_args = [initial_states[0], initial_states[1]]
            else:
                init_args = [initial_states]

        def f(x, *flat):
            if initial_states is not None:
                if is_lstm:
                    h0, c0, flat = flat[0], flat[1], flat[2:]
                else:
                    h0, flat = flat[0], flat[1:]
            else:
                h0 = c0 = None
            if not time_major:
                x = jnp.swapaxes(x, 0, 1)  # [T, B, I]
            b = x.shape[1]
            out = x
            last_h, last_c = [], []
            wi_idx = 0
            for layer in range(nl):
                dir_outs = []
                for d in range(nd):
                    wi, wh, bi, bh = flat[wi_idx:wi_idx + 4]
                    wi_idx += 4
                    sl = layer * nd + d
                    if h0 is not None:
                        hh = h0[sl]
                        cc = c0[sl] if is_lstm else None
                    else:
                        hh = jnp.zeros((b, hs), dtype=x.dtype)
                        cc = jnp.zeros((b, hs), dtype=x.dtype) if is_lstm else None
                    carry = (hh, cc) if is_lstm else hh
                    seq = out if d == 0 else jnp.flip(out, axis=0)

                    def scan_fn(c, xt, wi=wi, wh=wh, bi=bi, bh=bh):
                        return step(c, xt, wi, wh, bi, bh)

                    carry, ys = jax.lax.scan(scan_fn, carry, seq)
                    if d == 1:
                        ys = jnp.flip(ys, axis=0)
                    dir_outs.append(ys)
                    if is_lstm:
                        last_h.append(carry[0])
                        last_c.append(carry[1])
                    else:
                        last_h.append(carry)
                out = dir_outs[0] if nd == 1 else jnp.concatenate(dir_outs, axis=-1)
            final_h = jnp.stack(last_h, axis=0)
            if not time_major:
                out = jnp.swapaxes(out, 0, 1)
            if is_lstm:
                return out, final_h, jnp.stack(last_c, axis=0)
            return out, final_h

        outs = apply_op(f, inputs, *init_args, *weights)
        if is_lstm:
            out, h, c = outs
            return out, (h, c)
        out, h = outs
        return out, h


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        mode = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(mode, input_size, hidden_size, num_layers, direction,
                         time_major, dropout, weight_ih_attr, weight_hh_attr,
                         bias_ih_attr, bias_hh_attr)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, proj_size=0, name=None):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)


class RNN(Layer):
    """Wraps a cell into a scan over time (ref: nn.RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        # Python loop (eager clarity); _RNNBase is the compiled path.
        t_axis = 0 if self.time_major else 1
        steps = inputs.shape[t_axis]
        rng = range(steps - 1, -1, -1) if self.is_reverse else range(steps)
        states = initial_states
        outs = []
        from ..tensor_ops.manip import stack
        for ti in rng:
            xt = inputs[ti] if self.time_major else inputs[:, ti]
            out, states = self.cell(xt, states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        return stack(outs, axis=t_axis), states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        s_fw, s_bw = (initial_states if initial_states is not None
                      else (None, None))
        out_fw, st_fw = self.rnn_fw(inputs, s_fw)
        out_bw, st_bw = self.rnn_bw(inputs, s_bw)
        from ..tensor_ops.manip import concat
        return concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)
