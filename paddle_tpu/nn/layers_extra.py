"""Long-tail nn layers (ref: python/paddle/nn/layer/{loss,common,
activation,pooling}.py) — the remaining reference names probed absent in
the round-2 API sweep. All closed-form jnp; functional mirrors live in
nn/functional.py.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..autograd import apply_op
from ..framework import next_rng_key
from ..tensor import Tensor, to_tensor
from . import functional as F
from .layer import Layer

__all__ = [
    "GaussianNLLLoss", "MultiLabelSoftMarginLoss", "SoftMarginLoss",
    "MultiMarginLoss", "TripletMarginWithDistanceLoss", "Bilinear",
    "Softmax2D", "LogSigmoid", "FeatureAlphaDropout",
    "FractionalMaxPool2D", "AdaptiveLogSoftmaxWithLoss",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


class GaussianNLLLoss(Layer):
    """ref: nn.GaussianNLLLoss(full, epsilon, reduction)."""

    def __init__(self, full=False, epsilon=1e-6, reduction="mean",
                 name=None):
        super().__init__()
        self.full, self.epsilon, self.reduction = full, epsilon, reduction

    def forward(self, input, label, variance):
        return F.gaussian_nll_loss(input, label, variance, self.full,
                                   self.epsilon, self.reduction)


class MultiLabelSoftMarginLoss(Layer):
    """ref: nn.MultiLabelSoftMarginLoss(weight, reduction)."""

    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(input, label, self.weight,
                                              self.reduction)


class SoftMarginLoss(Layer):
    """ref: nn.SoftMarginLoss(reduction)."""

    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, self.reduction)


class MultiMarginLoss(Layer):
    """ref: nn.MultiMarginLoss(p, margin, weight, reduction)."""

    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self.p, self.margin = p, margin
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.multi_margin_loss(input, label, self.p, self.margin,
                                   self.weight, self.reduction)


class TripletMarginWithDistanceLoss(Layer):
    """ref: nn.TripletMarginWithDistanceLoss(distance_function, margin,
    swap, reduction)."""

    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.distance_function = distance_function
        self.margin, self.swap, self.reduction = margin, swap, reduction

    def forward(self, input, positive, negative):
        return F.triplet_margin_with_distance_loss(
            input, positive, negative, self.distance_function, self.margin,
            self.swap, self.reduction)


class Bilinear(Layer):
    """ref: nn.Bilinear — out[k] = x1 @ W[k] @ x2 + b[k]."""

    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            (out_features, in1_features, in2_features), attr=weight_attr)
        if bias_attr is False:
            self.bias = None
            self._parameters["bias"] = None
        else:
            self.bias = self.create_parameter((1, out_features),
                                              attr=bias_attr, is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class Softmax2D(Layer):
    """ref: nn.Softmax2D — softmax over the channel dim of [N?, C, H, W]."""

    def forward(self, x):
        t = _t(x)
        axis = -3
        return apply_op(lambda a: jax.nn.softmax(a, axis=axis), t)


class LogSigmoid(Layer):
    """ref: nn.LogSigmoid."""

    def forward(self, x):
        return apply_op(jax.nn.log_sigmoid, _t(x))


class FeatureAlphaDropout(Layer):
    """ref: nn.FeatureAlphaDropout — alpha dropout that drops whole
    channels (SELU-preserving statistics)."""

    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        t = _t(x)
        if not self.training or self.p == 0.0:
            return t
        p = self.p
        alpha = -1.7580993408473766  # selu alpha' = -scale*alpha
        a = (1 - p + p * alpha ** 2 * (1 - p)) ** -0.5
        b = -a * p * alpha
        key = next_rng_key()

        def f(v):
            # drop whole feature maps: mask shape [N, C, 1, 1...]
            mshape = v.shape[:2] + (1,) * (v.ndim - 2)
            keep = jax.random.bernoulli(key, 1 - p, mshape)
            return a * jnp.where(keep, v, alpha) + b
        return apply_op(f, t)


class FractionalMaxPool2D(Layer):
    """ref: nn.FractionalMaxPool2D — pseudo-random fractional pooling
    (Graham 2014). TPU-shaped: the row/col boundary sequences are drawn
    once per forward (static shapes), pooling is a gather + max.

    kernel_size=None (default) uses the disjoint fractional windows;
    a given kernel_size places fixed-size (possibly overlapping) windows
    at the fractional start positions, like the reference. return_mask
    adds the flat argmax indices (max_unpool2d-compatible)."""

    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.output_size = (output_size if isinstance(output_size, tuple)
                            else (output_size, output_size))
        self.kernel_size = (None if kernel_size is None else (
            kernel_size if isinstance(kernel_size, tuple)
            else (kernel_size, kernel_size)))
        self.random_u = random_u
        self.return_mask = return_mask

    def _bounds(self, n_in, n_out, u):
        # Graham's pseudo-random sequence a_i = ceil(alpha*(i+u)), then
        # clamped so every window is NON-EMPTY and ends exactly at n_in
        # (the raw sequence can hit n_in early, which would leave the last
        # window(s) empty and poison the max with -inf)
        alpha = n_in / n_out
        import numpy as np
        idx = np.arange(n_out + 1)
        b = np.ceil(alpha * (idx + u)).astype(int)
        b[0] = 0
        b[-1] = n_in
        # forward: strictly increasing; backward: leave >= 1 per window
        for i in range(1, n_out):
            b[i] = max(b[i], b[i - 1] + 1)
        for i in range(n_out - 1, 0, -1):
            b[i] = min(b[i], b[i + 1] - 1)
        return b

    def forward(self, x):
        import numpy as np
        t = _t(x)
        n, c, h, w = [int(s) for s in t.shape]
        oh, ow = self.output_size
        u = (self.random_u if self.random_u is not None
             else float(jax.random.uniform(next_rng_key(), ())))
        rb = self._bounds(h, oh, u)
        cb = self._bounds(w, ow, u)
        if self.kernel_size is not None:
            kh, kw = self.kernel_size
        else:
            kh = int((rb[1:] - rb[:-1]).max())
            kw = int((cb[1:] - cb[:-1]).max())
        # static gather: window i covers rows rb[i] .. rb[i]+kh-1,
        # clipped; with fractional (None) kernels, positions beyond the
        # window's true boundary are masked to -inf
        rpos = rb[:-1, None] + np.arange(kh)[None, :]
        cpos = cb[:-1, None] + np.arange(kw)[None, :]
        ri = np.minimum(rpos, h - 1)
        ci = np.minimum(cpos, w - 1)
        if self.kernel_size is None:
            rmask = rpos < rb[1:, None]
            cmask = cpos < cb[1:, None]
        else:
            rmask = rpos < h
            cmask = cpos < w
        flat_idx = (ri[:, :, None, None] * w
                    + ci[None, None, :, :])    # [oh,kh,ow,kw]

        def f(v):
            g = v[:, :, ri, :][:, :, :, :, ci]  # [N,C,oh,kh,ow,kw]
            m = (rmask[:, :, None, None]
                 & cmask[None, None, :, :])     # [oh,kh,ow,kw]
            neg = jnp.asarray(-jnp.inf, v.dtype)
            g = jnp.where(m[None, None], g, neg)
            g2 = jnp.moveaxis(g, 3, 4).reshape(n, c, oh, ow, kh * kw)
            out = jnp.max(g2, axis=-1)
            if not self.return_mask:
                return out
            am = jnp.argmax(g2, axis=-1)        # [N,C,oh,ow]
            fi = jnp.moveaxis(
                jnp.broadcast_to(flat_idx, (oh, kh, ow, kw)), 1, 2) \
                .reshape(oh, ow, kh * kw)
            mask = jnp.take_along_axis(
                jnp.broadcast_to(fi, (n, c, oh, ow, kh * kw)),
                am[..., None], -1)[..., 0]
            return out, mask
        return apply_op(f, t)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """ref: nn.AdaptiveLogSoftmaxWithLoss — hierarchical softmax with
    frequency-ordered clusters (Grave et al.).

    TPU note: the reference scatters per-cluster; here every cluster head
    is computed densely and combined with masks — static shapes, two
    small matmuls instead of data-dependent gathers.
    """

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, name=None):
        super().__init__()
        cutoffs = list(cutoffs)
        assert cutoffs == sorted(cutoffs) and cutoffs[-1] < n_classes
        self.in_features = in_features
        self.n_classes = n_classes
        self.cutoffs = cutoffs + [n_classes]
        self.div_value = div_value
        self.n_clusters = len(self.cutoffs) - 1
        self.head_size = self.cutoffs[0] + self.n_clusters
        self.head_weight = self.create_parameter(
            (in_features, self.head_size))
        self.head_bias_p = (self.create_parameter(
            (self.head_size,), is_bias=True) if head_bias else None)
        self.tail_weights = []
        for i in range(self.n_clusters):
            osz = self.cutoffs[i + 1] - self.cutoffs[i]
            hsz = max(1, int(in_features // (div_value ** (i + 1))))
            w1 = self.create_parameter((in_features, hsz))
            w2 = self.create_parameter((hsz, osz))
            setattr(self, f"tail_{i}_proj", w1)
            setattr(self, f"tail_{i}_out", w2)
            self.tail_weights.append((f"tail_{i}_proj", f"tail_{i}_out"))

    def _head_logp(self, x):
        h = F.linear(x, self.head_weight, self.head_bias_p)
        return apply_op(lambda a: jax.nn.log_softmax(a, -1), h)

    def log_prob(self, x):
        """Full [B, n_classes] log-probabilities."""
        xl = self._head_logp(x)
        parts = [apply_op(lambda a: a[:, :self.cutoffs[0]], xl)]
        for i in range(self.n_clusters):
            w1 = getattr(self, f"tail_{i}_proj")
            w2 = getattr(self, f"tail_{i}_out")
            tail = F.linear(F.linear(x, w1), w2)
            tail_lp = apply_op(lambda a: jax.nn.log_softmax(a, -1), tail)
            cluster_lp = apply_op(
                lambda a, i=i: a[:, self.cutoffs[0] + i:self.cutoffs[0]
                                 + i + 1], xl)
            parts.append(apply_op(jnp.add, tail_lp, cluster_lp))
        return apply_op(lambda *ps: jnp.concatenate(ps, -1), *parts)

    def forward(self, input, label):
        lp = self.log_prob(input)
        out = apply_op(
            lambda l, y: jnp.take_along_axis(
                l, y.astype(jnp.int32)[:, None], 1)[:, 0],
            lp, _t(label))
        loss = apply_op(lambda o: -jnp.mean(o), out)
        return out, loss

    def predict(self, input):
        return apply_op(lambda a: jnp.argmax(a, -1), self.log_prob(input))
