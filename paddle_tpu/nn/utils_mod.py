"""nn.utils (ref: python/paddle/nn/utils/*)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..autograd import no_grad
from ..tensor import Tensor

__all__ = ["clip_grad_norm_", "clip_grad_value_", "parameters_to_vector",
           "vector_to_parameters", "weight_norm", "remove_weight_norm",
           "spectral_norm"]


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p._grad_value for p in parameters if p._grad_value is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g) ** norm_type) for g in grads])) ** (1.0 / norm_type)
    if error_if_nonfinite and not bool(jnp.isfinite(total)):
        raise RuntimeError("grad norm is non-finite")
    coef = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in parameters:
        if p._grad_value is not None:
            p._grad_value = p._grad_value * coef
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p._grad_value is not None:
            p._grad_value = jnp.clip(p._grad_value, -clip_value, clip_value)


def parameters_to_vector(parameters, name=None):
    return Tensor(jnp.concatenate([p._value.reshape(-1) for p in parameters]))


def vector_to_parameters(vec, parameters, name=None):
    off = 0
    v = vec._value if isinstance(vec, Tensor) else jnp.asarray(vec)
    for p in parameters:
        n = int(np.prod(p._value.shape)) if p._value.shape else 1
        p._value = v[off:off + n].reshape(p._value.shape).astype(p._value.dtype)
        off += n


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize weight = g * v / ||v|| via a forward-pre hook."""
    from .layer import Parameter
    w = getattr(layer, name)
    arr = w._value
    if dim is None:
        norm = jnp.linalg.norm(arr)
    else:
        axes = tuple(i for i in range(arr.ndim) if i != dim)
        norm = jnp.sqrt(jnp.sum(jnp.square(arr), axis=axes, keepdims=True))
    g = Parameter(norm.reshape([arr.shape[dim] if dim is not None else 1]))
    v = Parameter(arr)
    del layer._parameters[name]
    layer.add_parameter(name + "_g", g)
    layer.add_parameter(name + "_v", v)

    def hook(lyr, inputs):
        vv = getattr(lyr, name + "_v")
        gg = getattr(lyr, name + "_g")
        if dim is None:
            w_new = vv * (gg / jnp.linalg.norm(vv._value))
        else:
            axes2 = tuple(i for i in range(vv._value.ndim) if i != dim)
            from ..autograd import apply_op
            def f(vv_a, gg_a):
                n = jnp.sqrt(jnp.sum(jnp.square(vv_a), axis=axes2, keepdims=True))
                shape = [1] * vv_a.ndim
                shape[dim] = vv_a.shape[dim]
                return vv_a / n * gg_a.reshape(shape)
            w_new = apply_op(f, vv, gg)
        object.__setattr__(lyr, "_wn_cache", w_new)
        lyr._parameters.pop(name, None)
        lyr.__dict__[name] = w_new
        return None

    h = layer.register_forward_pre_hook(hook)
    layer._weight_norm_hook = h
    layer._weight_norm_name = name
    return layer


def remove_weight_norm(layer, name="weight"):
    from .layer import Parameter
    hook = getattr(layer, "_weight_norm_hook", None)
    if hook is not None:
        hook.remove()
    g = getattr(layer, name + "_g")
    v = getattr(layer, name + "_v")
    dim_guess = 0
    axes = tuple(i for i in range(v._value.ndim) if i != dim_guess)
    n = jnp.sqrt(jnp.sum(jnp.square(v._value), axis=axes, keepdims=True))
    shape = [1] * v._value.ndim
    shape[dim_guess] = v._value.shape[dim_guess]
    w = v._value / n * g._value.reshape(shape)
    del layer._parameters[name + "_g"]
    del layer._parameters[name + "_v"]
    layer.__dict__.pop(name, None)
    layer.add_parameter(name, Parameter(w))
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    from .layers_norm import SpectralNorm
    w = getattr(layer, name)
    if dim is None:
        dim = 0
    sn = SpectralNorm(tuple(w.shape), dim=dim, power_iters=n_power_iterations,
                      eps=eps)
    layer.add_sublayer(name + "_sn", sn)
    orig = layer._parameters[name]
    del layer._parameters[name]
    layer.add_parameter(name + "_orig", orig)

    def hook(lyr, inputs):
        w_new = sn(getattr(lyr, name + "_orig"))
        lyr.__dict__[name] = w_new
        return None

    layer.register_forward_pre_hook(hook)
    return layer
