"""paddle.nn.quant parity — weight-only quantization for serving.

ref: python/paddle/nn/quant/quantized_linear.py (`weight_quantize`,
`weight_dequantize`, `weight_only_linear`, `llm_int8_linear`) — the
reference's LLM-serving path where weights sit in HBM as int8/int4 and
are dequantized on the fly inside the matmul kernel.

TPU-native design: HBM bandwidth is the decode bottleneck, so halving /
quartering weight bytes is the whole win. Weights are quantized
per-output-channel (absmax), stored int8 — or int4 PACKED two nibbles
per int8 byte (jnp has no int4 storage; the unpack is two shifts that
XLA fuses into the consumer matmul's prologue). weight_only_linear runs
the matmul in the activation dtype (bf16 MXU) after an in-kernel
dequant multiply; llm_int8_linear runs a true dynamic int8xint8 MXU
matmul with outlier decomposition (LLM.int8()); for CALIBRATED static
activation scales see quantization.Int8InferLinear.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tensor import Tensor
from .layer import Layer

__all__ = ["weight_quantize", "weight_dequantize", "weight_only_linear",
           "WeightOnlyLinear", "llm_int8_linear", "quantize_for_serving"]


def _arr(x):
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


def weight_quantize(x, algo="weight_only_int8"):
    """[K, N] float -> (quantized weight, per-channel scale [N]).
    algo: 'weight_only_int8' (int8 storage) or 'weight_only_int4'
    (two nibbles packed per int8 byte, K must be even).
    ref: paddle.nn.quant.weight_quantize."""
    w = _arr(x).astype(jnp.float32)
    amax = jnp.max(jnp.abs(w), axis=0)                    # [N]
    if algo == "weight_only_int8":
        scale = jnp.where(amax == 0, 1.0, amax / 127.0)
        q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
        return Tensor(q), Tensor(scale)
    if algo == "weight_only_int4":
        k = w.shape[0]
        if k % 2:
            raise ValueError(f"int4 packing needs even K, got {k}")
        scale = jnp.where(amax == 0, 1.0, amax / 7.0)
        q = jnp.clip(jnp.round(w / scale), -7, 7).astype(jnp.int8)
        # pack rows pairwise: byte = (hi << 4) | (lo & 0xF)
        lo = q[0::2] & 0xF
        hi = (q[1::2] & 0xF) << 4
        return Tensor((lo | hi).astype(jnp.int8)), Tensor(scale)
    raise ValueError(f"unknown algo {algo!r}")


def _unpack_int4(packed):
    """[K/2, N] packed bytes -> [K, N] int8 in [-7, 7] (sign-extended
    nibbles; two shifts — XLA fuses this into the consumer)."""
    b = packed.astype(jnp.int8)
    lo = jnp.left_shift(b, 4)
    lo = jnp.right_shift(lo, 4)              # arithmetic: sign-extends
    hi = jnp.right_shift(b, 4)
    k2, n = b.shape
    out = jnp.stack([lo, hi], axis=1)        # [K/2, 2, N]
    return out.reshape(2 * k2, n)


def weight_dequantize(x, scale, algo="weight_only_int8"):
    """Inverse of weight_quantize -> float32 [K, N]."""
    q = _arr(x)
    s = _arr(scale)
    if algo == "weight_only_int4":
        q = _unpack_int4(q)
    return Tensor(q.astype(jnp.float32) * s[None, :])


def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8"):
    """y = x @ dequant(weight) + bias, with the weight stored int8/int4.
    ref: paddle.nn.quant.weight_only_linear. The dequant multiply fuses
    into the matmul prologue under XLA; weight bytes in HBM are 2x/4x
    smaller — the lever that matters for bandwidth-bound decode."""
    from ..autograd import apply_op
    algo = ("weight_only_int4" if str(weight_dtype) in ("int4", "4")
            else "weight_only_int8")
    wq = _arr(weight)
    ws = _arr(weight_scale)

    def f(a):
        q = _unpack_int4(wq) if algo == "weight_only_int4" else wq
        w = (q.astype(a.dtype) * ws[None, :].astype(a.dtype))
        y = a @ w
        if bias is not None:
            y = y + _arr(bias).astype(y.dtype)
        return y

    return apply_op(f, x if isinstance(x, Tensor) else Tensor(_arr(x)))


def llm_int8_linear(x, weight, bias=None, weight_scale=None,
                    threshold=6.0):
    """ref: paddle.nn.quant.llm_int8_linear — the REAL LLM.int8()
    scheme (Dettmers et al.): dynamic per-row int8 quantization of the
    activations, int8 x int8 -> int32 matmul (MXU-native via
    dot_general preferred_element_type), and outlier feature
    decomposition — input features whose batch absmax exceeds
    `threshold` bypass quantization and run at full precision, which is
    what keeps transformer activations (systematic outlier channels)
    accurate under int8.

    TPU-native divergence from the CUDA kernel: outlier columns are
    handled by MASKING (zeroed in the int8 path, zeroed-complement in
    the fp path) instead of gathering a data-dependent column subset —
    shapes stay static under jit, which XLA requires; the fp outlier
    matmul is therefore full-width and runs in the activation dtype.
    Precision semantics match the paper; the compute saving of the
    gathered form does not apply on TPU, where the win is the int8 MXU
    path + halved weight HBM. Gradients are straight-through (the
    dequant-matmul jacobian, like the STE fake-quant pattern in
    paddle_tpu.quantization): quantization round/cast ops would
    otherwise silently zero the tangent."""
    from ..autograd import apply_op
    wq = _arr(weight)                       # int8 [K, N]
    ws = _arr(weight_scale)                 # [N]
    b = None if bias is None else _arr(bias)

    def f(a):
        y = _llm_int8_mm(a, wq, ws, float(threshold))
        if b is not None:
            y = y + b.astype(y.dtype)
        return y

    return apply_op(f, x if isinstance(x, Tensor) else Tensor(_arr(x)))


def _llm_int8_impl(af, wq, ws, threshold):
    dt = af.dtype
    a32 = af.astype(jnp.float32)
    # outlier feature columns: batch absmax over all leading dims
    amax = jnp.max(jnp.abs(a32), axis=tuple(range(a32.ndim - 1)))
    outlier = amax > jnp.float32(threshold)              # [K]
    a_reg = jnp.where(outlier, 0.0, a32)
    # vector-wise (per-row) activation quantization
    row_s = jnp.max(jnp.abs(a_reg), axis=-1, keepdims=True) / 127.0
    row_s = jnp.maximum(row_s, jnp.float32(1e-8))
    aq = jnp.clip(jnp.round(a_reg / row_s), -127, 127).astype(jnp.int8)
    acc = jax.lax.dot_general(
        aq, wq, (((aq.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)                # int32 [..., N]
    # bare `ws` (not ws[None, :]) so a 1-D input keeps its rank
    y = (acc.astype(jnp.float32) * row_s * ws.astype(jnp.float32)
         ).astype(dt)
    # outlier features at full precision, in the ACTIVATION dtype (bf16
    # inputs keep the MXU fast path for this full-width matmul)
    a_out = jnp.where(outlier, af, jnp.zeros((), dt))
    w_f = wq.astype(dt) * ws[None, :].astype(dt)
    return y + a_out @ w_f


import functools  # noqa: E402


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _llm_int8_mm(af, wq, ws, threshold):
    return _llm_int8_impl(af, wq, ws, threshold)


def _llm_int8_mm_fwd(af, wq, ws, threshold):
    return _llm_int8_impl(af, wq, ws, threshold), (wq, ws)


def _llm_int8_mm_bwd(threshold, res, g):
    wq, ws = res
    dt = g.dtype                   # output dtype == activation dtype
    # straight-through: jacobian of the dequantized matmul; frozen int8
    # weight storage gets a zero cotangent (serving weights don't train)
    w_f = wq.astype(dt) * ws[None, :].astype(dt)
    ga = g @ w_f.T
    import numpy as _np
    from jax import dtypes as _dtypes
    gwq = _np.zeros(wq.shape, _dtypes.float0) if not \
        jnp.issubdtype(wq.dtype, jnp.floating) else jnp.zeros_like(wq)
    return ga, gwq, jnp.zeros_like(ws)


_llm_int8_mm.defvjp(_llm_int8_mm_fwd, _llm_int8_mm_bwd)


class WeightOnlyLinear(Layer):
    """Serving Linear with int8/int4 weight storage (module form of
    weight_only_linear; build from a trained Linear via from_linear)."""

    def __init__(self, in_features, out_features, weight_dtype="int8",
                 bias=True):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight_dtype = str(weight_dtype)
        rows = in_features // 2 if self.weight_dtype == "int4" \
            else in_features
        self.register_buffer("qweight",
                             Tensor(jnp.zeros((rows, out_features),
                                              jnp.int8)))
        self.register_buffer("weight_scale",
                             Tensor(jnp.ones((out_features,), jnp.float32)))
        if bias:
            self.register_buffer("bias",
                                 Tensor(jnp.zeros((out_features,),
                                                  jnp.float32)))
        else:
            self.bias = None

    @classmethod
    def from_linear(cls, linear, weight_dtype="int8"):
        w = linear.weight
        k, n = w.shape
        m = cls(k, n, weight_dtype=weight_dtype,
                bias=linear.bias is not None)
        algo = ("weight_only_int4" if str(weight_dtype) == "int4"
                else "weight_only_int8")
        q, s = weight_quantize(w, algo)
        m.qweight.set_value(q._value)
        m.weight_scale.set_value(s._value)
        if linear.bias is not None:
            m.bias.set_value(_arr(linear.bias))
        return m

    def forward(self, x):
        return weight_only_linear(x, self.qweight, self.bias,
                                  self.weight_scale, self.weight_dtype)


def quantize_for_serving(model, weight_dtype="int8", min_features=1):
    """In-place walk: swap every Linear-shaped sublayer for a
    WeightOnlyLinear holding int8/int4 weights. Returns the number of
    layers converted. ref: the reference's weight-only serving convert
    (paddle.nn.quant + PaddleNLP's quant_weights pass).

    Tensor-parallel Column/RowParallelLinear are eligible ONLY when no
    mp mesh axis is live (single-chip serving): their forward then
    degenerates to plain x @ W + b, which WeightOnlyLinear reproduces.
    With a bound mp axis the walk refuses rather than silently dropping
    the collective semantics."""
    from .layers_common import Linear

    eligible = [Linear]
    parallel_types = ()
    try:
        from ..distributed.fleet.mpu import (ColumnParallelLinear,
                                             RowParallelLinear, axis_bound)
        parallel_types = (ColumnParallelLinear, RowParallelLinear)
        eligible.extend(parallel_types)
    except ImportError:  # pragma: no cover
        axis_bound = lambda _axis: False  # noqa: E731
    eligible = tuple(eligible)

    count = 0

    def walk(layer):
        nonlocal count
        for name, sub in list(layer._sub_layers.items()):
            if sub is None:
                continue
            if type(sub) in (WeightOnlyLinear,):
                continue
            if isinstance(sub, parallel_types) \
                    and axis_bound(getattr(sub, "mp_axis", "mp")):
                raise ValueError(
                    f"cannot weight-only-quantize {type(sub).__name__} "
                    f"'{name}' while its mp mesh axis is live — quantize "
                    "before sharding, or serve single-chip")
            if isinstance(sub, eligible) and \
                    sub.weight.shape[0] >= min_features:
                if str(weight_dtype) == "int4" and sub.weight.shape[0] % 2:
                    walk(sub)
                    continue  # odd K can't pack; leave at full precision
                layer._sub_layers[name] = WeightOnlyLinear.from_linear(
                    sub, weight_dtype=weight_dtype)
                count += 1
            else:
                walk(sub)
    walk(model)
    return count
