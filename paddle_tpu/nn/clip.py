"""Gradient clipping strategies (ref: python/paddle/nn/clip.py).

Used two ways: eagerly over Parameter.grad (API parity) and functionally
over a grad pytree inside the jitted train step (Engine/optimizer path).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..tensor import Tensor


class ClipGradBase:
    def __call__(self, params_grads):
        """Eager form: list[(param, grad_tensor)] -> same with clipped grads."""
        arrs = {i: g._value if isinstance(g, Tensor) else g
                for i, (p, g) in enumerate(params_grads) if g is not None}
        clipped = self.apply(arrs)
        out = []
        for i, (p, g) in enumerate(params_grads):
            if g is None:
                out.append((p, g))
            else:
                out.append((p, Tensor(clipped[i])))
        return out

    def apply(self, grads):
        """Functional form over any pytree of jax arrays."""
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def apply(self, grads):
        return jax.tree_util.tree_map(
            lambda g: jnp.clip(g, self.min, self.max), grads)


class ClipGradByNorm(ClipGradBase):
    """Per-tensor L2 norm clip."""

    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def apply(self, grads):
        def clip(g):
            n = jnp.sqrt(jnp.sum(jnp.square(g)))
            return g * jnp.minimum(self.clip_norm / jnp.maximum(n, 1e-6), 1.0)
        return jax.tree_util.tree_map(clip, grads)


class ClipGradByGlobalNorm(ClipGradBase):
    """Global L2 norm clip (the Fleet default for LM training)."""

    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = float(clip_norm)

    def apply(self, grads):
        leaves = jax.tree_util.tree_leaves(grads)
        if not leaves:
            return grads
        total = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                             for g in leaves))
        coef = jnp.minimum(self.clip_norm / jnp.maximum(total, 1e-6), 1.0)
        return jax.tree_util.tree_map(lambda g: (g * coef).astype(g.dtype), grads)
