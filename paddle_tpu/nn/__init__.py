"""paddle_tpu.nn — layers, functional ops, initializers.

ref: python/paddle/nn/__init__.py exports the same names.
"""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .layer import Layer, Parameter, functional_call  # noqa: F401
from .initializer import ParamAttr  # noqa: F401

from .layers_common import (  # noqa: F401
    AlphaDropout, ChannelShuffle, CosineSimilarity, Dropout, Dropout2D,
    Dropout3D, Embedding, Flatten, Fold, Identity, LayerDict, LayerList,
    Linear, Pad1D, Pad2D, Pad3D, PairwiseDistance, ParameterList,
    PixelShuffle, PixelUnshuffle, Sequential, Unfold, Upsample,
    UpsamplingBilinear2D, UpsamplingNearest2D, ZeroPad2D,
)
from .layers_extra import (  # noqa: F401
    AdaptiveLogSoftmaxWithLoss, Bilinear, FeatureAlphaDropout,
    FractionalMaxPool2D, GaussianNLLLoss, LogSigmoid,
    MultiLabelSoftMarginLoss, MultiMarginLoss, SoftMarginLoss, Softmax2D,
    TripletMarginWithDistanceLoss,
)
from .layers_conv import (  # noqa: F401
    Conv1D, Conv1DTranspose, Conv2D, Conv2DTranspose, Conv3D, Conv3DTranspose,
)
from .layers_norm import (  # noqa: F401
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, GroupNorm,
    InstanceNorm1D, InstanceNorm2D, InstanceNorm3D, LayerNorm,
    LocalResponseNorm, RMSNorm, SpectralNorm, SyncBatchNorm,
)
from .layers_activation import (  # noqa: F401
    CELU, ELU, GELU, GLU, Hardshrink, Hardsigmoid, Hardswish, Hardtanh,
    LeakyReLU, LogSoftmax, Maxout, Mish, PReLU, ReLU, ReLU6, RReLU, SELU,
    Sigmoid, Silu, Softmax, Softplus, Softshrink, Softsign, Swish, Tanh,
    Tanhshrink, ThresholdedReLU,
)
from .layers_pooling import (  # noqa: F401
    AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D,
    AdaptiveMaxPool1D, AdaptiveMaxPool2D, AvgPool1D, AvgPool2D, AvgPool3D,
    LPPool1D, LPPool2D, MaxPool1D, MaxPool2D, MaxPool3D,
)
from .layers_loss import (  # noqa: F401
    BCELoss, BCEWithLogitsLoss, CosineEmbeddingLoss, CrossEntropyLoss,
    CTCLoss, HingeEmbeddingLoss, HuberLoss, KLDivLoss, L1Loss,
    MarginRankingLoss, MSELoss, NLLLoss, PoissonNLLLoss, SmoothL1Loss,
    TripletMarginLoss,
)
from .layers_transformer import (  # noqa: F401
    MultiHeadAttention, Transformer, TransformerDecoder,
    TransformerDecoderLayer, TransformerEncoder, TransformerEncoderLayer,
)
from .layers_rnn import (  # noqa: F401
    BiRNN, GRU, GRUCell, LSTM, LSTMCell, RNN, RNNCellBase, SimpleRNN,
    SimpleRNNCell,
)
from . import utils_mod as utils  # noqa: F401
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
from . import quant  # noqa: E402,F401
