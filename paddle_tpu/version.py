"""paddle.version parity (ref: python/paddle/version/__init__.py)."""
from __future__ import annotations

full_version = "0.2.0"
major = "0"
minor = "2"
patch = "0"
rc = "0"
cuda_version = "False"   # the reference reports a string here
cudnn_version = "False"
tpu = True
commit = "unknown"
with_pip = True


def show():
    print(f"full_version: {full_version}")
    print(f"major: {major}")
    print(f"minor: {minor}")
    print(f"patch: {patch}")
    print(f"tpu: {tpu}")


def cuda():
    return cuda_version


def cudnn():
    return cudnn_version
