"""Numeric debugging (ref: python/paddle/amp/debugging.py).

check_numerics / TensorCheckerConfig: the reference instruments kernels to
trap NaN/Inf per op. TPU-native: jax.debug callbacks can't fire per-kernel
inside one fused XLA program, so the check operates at tensor/pytree
granularity — wrap the values you care about (activations, grads, whole
train-step outputs) and failures raise with the offending path. The
failure-detection hook in SURVEY §2.11 (grad-norm spike detector) also
lives here.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["check_numerics", "TensorCheckerConfig", "enable_tensor_checker",
           "disable_tensor_checker", "GradNormSpikeDetector",
           "DebugMode", "collect_operator_stats"]


class DebugMode:
    CHECK_NAN_INF_AND_ABORT = "abort"
    CHECK_NAN_INF = "warn"
    CHECK_ALL = "all"


@dataclass
class TensorCheckerConfig:
    enable: bool = True
    debug_mode: str = DebugMode.CHECK_NAN_INF_AND_ABORT
    checked_op_list: tuple = ()
    skipped_op_list: tuple = ()


_checker: TensorCheckerConfig | None = None


def enable_tensor_checker(config: TensorCheckerConfig):
    global _checker
    _checker = config


def disable_tensor_checker():
    global _checker
    _checker = None


def tensor_checker_enabled():
    return _checker is not None and _checker.enable


def check_numerics(tensor, op_type="", var_name="", debug_mode=None,
                   stack_height_limit=None):
    """ref: paddle.amp.debugging.check_numerics — raise (abort mode) or
    warn on NaN/Inf anywhere in the pytree. Works on Tensor/jax arrays,
    host-side (call outside jit, or on jitted outputs — XLA has already
    materialised them)."""
    from ..tensor import Tensor

    mode = debug_mode or (
        _checker.debug_mode if _checker else DebugMode.CHECK_NAN_INF_AND_ABORT)
    bad = []

    def visit(path, x):
        if isinstance(x, Tensor):
            x = x._value
        if isinstance(x, (bool, str, bytes)) or x is None:
            return
        if isinstance(x, jax.Array):
            if not jnp.issubdtype(x.dtype, jnp.floating):
                return
            # count on device; ONE two-scalar transfer to host
            counts = np.asarray(jnp.stack([jnp.isnan(x).sum(),
                                           jnp.isinf(x).sum()]))
            n_nan, n_inf = int(counts[0]), int(counts[1])
            shape = x.shape
        else:
            try:
                arr = np.asarray(x)
            except Exception:
                return
            if not np.issubdtype(arr.dtype, np.floating):
                return
            n_nan = int(np.isnan(arr).sum())
            n_inf = int(np.isinf(arr).sum())
            shape = arr.shape
        if n_nan or n_inf:
            bad.append(f"{var_name or path}: {n_nan} NaN, {n_inf} Inf "
                       f"(shape {shape}, op {op_type or '?'})")

    leaves = jax.tree_util.tree_leaves_with_path(
        tensor, is_leaf=lambda t: isinstance(t, Tensor))
    for path, leaf in leaves:
        visit(jax.tree_util.keystr(path), leaf)
    if bad:
        msg = "check_numerics found non-finite values:\n  " + "\n  ".join(bad)
        if mode == DebugMode.CHECK_NAN_INF_AND_ABORT:
            raise FloatingPointError(msg)
        import warnings
        warnings.warn(msg)
    return tensor


class GradNormSpikeDetector:
    """Failure-detection hook (SURVEY §2.11): flags a step whose global
    grad norm exceeds `factor` x the trailing-window median — the classic
    precursor of divergence the reference's fault-tolerance hooks watch."""

    def __init__(self, window=32, factor=10.0):
        self.window = window
        self.factor = factor
        self._history = []

    def global_norm(self, grads):
        from ..tensor import Tensor
        leaves = [g._value if isinstance(g, Tensor) else g
                  for g in jax.tree_util.tree_leaves(
                      grads, is_leaf=lambda t: isinstance(t, Tensor))]
        sq = sum(float(jnp.sum(jnp.square(g.astype(jnp.float32))))
                 for g in leaves if hasattr(g, "dtype"))
        return float(np.sqrt(sq))

    def check(self, grads) -> bool:
        """Returns True (spike!) when the current norm is anomalous; always
        records the observation."""
        norm = self.global_norm(grads)
        spike = False
        warmup = max(2, min(8, self.window))
        if len(self._history) >= warmup:
            med = float(np.median(self._history))
            spike = med > 0 and norm > self.factor * med
        self._history.append(norm)
        self._history = self._history[-self.window:]
        return spike


class _OpStats:
    def __init__(self):
        self.records = []

    def summary(self):
        return list(self.records)


def collect_operator_stats(*a, **kw):
    """ref: paddle.amp.debugging.collect_operator_stats — per-op dtype
    stats. Under XLA ops fuse into one program, so per-op collection is
    meaningless; returns an empty context for API compatibility."""
    import contextlib

    @contextlib.contextmanager
    def cm():
        yield _OpStats()
    return cm()
