"""AMP (ref: python/paddle/amp/*).

TPU-first AMP is bf16: no loss scaling needed, auto_cast simply runs
white-listed ops in bfloat16 (level O1) or casts whole models (O2 via
`decorate`). The fp16 GradScaler semantics (dynamic loss scaling with
inf-skip, growth/backoff) are kept for parity and are implemented
functionally so they can live inside the jitted train step.
"""
from __future__ import annotations

import contextlib
import threading

import jax
import jax.numpy as jnp

from .. import framework
from ..tensor import Tensor

__all__ = ["auto_cast", "autocast", "amp_guard", "GradScaler", "decorate",
           "is_auto_cast_enabled", "get_amp_dtype"]

_state = threading.local()

# ops that are numerically safe in low precision (ref: white/black lists in
# python/paddle/amp/amp_lists.py)
WHITE_LIST = {"matmul", "conv2d", "linear", "einsum", "bmm"}
BLACK_LIST = {"log", "exp", "softmax", "cross_entropy", "mean", "sum",
              "layer_norm", "batch_norm"}


def is_auto_cast_enabled():
    return getattr(_state, "enabled", False)


def get_amp_dtype():
    return getattr(_state, "dtype", "bfloat16")


def get_amp_level():
    return getattr(_state, "level", "O1")


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    prev = (getattr(_state, "enabled", False), getattr(_state, "dtype", None),
            getattr(_state, "level", None))
    _state.enabled = enable
    _state.dtype = dtype
    _state.level = level
    try:
        yield
    finally:
        _state.enabled, _state.dtype, _state.level = prev


autocast = auto_cast
amp_guard = auto_cast


def amp_dtype_of(x):
    return framework.convert_dtype(get_amp_dtype())


def decorate(models, optimizers=None, level="O2", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """ref: paddle.amp.decorate — O2 casts model params to the amp dtype;
    optimizers get multi_precision master weights."""
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        for m in model_list:
            m.to(dtype=dtype)
    if optimizers is not None:
        opt_single = not isinstance(optimizers, (list, tuple))
        opt_list = [optimizers] if opt_single else list(optimizers)
        for o in opt_list:
            o._multi_precision = True
        if opt_single:
            optimizers = opt_list[0]
        ret_models = model_list[0] if single else model_list
        return ret_models, optimizers
    return model_list[0] if single else model_list


class GradScaler:
    """ref: paddle.amp.GradScaler — dynamic loss scaling.

    Eager API: scale()/unscale_()/step()/update() or minimize(). The
    functional core (scaler_state / scaled_step semantics) is used by the
    Engine so the skip-on-inf logic compiles into the train step via
    lax.cond-free arithmetic (weights update is masked by the finite flag).
    """

    def __init__(self, enable=True, init_loss_scaling=65536.0,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good = 0
        self._bad = 0
        self._found_inf = False
        # lifetime observability counters (mirrors
        # criterion.last_mlm_overflow from PR 1): how many steps saw
        # non-finite grads / were skipped. Surfaced in hapi fit() logs
        # when the scaler rides a resilience.TrainGuard, and bumped by
        # the eager unscale_/step paths too.
        self._found_inf_count = 0
        self._skip_count = 0

    @property
    def found_inf_count(self):
        """Steps that observed a non-finite loss/grad (lifetime)."""
        return self._found_inf_count

    @property
    def skip_count(self):
        """Optimizer updates skipped because of non-finite grads."""
        return self._skip_count

    def note_step(self, found_inf):
        """Record one guarded-step outcome (called by TrainGuard; the
        dynamic-scale arithmetic itself runs in-step functionally)."""
        if found_inf:
            self._found_inf_count += 1
            self._skip_count += 1

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        inv = 1.0 / self._scale
        found = False
        for p in optimizer._parameter_list or []:
            if p._grad_value is not None:
                g = p._grad_value * inv
                finite = bool(jnp.all(jnp.isfinite(g)))
                found = found or not finite
                p._grad_value = g
        self._found_inf = found
        # latch so a following step() does NOT unscale again — the
        # explicit unscale_-then-clip-then-step pattern must divide by
        # the scale exactly once
        self._unscaled = True
        if found:
            self._found_inf_count += 1

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        if not getattr(self, "_unscaled", False):
            self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        else:
            self._skip_count += 1
        self._unscaled = False

    def update(self):
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad += 1
            self._good = 0
            if self._bad >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad = 0
        else:
            self._good += 1
            self._bad = 0
            if self._good >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good = 0

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        inv = 1.0 / self._scale if self._enable else 1.0
        for p in optimizer._parameter_list or []:
            if p._grad_value is not None and self._enable:
                p._grad_value = p._grad_value * inv
        self.unscale_guarded_step(optimizer)
        self.update()
        optimizer.clear_grad()

    def unscale_guarded_step(self, optimizer):
        found = False
        for p in optimizer._parameter_list or []:
            if p._grad_value is not None:
                if not bool(jnp.all(jnp.isfinite(p._grad_value))):
                    found = True
                    break
        self._found_inf = found
        if not found:
            optimizer.step()
        else:
            self._found_inf_count += 1
            self._skip_count += 1

    # -- functional core for the jitted path --------------------------------
    @staticmethod
    def functional_init(init_scale=65536.0):
        return {"scale": jnp.float32(init_scale),
                "good": jnp.int32(0), "bad": jnp.int32(0)}

    @staticmethod
    def functional_update(state, found_inf, incr_ratio=2.0, decr_ratio=0.5,
                          incr_every=2000, decr_every=1):
        good = jnp.where(found_inf, 0, state["good"] + 1)
        bad = jnp.where(found_inf, state["bad"] + 1, 0)
        scale = state["scale"]
        scale = jnp.where(bad >= decr_every,
                          jnp.maximum(scale * decr_ratio, 1.0), scale)
        bad = jnp.where(bad >= decr_every, 0, bad)
        scale = jnp.where(good >= incr_every, scale * incr_ratio, scale)
        good = jnp.where(good >= incr_every, 0, good)
        return {"scale": scale, "good": good, "bad": bad}

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio, "good": self._good,
                "bad": self._bad,
                "found_inf_count": self._found_inf_count,
                "skip_count": self._skip_count}

    def load_state_dict(self, state):
        self._scale = state["scale"]
        self._good = state.get("good", 0)
        self._bad = state.get("bad", 0)
        self._found_inf_count = state.get("found_inf_count", 0)
        self._skip_count = state.get("skip_count", 0)


from . import debugging  # noqa: F401,E402


def is_bfloat16_supported(device=None):
    """ref: paddle.amp.is_bfloat16_supported — always true on TPU/XLA."""
    return True


def is_float16_supported(device=None):
    """ref: paddle.amp.is_float16_supported — XLA supports f16 math,
    though bf16 is the native TPU dtype."""
    return True
