"""paddle.fft parity (ref: python/paddle/fft.py).

Thin differentiable wrappers over jnp.fft — XLA lowers these to the TPU
FFT HLO, so they fuse with surrounding ops and run on device. Norm-mode
semantics ('backward' | 'ortho' | 'forward') match the reference.
"""
from __future__ import annotations

import jax.numpy as jnp

from .autograd import apply_op
from .tensor import Tensor, to_tensor

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2", "hfft2", "ihfft2",
    "fftn", "ifftn", "rfftn", "irfftn", "hfftn", "ihfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]

_NORMS = ("backward", "ortho", "forward")


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _norm(norm):
    n = norm or "backward"
    if n not in _NORMS:
        raise ValueError(f"norm must be one of {_NORMS}, got {norm!r}")
    return n


def _wrap1(jfn, x, n, axis, norm):
    return apply_op(lambda a: jfn(a, n=n, axis=axis, norm=_norm(norm)), _t(x))


def _wrapn(jfn, x, s, axes, norm):
    return apply_op(lambda a: jfn(a, s=s, axes=axes, norm=_norm(norm)), _t(x))


# 1-D -----------------------------------------------------------------------
def fft(x, n=None, axis=-1, norm="backward", name=None):
    return _wrap1(jnp.fft.fft, x, n, axis, norm)


def ifft(x, n=None, axis=-1, norm="backward", name=None):
    return _wrap1(jnp.fft.ifft, x, n, axis, norm)


def rfft(x, n=None, axis=-1, norm="backward", name=None):
    return _wrap1(jnp.fft.rfft, x, n, axis, norm)


def irfft(x, n=None, axis=-1, norm="backward", name=None):
    return _wrap1(jnp.fft.irfft, x, n, axis, norm)


def hfft(x, n=None, axis=-1, norm="backward", name=None):
    return _wrap1(jnp.fft.hfft, x, n, axis, norm)


def ihfft(x, n=None, axis=-1, norm="backward", name=None):
    return _wrap1(jnp.fft.ihfft, x, n, axis, norm)


# 2-D -----------------------------------------------------------------------
def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _wrapn(jnp.fft.fft2, x, s, axes, norm)


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _wrapn(jnp.fft.ifft2, x, s, axes, norm)


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _wrapn(jnp.fft.rfft2, x, s, axes, norm)


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _wrapn(jnp.fft.irfft2, x, s, axes, norm)


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _hfftn_impl(x, s, axes, norm)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _ihfftn_impl(x, s, axes, norm)


# N-D -----------------------------------------------------------------------
def fftn(x, s=None, axes=None, norm="backward", name=None):
    return _wrapn(jnp.fft.fftn, x, s, axes, norm)


def ifftn(x, s=None, axes=None, norm="backward", name=None):
    return _wrapn(jnp.fft.ifftn, x, s, axes, norm)


def rfftn(x, s=None, axes=None, norm="backward", name=None):
    return _wrapn(jnp.fft.rfftn, x, s, axes, norm)


def irfftn(x, s=None, axes=None, norm="backward", name=None):
    return _wrapn(jnp.fft.irfftn, x, s, axes, norm)


def _hfftn_impl(x, s, axes, norm):
    """hermitian-input N-D (jnp has no hfftn): forward fft over the leading
    axes + hfft on the last — matches scipy.fft.hfftn."""
    def f(a):
        # scipy convention: axes default to the last len(s) axes when s is
        # given, else all axes
        ax = (tuple(axes) if axes is not None
              else tuple(range(0 if s is None else a.ndim - len(s),
                               a.ndim)))
        lead, last = tuple(ax[:-1]), ax[-1]
        if lead:
            s_lead = None if s is None else tuple(s[:-1])
            a = jnp.fft.fftn(a, s=s_lead, axes=lead, norm=_norm(norm))
        n_last = None if s is None else s[-1]
        return jnp.fft.hfft(a, n=n_last, axis=last, norm=_norm(norm))
    return apply_op(f, _t(x))


def _ihfftn_impl(x, s, axes, norm):
    """inverse of hfftn: ihfft on the last axis + ifftn over the leading
    axes — matches scipy.fft.ihfftn."""
    def f(a):
        ax = (tuple(axes) if axes is not None
              else tuple(range(0 if s is None else a.ndim - len(s),
                               a.ndim)))
        lead, last = tuple(ax[:-1]), ax[-1]
        n_last = None if s is None else s[-1]
        a = jnp.fft.ihfft(a, n=n_last, axis=last, norm=_norm(norm))
        if lead:
            s_lead = None if s is None else tuple(s[:-1])
            a = jnp.fft.ifftn(a, s=s_lead, axes=lead, norm=_norm(norm))
        return a
    return apply_op(f, _t(x))


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    return _hfftn_impl(x, s, axes, norm)


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    return _ihfftn_impl(x, s, axes, norm)


# helpers -------------------------------------------------------------------
def fftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.fftfreq(int(n), d=float(d))
    if dtype is not None:
        from .framework import convert_dtype
        out = out.astype(convert_dtype(dtype))
    return Tensor(out)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.rfftfreq(int(n), d=float(d))
    if dtype is not None:
        from .framework import convert_dtype
        out = out.astype(convert_dtype(dtype))
    return Tensor(out)


def fftshift(x, axes=None, name=None):
    return apply_op(lambda a: jnp.fft.fftshift(a, axes=axes), _t(x))


def ifftshift(x, axes=None, name=None):
    return apply_op(lambda a: jnp.fft.ifftshift(a, axes=axes), _t(x))
