"""KL divergence registry (ref: python/paddle/distribution/kl.py).

`register_kl((P, Q))` decorator + closed forms for the shipped pairs;
dispatch walks the MRO like the reference so subclasses inherit entries.
"""
from __future__ import annotations

import math

import jax.numpy as jnp
from jax.scipy import special as jss

from ..autograd import apply_op
from ..tensor import Tensor
from .continuous import (Beta, Dirichlet, Exponential, Gamma, Gumbel,
                         Laplace, LogNormal, Normal, Uniform)
from .discrete import Bernoulli, Categorical, Geometric, Poisson
from .distribution import Distribution, _arr

__all__ = ["kl_divergence", "register_kl"]

_REGISTRY = {}


def register_kl(cls_p, cls_q):
    def deco(fn):
        _REGISTRY[(cls_p, cls_q)] = fn
        return fn
    return deco


def _dispatch(tp, tq):
    matches = []
    for (p, q), fn in _REGISTRY.items():
        if issubclass(tp, p) and issubclass(tq, q):
            matches.append((p, q, fn))
    if not matches:
        raise NotImplementedError(
            f"no KL registered for ({tp.__name__}, {tq.__name__})")
    # most-derived match, like the reference's total-order heuristic
    matches.sort(key=lambda t: (len(t[0].__mro__) + len(t[1].__mro__)),
                 reverse=True)
    return matches[0][2]


def kl_divergence(p: Distribution, q: Distribution):
    return _dispatch(type(p), type(q))(p, q)


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    def _kl(pl, ps, ql, qs):
        var_ratio = (ps / qs) ** 2
        t1 = ((pl - ql) / qs) ** 2
        return 0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio))
    return apply_op(_kl, p.loc, p.scale, q.loc,
                    q.scale)


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    def _kl(pl, ph, ql, qh):
        out = jnp.log((qh - ql) / (ph - pl))
        return jnp.where((ql <= pl) & (ph <= qh), out, jnp.inf)
    return apply_op(_kl, p.low, p.high, q.low,
                    q.high)


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    def _kl(pp, qp):
        eps = jnp.finfo(pp.dtype).eps
        pp = jnp.clip(pp, eps, 1 - eps)
        qp = jnp.clip(qp, eps, 1 - eps)
        return (pp * (jnp.log(pp) - jnp.log(qp))
                + (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qp)))
    return apply_op(_kl, p.probs_param, q.probs_param)


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    def _kl(lp, lq):
        return jnp.sum(jnp.exp(lp) * (lp - lq), -1)
    return apply_op(_kl, p._logp_t(), q._logp_t())


@register_kl(Beta, Beta)
def _kl_beta(p, q):
    def _kl(pa, pb, qa, qb):
        pt = pa + pb
        return (jss.gammaln(pt) - jss.gammaln(pa) - jss.gammaln(pb)
                - jss.gammaln(qa + qb) + jss.gammaln(qa) + jss.gammaln(qb)
                + (pa - qa) * jss.digamma(pa) + (pb - qb) * jss.digamma(pb)
                + (qa + qb - pt) * jss.digamma(pt))
    return apply_op(_kl, p.alpha, p.beta, q.alpha,
                    q.beta)


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet(p, q):
    def _kl(pc, qc):
        p0 = jnp.sum(pc, -1)
        return (jss.gammaln(p0) - jnp.sum(jss.gammaln(pc), -1)
                - jss.gammaln(jnp.sum(qc, -1))
                + jnp.sum(jss.gammaln(qc), -1)
                + jnp.sum((pc - qc)
                          * (jss.digamma(pc) - jss.digamma(p0)[..., None]),
                          -1))
    return apply_op(_kl, p.concentration, q.concentration)


@register_kl(Gamma, Gamma)
def _kl_gamma(p, q):
    def _kl(pa, pr, qa, qr):
        return ((pa - qa) * jss.digamma(pa) - jss.gammaln(pa)
                + jss.gammaln(qa) + qa * (jnp.log(pr) - jnp.log(qr))
                + pa * (qr / pr - 1.0))
    return apply_op(_kl, p.concentration, p.rate,
                    q.concentration, q.rate)


@register_kl(Exponential, Exponential)
def _kl_exponential(p, q):
    def _kl(pr, qr):
        ratio = qr / pr
        return ratio - 1 - jnp.log(ratio)
    return apply_op(_kl, p.rate, q.rate)


@register_kl(Laplace, Laplace)
def _kl_laplace(p, q):
    def _kl(pl, ps, ql, qs):
        d = jnp.abs(pl - ql)
        return (jnp.log(qs / ps) + d / qs
                + ps / qs * jnp.exp(-d / ps) - 1)
    return apply_op(_kl, p.loc, p.scale, q.loc,
                    q.scale)


@register_kl(LogNormal, LogNormal)
def _kl_lognormal(p, q):
    return _kl_normal(p._base, q._base)


@register_kl(Gumbel, Gumbel)
def _kl_gumbel(p, q):
    # closed form: log(qs/ps) + euler*(ps/qs - 1) + (pl - ql)/qs
    #              + exp(-(pl - ql)/qs) * Gamma(ps/qs + 1) - 1
    def _kl(pl, ps, ql, qs):
        euler = 0.57721566490153286060
        ratio = ps / qs
        return (jnp.log(qs) - jnp.log(ps) + euler * (ratio - 1.0)
                + (pl - ql) / qs
                + jnp.exp(-(pl - ql) / qs + jss.gammaln(ratio + 1.0)) - 1.0)
    return apply_op(_kl, p.loc, p.scale, q.loc,
                    q.scale)


@register_kl(Geometric, Geometric)
def _kl_geometric(p, q):
    def _kl(pp, qp):
        return (-(-pp * jnp.log(pp) - (1 - pp) * jnp.log1p(-pp)) / pp
                + (-jnp.log(qp) * pp - jnp.log1p(-qp) * (1 - pp)) / pp)
    return apply_op(_kl, p.probs_param, q.probs_param)


@register_kl(Poisson, Poisson)
def _kl_poisson(p, q):
    def _kl(pr, qr):
        return pr * (jnp.log(pr) - jnp.log(qr)) - pr + qr
    return apply_op(_kl, p.rate, q.rate)
