"""Transforms + TransformedDistribution + Independent
(ref: python/paddle/distribution/transform.py,
 transformed_distribution.py, independent.py).

Transforms are pure jnp bijections with closed-form
`forward_log_det_jacobian`; TransformedDistribution composes them with a
base distribution's log_prob via the change-of-variables formula — all of
it fuses under jit.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..autograd import apply_op
from ..tensor import Tensor
from .distribution import Distribution, _arr, _t

__all__ = [
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "PowerTransform", "SigmoidTransform", "SoftmaxTransform",
    "StackTransform", "StickBreakingTransform", "TanhTransform",
    "ReshapeTransform", "IndependentTransform", "TransformedDistribution",
    "Independent",
]


class Transform:
    """Bijection base class (ref: paddle.distribution.Transform)."""

    _codomain_event_rank = 0
    _domain_event_rank = 0

    def forward(self, x):
        return apply_op(self._forward, _t(x))

    def inverse(self, y):
        return apply_op(self._inverse, _t(y))

    def forward_log_det_jacobian(self, x):
        return apply_op(self._fldj, _t(x))

    def inverse_log_det_jacobian(self, y):
        return apply_op(lambda yv: -self._fldj(self._inverse(yv)), _t(y))

    # jnp-level hooks
    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _fldj(self, x):
        raise NotImplementedError


class AbsTransform(Transform):
    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y  # right inverse (the reference returns the positive branch)

    def _fldj(self, x):
        return jnp.zeros_like(x)


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _arr(_t(loc))
        self.scale = _arr(_t(scale))

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _fldj(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), jnp.shape(x))


class ExpTransform(Transform):
    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        return x


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = _arr(_t(power))

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _fldj(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class SigmoidTransform(Transform):
    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _fldj(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(y)

    def _fldj(self, x):
        # log(1 - tanh(x)^2) = 2*(log2 - x - softplus(-2x)) — stable form
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class SoftmaxTransform(Transform):
    """Not a bijection; forward normalizes exp(x), inverse returns log(y)
    (the reference's convention)."""

    _codomain_event_rank = 1
    _domain_event_rank = 1

    def _forward(self, x):
        return jax.nn.softmax(x, axis=-1)

    def _inverse(self, y):
        return jnp.log(y)

    def _fldj(self, x):
        raise NotImplementedError("SoftmaxTransform has no log-det jacobian")


class StickBreakingTransform(Transform):
    """R^{K-1} -> K-simplex (ref semantics)."""

    _codomain_event_rank = 1
    _domain_event_rank = 1

    def _forward(self, x):
        k = x.shape[-1]
        offset = jnp.arange(k, 0, -1, dtype=x.dtype)
        z = jax.nn.sigmoid(x - jnp.log(offset))
        zc = jnp.cumprod(1 - z, axis=-1)
        lead = jnp.concatenate(
            [jnp.ones_like(zc[..., :1]), zc[..., :-1]], -1)
        head = z * lead
        return jnp.concatenate([head, zc[..., -1:]], -1)

    def _inverse(self, y):
        k = y.shape[-1] - 1
        offset = jnp.arange(k, 0, -1, dtype=y.dtype)
        csum = jnp.cumsum(y[..., :-1], -1)
        rem = 1 - jnp.concatenate(
            [jnp.zeros_like(csum[..., :1]), csum[..., :-1]], -1)
        z = y[..., :-1] / rem
        return jnp.log(z) - jnp.log1p(-z) + jnp.log(offset)

    def _fldj(self, x):
        k = x.shape[-1]
        offset = jnp.arange(k, 0, -1, dtype=x.dtype)
        t = x - jnp.log(offset)
        z = jax.nn.sigmoid(t)
        zc = jnp.cumprod(1 - z, axis=-1)
        lead = jnp.concatenate([jnp.ones_like(zc[..., :1]), zc[..., :-1]], -1)
        return jnp.sum(jnp.log(z) + jnp.log1p(-z) + jnp.log(lead), -1)


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _fldj(self, x):
        total = 0.0
        for t in self.transforms:
            total = total + t._fldj(x)
            x = t._forward(x)
        return total


class StackTransform(Transform):
    """Apply transforms[i] along slice i of `axis`."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = axis

    def _map(self, fn_name, x):
        parts = jnp.split(x, len(self.transforms), axis=self.axis)
        outs = [getattr(t, fn_name)(p)
                for t, p in zip(self.transforms, parts)]
        return jnp.concatenate(outs, axis=self.axis)

    def _forward(self, x):
        return self._map("_forward", x)

    def _inverse(self, y):
        return self._map("_inverse", y)

    def _fldj(self, x):
        return self._map("_fldj", x)


class ReshapeTransform(Transform):
    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)
        self._domain_event_rank = len(self.in_event_shape)
        self._codomain_event_rank = len(self.out_event_shape)

    def _forward(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return x.reshape(batch + self.out_event_shape)

    def _inverse(self, y):
        batch = y.shape[:y.ndim - len(self.out_event_shape)]
        return y.reshape(batch + self.in_event_shape)

    def _fldj(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return jnp.zeros(batch, dtype=x.dtype)


class IndependentTransform(Transform):
    """Sums the base transform's log-det over trailing dims."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        self._domain_event_rank = base._domain_event_rank + self.rank
        self._codomain_event_rank = base._codomain_event_rank + self.rank

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _fldj(self, x):
        ld = self.base._fldj(x)
        return jnp.sum(ld, axis=tuple(range(ld.ndim - self.rank, ld.ndim)))


def _sum_rightmost(a, k):
    if k <= 0:
        return a
    return jnp.sum(a, axis=tuple(range(a.ndim - k, a.ndim)))


class TransformedDistribution(Distribution):
    """ref: paddle.distribution.TransformedDistribution(base, transforms)."""

    def __init__(self, base, transforms):
        self.base = base
        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.transforms = list(transforms)
        self._chain = ChainTransform(self.transforms)
        # the transforms may change the event shape (e.g. StickBreaking
        # maps R^{K-1} -> K-simplex) and may also reinterpret trailing
        # batch dims as event dims: derive the output shape by shape-
        # tracing forward over an abstract sample (no FLOPs) and split it
        # at the codomain event rank
        in_shape = tuple(base.batch_shape) + tuple(base.event_shape)
        cod_rank = max([len(base.event_shape)]
                       + [t._codomain_event_rank for t in self.transforms])
        try:
            out = jax.eval_shape(
                self._chain._forward,
                jax.ShapeDtypeStruct(in_shape, jnp.float32))
            out_shape = tuple(out.shape)
        except Exception:
            out_shape = in_shape
        cut = len(out_shape) - cod_rank
        super().__init__(out_shape[:cut], out_shape[cut:])

    def sample(self, shape=()):
        x = self.base.sample(shape)
        return Tensor(jax.lax.stop_gradient(self._chain._forward(_arr(x))))

    def rsample(self, shape=()):
        x = self.base.rsample(shape)
        return apply_op(self._chain._forward, x)

    def log_prob(self, value):
        # change-of-variables with event-rank bookkeeping: each per-
        # transform log-det and the base log_prob are reduced over the
        # dims they don't already reduce (the reference's sum_rightmost
        # logic). Composed from separate apply_op calls so eager-tape
        # gradients reach the base distribution's parameters.
        base_rank = len(self.base.event_shape)
        event_dim = max([base_rank]
                        + [t._domain_event_rank for t in self.transforms])
        y = _t(value)
        lds = []
        for t in reversed(self.transforms):
            x = apply_op(t._inverse, y)
            k = event_dim - t._domain_event_rank
            lds.append(apply_op(
                lambda xv, t=t, k=k: _sum_rightmost(t._fldj(xv), k), x))
            y = x
        lp = apply_op(lambda a: _sum_rightmost(a, event_dim - base_rank),
                      self.base.log_prob(y))
        for ld in lds:
            lp = apply_op(jnp.subtract, lp, ld)
        return lp


class Independent(Distribution):
    """Reinterprets trailing batch dims as event dims
    (ref: paddle.distribution.Independent)."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)
        bshape = base.batch_shape
        if self.rank > len(bshape):
            raise ValueError("reinterpreted_batch_rank exceeds batch rank")
        cut = len(bshape) - self.rank
        super().__init__(bshape[:cut],
                         bshape[cut:] + tuple(base.event_shape))

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def _sum_rightmost(self, x):
        if self.rank == 0:
            return x
        return jnp.sum(x, axis=tuple(range(x.ndim - self.rank, x.ndim)))

    def log_prob(self, value):
        return apply_op(self._sum_rightmost, self.base.log_prob(value))

    def entropy(self):
        return apply_op(self._sum_rightmost, self.base.entropy())
