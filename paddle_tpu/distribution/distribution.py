"""Distribution base classes (ref: python/paddle/distribution/distribution.py).

TPU-native design notes: every density/statistic is a pure jnp function
routed through apply_op so it is differentiable both on the eager tape and
under jit/grad; sampling draws keys from the global generator
(framework.next_rng_key), which inside a traced step is a pure function of
the step's rng scope — so `dist.sample()` is legal inside a jitted train
step and reproducible across replicas.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..autograd import apply_op
from ..framework import next_rng_key
from ..tensor import Tensor, to_tensor


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _arr(x):
    """jnp array view of a Tensor / python scalar / array."""
    if isinstance(x, Tensor):
        return x._value
    return jnp.asarray(x)


def _pt(x):
    """Parameter Tensor: preserves a caller's float Tensor identity, so
    eager pathwise/score-function gradients flow back to distribution
    parameters (the reference's dygraph behavior); scalars/arrays wrap as
    constant Tensors, promoted to the default float dtype."""
    from ..framework import get_default_dtype
    if isinstance(x, Tensor):
        if jnp.issubdtype(x._value.dtype, jnp.floating):
            return x
        return x.astype(get_default_dtype())
    a = jnp.asarray(x)
    if not jnp.issubdtype(a.dtype, jnp.floating):
        a = a.astype(get_default_dtype())
    return Tensor(a)


def _fshape(shape):
    if shape is None:
        return ()
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._value))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


class Distribution:
    """Base class (ref: paddle.distribution.Distribution).

    `batch_shape`/`event_shape` follow the reference semantics; sample
    shapes are `sample_shape + batch_shape + event_shape`.
    """

    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    # -- interface -----------------------------------------------------
    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    def sample(self, shape=()):
        """Non-reparameterized draw (wrapped in stop_gradient)."""
        s = self.rsample(shape)
        return Tensor(jax.lax.stop_gradient(_arr(s)))

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return apply_op(jnp.exp, self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        from .kl import kl_divergence
        return kl_divergence(self, other)

    # -- helpers -------------------------------------------------------
    def _extend_shape(self, sample_shape):
        return _fshape(sample_shape) + self._batch_shape + self._event_shape

    def __repr__(self):
        return "{}(batch_shape={}, event_shape={})".format(
            type(self).__name__, self._batch_shape, self._event_shape)


class ExponentialFamily(Distribution):
    """Exponential-family base (ref: paddle.distribution.ExponentialFamily).

    Subclasses expose `_natural_parameters` and `_log_normalizer`; entropy
    falls back to the Bregman-divergence identity computed with jax.grad —
    the reference's autodiff trick, expressed functionally.
    """

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        return 0.0

    def entropy(self):
        nat = [_arr(p) for p in self._natural_parameters]

        def _ent(*np_):
            lg = self._log_normalizer(*np_)
            grads = jax.grad(lambda *a: jnp.sum(self._log_normalizer(*a)),
                             argnums=tuple(range(len(np_))))(*np_)
            ent = lg - self._mean_carrier_measure
            for p, g in zip(np_, grads):
                ent = ent - p * g
            return ent

        return apply_op(_ent, *[Tensor(n) for n in nat])
