"""Continuous distributions (ref: python/paddle/distribution/{normal,uniform,
beta,dirichlet,gamma,exponential,laplace,lognormal,gumbel,cauchy,
student_t}.py).

All math is closed-form jnp (lgamma/digamma from jax.scipy.special) so every
method fuses into the surrounding XLA graph. Parameters are stored as
Tensors (`d.loc`, `d.scale`, ... — the reference's dygraph convention), and
every density/statistic routes through apply_op, so gradients flow to
parameters on the eager tape AND under jit; rsample is reparameterized
(pathwise) wherever the reference supports it.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.scipy import special as jss

from ..autograd import apply_op
from ..framework import get_default_dtype, next_rng_key
from ..tensor import Tensor
from .distribution import Distribution, _arr, _fshape, _pt, _t

__all__ = [
    "Normal", "Uniform", "Beta", "Dirichlet", "Gamma", "Exponential",
    "Laplace", "LogNormal", "Gumbel", "Cauchy", "StudentT",
]


def _bshape(*ts):
    return jnp.broadcast_shapes(*[jnp.shape(_arr(t)) for t in ts])


class Normal(Distribution):
    """ref: paddle.distribution.Normal(loc, scale)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _pt(loc)
        self.scale = _pt(scale)
        super().__init__(_bshape(self.loc, self.scale))

    @property
    def mean(self):
        return apply_op(lambda l: jnp.broadcast_to(l, self.batch_shape),
                        self.loc)

    @property
    def variance(self):
        return apply_op(lambda s: jnp.broadcast_to(s ** 2, self.batch_shape),
                        self.scale)

    @property
    def stddev(self):
        return apply_op(lambda s: jnp.broadcast_to(s, self.batch_shape),
                        self.scale)

    def rsample(self, shape=()):
        shp = self._extend_shape(shape)
        eps = jax.random.normal(next_rng_key(), shp,
                                dtype=_arr(self.loc).dtype)
        return apply_op(lambda l, s: l + s * eps, self.loc, self.scale)

    def log_prob(self, value):
        return apply_op(
            lambda v, l, s: -((v - l) ** 2) / (2 * s ** 2)
            - jnp.log(s) - 0.5 * math.log(2 * math.pi),
            _t(value), self.loc, self.scale)

    def entropy(self):
        return apply_op(
            lambda s: jnp.broadcast_to(
                0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s),
                self.batch_shape),
            self.scale)

    def cdf(self, value):
        return apply_op(
            lambda v, l, s: 0.5 * (1 + jss.erf((v - l) / (s * math.sqrt(2)))),
            _t(value), self.loc, self.scale)

    def icdf(self, value):
        return apply_op(lambda q, l, s: l + s * jss.ndtri(q),
                        _t(value), self.loc, self.scale)

    def probs(self, value):
        return self.prob(value)


class Uniform(Distribution):
    """ref: paddle.distribution.Uniform(low, high)."""

    def __init__(self, low, high, name=None):
        self.low = _pt(low)
        self.high = _pt(high)
        super().__init__(_bshape(self.low, self.high))

    @property
    def mean(self):
        return apply_op(
            lambda lo, hi: jnp.broadcast_to((lo + hi) / 2, self.batch_shape),
            self.low, self.high)

    @property
    def variance(self):
        return apply_op(
            lambda lo, hi: jnp.broadcast_to((hi - lo) ** 2 / 12,
                                            self.batch_shape),
            self.low, self.high)

    def rsample(self, shape=()):
        shp = self._extend_shape(shape)
        u = jax.random.uniform(next_rng_key(), shp,
                               dtype=get_default_dtype())
        return apply_op(lambda lo, hi: lo + (hi - lo) * u,
                        self.low, self.high)

    def log_prob(self, value):
        return apply_op(
            lambda v, lo, hi: jnp.where(
                (v >= lo) & (v < hi), -jnp.log(hi - lo), -jnp.inf),
            _t(value), self.low, self.high)

    def entropy(self):
        return apply_op(lambda lo, hi: jnp.log(hi - lo),
                        self.low, self.high)

    def cdf(self, value):
        return apply_op(
            lambda v, lo, hi: jnp.clip((v - lo) / (hi - lo), 0.0, 1.0),
            _t(value), self.low, self.high)


class Beta(Distribution):
    """ref: paddle.distribution.Beta(alpha, beta)."""

    def __init__(self, alpha, beta):
        self.alpha = _pt(alpha)
        self.beta = _pt(beta)
        super().__init__(_bshape(self.alpha, self.beta))

    @property
    def mean(self):
        return apply_op(lambda a, b: a / (a + b), self.alpha, self.beta)

    @property
    def variance(self):
        return apply_op(lambda a, b: a * b / ((a + b) ** 2 * (a + b + 1)),
                        self.alpha, self.beta)

    def rsample(self, shape=()):
        shp = self._extend_shape(shape)
        k1, k2 = jax.random.split(next_rng_key())

        def _rs(a, b):
            ga = jax.random.gamma(k1, jnp.broadcast_to(a, shp),
                                  dtype=get_default_dtype())
            gb = jax.random.gamma(k2, jnp.broadcast_to(b, shp),
                                  dtype=get_default_dtype())
            return ga / (ga + gb)
        return apply_op(_rs, self.alpha, self.beta)

    def log_prob(self, value):
        return apply_op(
            lambda v, a, b: (a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v)
            - (jss.gammaln(a) + jss.gammaln(b) - jss.gammaln(a + b)),
            _t(value), self.alpha, self.beta)

    def entropy(self):
        def _ent(a, b):
            total = a + b
            lbeta = jss.gammaln(a) + jss.gammaln(b) - jss.gammaln(total)
            return (lbeta - (a - 1) * jss.digamma(a)
                    - (b - 1) * jss.digamma(b)
                    + (total - 2) * jss.digamma(total))
        return apply_op(_ent, self.alpha, self.beta)


class Dirichlet(Distribution):
    """ref: paddle.distribution.Dirichlet(concentration)."""

    def __init__(self, concentration):
        self.concentration = _pt(concentration)
        c = _arr(self.concentration)
        if c.ndim < 1:
            raise ValueError("concentration must be at least 1-D")
        super().__init__(c.shape[:-1], c.shape[-1:])

    @property
    def mean(self):
        return apply_op(lambda c: c / jnp.sum(c, -1, keepdims=True),
                        self.concentration)

    @property
    def variance(self):
        def _var(c):
            c0 = jnp.sum(c, -1, keepdims=True)
            m = c / c0
            return m * (1 - m) / (c0 + 1)
        return apply_op(_var, self.concentration)

    def rsample(self, shape=()):
        shp = _fshape(shape) + jnp.shape(_arr(self.concentration))
        key = next_rng_key()

        def _rs(c):
            g = jax.random.gamma(key, jnp.broadcast_to(c, shp),
                                 dtype=get_default_dtype())
            return g / jnp.sum(g, -1, keepdims=True)
        return apply_op(_rs, self.concentration)

    def log_prob(self, value):
        def _lp(v, c):
            lnorm = jnp.sum(jss.gammaln(c), -1) - jss.gammaln(jnp.sum(c, -1))
            return jnp.sum((c - 1) * jnp.log(v), -1) - lnorm
        return apply_op(_lp, _t(value), self.concentration)

    def entropy(self):
        def _ent(c):
            k = c.shape[-1]
            c0 = jnp.sum(c, -1)
            lnorm = jnp.sum(jss.gammaln(c), -1) - jss.gammaln(c0)
            return (lnorm + (c0 - k) * jss.digamma(c0)
                    - jnp.sum((c - 1) * jss.digamma(c), -1))
        return apply_op(_ent, self.concentration)


class Gamma(Distribution):
    """ref: paddle.distribution.Gamma(concentration, rate)."""

    def __init__(self, concentration, rate):
        self.concentration = _pt(concentration)
        self.rate = _pt(rate)
        super().__init__(_bshape(self.concentration, self.rate))

    @property
    def mean(self):
        return apply_op(
            lambda a, r: jnp.broadcast_to(a / r, self.batch_shape),
            self.concentration, self.rate)

    @property
    def variance(self):
        return apply_op(
            lambda a, r: jnp.broadcast_to(a / r ** 2, self.batch_shape),
            self.concentration, self.rate)

    def rsample(self, shape=()):
        shp = self._extend_shape(shape)
        key = next_rng_key()
        # jax.random.gamma is reparameterized (implicit-gradient rule)
        return apply_op(
            lambda a, r: jax.random.gamma(
                key, jnp.broadcast_to(a, shp), dtype=get_default_dtype())
            / jnp.broadcast_to(r, shp),
            self.concentration, self.rate)

    def log_prob(self, value):
        return apply_op(
            lambda v, a, r: a * jnp.log(r) + (a - 1) * jnp.log(v)
            - r * v - jss.gammaln(a),
            _t(value), self.concentration, self.rate)

    def entropy(self):
        return apply_op(
            lambda a, r: a - jnp.log(r) + jss.gammaln(a)
            + (1 - a) * jss.digamma(a),
            self.concentration, self.rate)


class Exponential(Distribution):
    """ref: paddle.distribution.Exponential(rate)."""

    def __init__(self, rate):
        self.rate = _pt(rate)
        super().__init__(jnp.shape(_arr(self.rate)))

    @property
    def mean(self):
        return apply_op(lambda r: 1.0 / r, self.rate)

    @property
    def variance(self):
        return apply_op(lambda r: r ** -2.0, self.rate)

    def rsample(self, shape=()):
        shp = self._extend_shape(shape)
        u = jax.random.uniform(next_rng_key(), shp,
                               dtype=get_default_dtype())
        # inverse-CDF; -log1p(-u) is exact near 0
        return apply_op(lambda r: -jnp.log1p(-u) / jnp.broadcast_to(r, shp),
                        self.rate)

    def log_prob(self, value):
        return apply_op(lambda v, r: jnp.log(r) - r * v,
                        _t(value), self.rate)

    def entropy(self):
        return apply_op(lambda r: 1.0 - jnp.log(r), self.rate)

    def cdf(self, value):
        return apply_op(lambda v, r: -jnp.expm1(-r * v),
                        _t(value), self.rate)


class Laplace(Distribution):
    """ref: paddle.distribution.Laplace(loc, scale)."""

    def __init__(self, loc, scale):
        self.loc = _pt(loc)
        self.scale = _pt(scale)
        super().__init__(_bshape(self.loc, self.scale))

    @property
    def mean(self):
        return apply_op(lambda l: jnp.broadcast_to(l, self.batch_shape),
                        self.loc)

    @property
    def variance(self):
        return apply_op(
            lambda s: jnp.broadcast_to(2 * s ** 2, self.batch_shape),
            self.scale)

    @property
    def stddev(self):
        return apply_op(
            lambda s: jnp.broadcast_to(math.sqrt(2) * s, self.batch_shape),
            self.scale)

    def rsample(self, shape=()):
        shp = self._extend_shape(shape)
        # sample from U(-1/2, 1/2); finfo.tiny keeps |u| away from 1/2
        u = jax.random.uniform(
            next_rng_key(), shp, dtype=get_default_dtype(),
            minval=jnp.finfo(get_default_dtype()).tiny - 0.5, maxval=0.5)
        return apply_op(
            lambda l, s: l - jnp.broadcast_to(s, shp) * jnp.sign(u)
            * jnp.log1p(-2 * jnp.abs(u)),
            self.loc, self.scale)

    def log_prob(self, value):
        return apply_op(
            lambda v, l, s: -jnp.abs(v - l) / s - jnp.log(2 * s),
            _t(value), self.loc, self.scale)

    def entropy(self):
        return apply_op(
            lambda s: jnp.broadcast_to(1 + jnp.log(2 * s), self.batch_shape),
            self.scale)

    def cdf(self, value):
        return apply_op(
            lambda v, l, s: 0.5 - 0.5 * jnp.sign(v - l)
            * jnp.expm1(-jnp.abs(v - l) / s),
            _t(value), self.loc, self.scale)

    def icdf(self, value):
        return apply_op(
            lambda q, l, s: l - s * jnp.sign(q - 0.5)
            * jnp.log1p(-2 * jnp.abs(q - 0.5)),
            _t(value), self.loc, self.scale)


class LogNormal(Distribution):
    """ref: paddle.distribution.LogNormal(loc, scale) — exp(Normal)."""

    def __init__(self, loc, scale):
        self._base = Normal(loc, scale)
        self.loc = self._base.loc
        self.scale = self._base.scale
        super().__init__(self._base.batch_shape)

    @property
    def mean(self):
        return apply_op(
            lambda l, s: jnp.broadcast_to(jnp.exp(l + s ** 2 / 2),
                                          self.batch_shape),
            self.loc, self.scale)

    @property
    def variance(self):
        return apply_op(
            lambda l, s: jnp.broadcast_to(
                jnp.expm1(s ** 2) * jnp.exp(2 * l + s ** 2),
                self.batch_shape),
            self.loc, self.scale)

    def rsample(self, shape=()):
        return apply_op(jnp.exp, self._base.rsample(shape))

    def log_prob(self, value):
        v = _t(value)
        base_lp = self._base.log_prob(apply_op(jnp.log, v))
        return apply_op(lambda lp, vv: lp - jnp.log(vv), base_lp, v)

    def entropy(self):
        return apply_op(
            lambda l, s: jnp.broadcast_to(
                0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s) + l,
                self.batch_shape),
            self.loc, self.scale)


class Gumbel(Distribution):
    """ref: paddle.distribution.Gumbel(loc, scale)."""

    _EULER = 0.57721566490153286060

    def __init__(self, loc, scale):
        self.loc = _pt(loc)
        self.scale = _pt(scale)
        super().__init__(_bshape(self.loc, self.scale))

    @property
    def mean(self):
        return apply_op(
            lambda l, s: jnp.broadcast_to(l + self._EULER * s,
                                          self.batch_shape),
            self.loc, self.scale)

    @property
    def variance(self):
        return apply_op(
            lambda s: jnp.broadcast_to((math.pi ** 2 / 6) * s ** 2,
                                       self.batch_shape),
            self.scale)

    @property
    def stddev(self):
        return apply_op(jnp.sqrt, self.variance)

    def rsample(self, shape=()):
        shp = self._extend_shape(shape)
        g = jax.random.gumbel(next_rng_key(), shp, dtype=get_default_dtype())
        return apply_op(lambda l, s: l + jnp.broadcast_to(s, shp) * g,
                        self.loc, self.scale)

    def log_prob(self, value):
        def _lp(v, l, s):
            z = (v - l) / s
            return -(z + jnp.exp(-z)) - jnp.log(s)
        return apply_op(_lp, _t(value), self.loc, self.scale)

    def entropy(self):
        return apply_op(
            lambda s: jnp.broadcast_to(jnp.log(s) + 1 + self._EULER,
                                       self.batch_shape),
            self.scale)

    def cdf(self, value):
        return apply_op(
            lambda v, l, s: jnp.exp(-jnp.exp(-(v - l) / s)),
            _t(value), self.loc, self.scale)


class Cauchy(Distribution):
    """ref: paddle.distribution.Cauchy(loc, scale)."""

    def __init__(self, loc, scale):
        self.loc = _pt(loc)
        self.scale = _pt(scale)
        super().__init__(_bshape(self.loc, self.scale))

    @property
    def mean(self):
        raise ValueError("Cauchy distribution has no mean")

    @property
    def variance(self):
        raise ValueError("Cauchy distribution has no variance")

    def rsample(self, shape=()):
        shp = self._extend_shape(shape)
        u = jax.random.uniform(next_rng_key(), shp,
                               dtype=get_default_dtype(),
                               minval=jnp.finfo(get_default_dtype()).eps,
                               maxval=1.0)
        return apply_op(
            lambda l, s: l + jnp.broadcast_to(s, shp)
            * jnp.tan(math.pi * (u - 0.5)),
            self.loc, self.scale)

    def log_prob(self, value):
        return apply_op(
            lambda v, l, s: -math.log(math.pi) - jnp.log(s)
            - jnp.log1p(((v - l) / s) ** 2),
            _t(value), self.loc, self.scale)

    def entropy(self):
        return apply_op(
            lambda s: jnp.broadcast_to(math.log(4 * math.pi) + jnp.log(s),
                                       self.batch_shape),
            self.scale)

    def cdf(self, value):
        return apply_op(
            lambda v, l, s: jnp.arctan((v - l) / s) / math.pi + 0.5,
            _t(value), self.loc, self.scale)


class StudentT(Distribution):
    """ref: paddle.distribution.StudentT(df, loc, scale)."""

    def __init__(self, df, loc=0.0, scale=1.0):
        self.df = _pt(df)
        self.loc = _pt(loc)
        self.scale = _pt(scale)
        super().__init__(_bshape(self.df, self.loc, self.scale))

    @property
    def mean(self):
        return apply_op(
            lambda df, l: jnp.broadcast_to(jnp.where(df > 1, l, jnp.nan),
                                           self.batch_shape),
            self.df, self.loc)

    @property
    def variance(self):
        def _var(df, s):
            v = jnp.where(df > 2, s ** 2 * df / (df - 2),
                          jnp.where(df > 1, jnp.inf, jnp.nan))
            return jnp.broadcast_to(v, self.batch_shape)
        return apply_op(_var, self.df, self.scale)

    def rsample(self, shape=()):
        shp = self._extend_shape(shape)
        key = next_rng_key()

        def _rs(df, l, s):
            z = jax.random.t(key, jnp.broadcast_to(df, shp),
                             dtype=get_default_dtype())
            return l + jnp.broadcast_to(s, shp) * z
        return apply_op(_rs, self.df, self.loc, self.scale)

    def log_prob(self, value):
        def _lp(v, df, l, s):
            z = (v - l) / s
            return (jss.gammaln((df + 1) / 2) - jss.gammaln(df / 2)
                    - 0.5 * jnp.log(df * math.pi) - jnp.log(s)
                    - (df + 1) / 2 * jnp.log1p(z ** 2 / df))
        return apply_op(_lp, _t(value), self.df, self.loc, self.scale)

    def entropy(self):
        def _ent(df, s):
            return (jnp.log(s) + (df + 1) / 2
                    * (jss.digamma((df + 1) / 2) - jss.digamma(df / 2))
                    + 0.5 * jnp.log(df)
                    + jss.gammaln(df / 2) + jss.gammaln(0.5)
                    - jss.gammaln((df + 1) / 2))
        return apply_op(_ent, self.df, self.scale)
