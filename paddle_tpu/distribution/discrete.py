"""Discrete distributions (ref: python/paddle/distribution/{bernoulli,
categorical,multinomial,geometric,poisson,binomial}.py).

Sampling is TPU-shaped: Categorical/Multinomial use the Gumbel-argmax trick
(jax.random.categorical) so draws are one fused kernel, no host round trip;
Poisson/Binomial route through jax.random's rejection samplers. Parameters
are stored as Tensors and all densities route through apply_op, so
score-function gradients flow to parameters on the eager tape and under jit.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.scipy import special as jss

from ..autograd import apply_op
from ..framework import get_default_dtype, next_rng_key
from ..tensor import Tensor
from .distribution import Distribution, _arr, _fshape, _pt, _t

__all__ = ["Bernoulli", "Categorical", "Multinomial", "Geometric",
           "Poisson", "Binomial"]


# x*log(y) with 0*log(0)=0 — jax maintains the gradient rule upstream
_xlogy = jss.xlogy


class Bernoulli(Distribution):
    """ref: paddle.distribution.Bernoulli(probs)."""

    def __init__(self, probs, name=None):
        self.probs_param = _pt(probs)
        super().__init__(jnp.shape(_arr(self.probs_param)))

    @property
    def mean(self):
        return apply_op(lambda p: p, self.probs_param)

    @property
    def variance(self):
        return apply_op(lambda p: p * (1 - p), self.probs_param)

    def sample(self, shape=()):
        shp = self._extend_shape(shape)
        u = jax.random.uniform(next_rng_key(), shp,
                               dtype=get_default_dtype())
        return Tensor((u < jnp.broadcast_to(_arr(self.probs_param), shp))
                      .astype(get_default_dtype()))

    def rsample(self, shape=(), temperature=1.0):
        """Gumbel-softmax relaxation (the reference's rsample contract)."""
        shp = self._extend_shape(shape)
        u = jax.random.uniform(
            next_rng_key(), shp, dtype=get_default_dtype(),
            minval=jnp.finfo(get_default_dtype()).eps, maxval=1.0)
        logistic = jnp.log(u) - jnp.log1p(-u)

        def _rs(p):
            logits = jnp.log(p) - jnp.log1p(-p)
            return jax.nn.sigmoid(
                (jnp.broadcast_to(logits, shp) + logistic) / temperature)
        return apply_op(_rs, self.probs_param)

    def log_prob(self, value):
        return apply_op(
            lambda v, p: _xlogy(v, p) + _xlogy(1 - v, 1 - p),
            _t(value), self.probs_param)

    def entropy(self):
        return apply_op(
            lambda p: -(_xlogy(p, p) + _xlogy(1 - p, 1 - p)),
            self.probs_param)

    def cdf(self, value):
        return apply_op(
            lambda v, p: jnp.where(v < 0, 0.0, jnp.where(v < 1, 1 - p, 1.0)),
            _t(value), self.probs_param)

    def kl_divergence(self, other):
        from .kl import kl_divergence
        return kl_divergence(self, other)


class Categorical(Distribution):
    """ref: paddle.distribution.Categorical(logits).

    NOTE the reference quirk: `logits` are UNNORMALIZED NON-NEGATIVE scores
    (normalized by their sum), not log-probabilities. We follow it for
    parity; `Categorical.from_logits` gives the conventional log-space
    constructor.
    """

    def __init__(self, logits, name=None):
        self.scores = _pt(logits)
        self._logits_t = None
        super().__init__(jnp.shape(_arr(self.scores))[:-1])

    @classmethod
    def from_logits(cls, logits):
        c = cls.__new__(cls)
        c.scores = None
        c._logits_t = _pt(logits)
        Distribution.__init__(c, jnp.shape(_arr(c._logits_t))[:-1])
        return c

    def _logp_t(self):
        """log-probabilities as a Tensor (grads flow to the params)."""
        if self._logits_t is not None:
            return apply_op(lambda lg: jax.nn.log_softmax(lg, axis=-1),
                            self._logits_t)
        return apply_op(lambda s: jnp.log(s / jnp.sum(s, -1, keepdims=True)),
                        self.scores)

    @property
    def num_events(self):
        return jnp.shape(_arr(self._logp_t()))[-1]

    def sample(self, shape=()):
        shp = _fshape(shape)
        lp = _arr(self._logp_t())
        draw = jax.random.categorical(
            next_rng_key(), lp, shape=shp + self.batch_shape)
        return Tensor(draw.astype(jnp.int64))

    def probs(self, value):
        def _p(lp, v):
            return jnp.take_along_axis(
                jnp.exp(lp), v.astype(jnp.int32)[..., None], axis=-1)[..., 0]
        return apply_op(_p, self._logp_t(), _t(value))

    def log_prob(self, value):
        def _lp(lp, v):
            return jnp.take_along_axis(
                lp, v.astype(jnp.int32)[..., None], axis=-1)[..., 0]
        return apply_op(_lp, self._logp_t(), _t(value))

    def entropy(self):
        return apply_op(lambda lp: -jnp.sum(jnp.exp(lp) * lp, -1),
                        self._logp_t())

    def kl_divergence(self, other):
        from .kl import kl_divergence
        return kl_divergence(self, other)


class Multinomial(Distribution):
    """ref: paddle.distribution.Multinomial(total_count, probs).

    Sampling is `total_count` fused categorical draws scattered into counts
    via one_hot-sum — static shapes throughout, so it jits cleanly.
    """

    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        p = _pt(probs)
        self.probs_param = apply_op(
            lambda a: a / jnp.sum(a, -1, keepdims=True), p)
        shp = jnp.shape(_arr(p))
        super().__init__(shp[:-1], shp[-1:])

    @property
    def mean(self):
        return apply_op(lambda p: self.total_count * p, self.probs_param)

    @property
    def variance(self):
        return apply_op(lambda p: self.total_count * p * (1 - p),
                        self.probs_param)

    def sample(self, shape=()):
        shp = _fshape(shape)
        p = _arr(self.probs_param)
        k = p.shape[-1]
        draws = jax.random.categorical(
            next_rng_key(), jnp.log(p),
            shape=(self.total_count,) + shp + self.batch_shape)
        counts = jax.nn.one_hot(draws, k, dtype=get_default_dtype()).sum(0)
        return Tensor(counts)

    def log_prob(self, value):
        def _lp(v, p):
            return (jss.gammaln(jnp.asarray(self.total_count + 1.0))
                    - jnp.sum(jss.gammaln(v + 1.0), -1)
                    + jnp.sum(_xlogy(v, p), -1))
        return apply_op(_lp, _t(value), self.probs_param)

    def entropy(self):
        # exact entropy has no closed form; we report the independent-draws
        # bound n*H(p) (documented approximation, matching scale)
        def _ent(p):
            h = -jnp.sum(_xlogy(p, p), -1)
            return self.total_count * h
        return apply_op(_ent, self.probs_param)


class Geometric(Distribution):
    """ref: paddle.distribution.Geometric(probs) — #failures before the
    first success, support {0, 1, 2, ...}."""

    def __init__(self, probs):
        self.probs_param = _pt(probs)
        super().__init__(jnp.shape(_arr(self.probs_param)))

    @property
    def mean(self):
        return apply_op(lambda p: (1 - p) / p, self.probs_param)

    @property
    def variance(self):
        return apply_op(lambda p: (1 - p) / p ** 2, self.probs_param)

    @property
    def stddev(self):
        return apply_op(jnp.sqrt, self.variance)

    def sample(self, shape=()):
        shp = self._extend_shape(shape)
        u = jax.random.uniform(
            next_rng_key(), shp, dtype=get_default_dtype(),
            minval=jnp.finfo(get_default_dtype()).tiny, maxval=1.0)
        p = jnp.broadcast_to(_arr(self.probs_param), shp)
        return Tensor(jnp.floor(jnp.log(u) / jnp.log1p(-p)))

    def log_prob(self, value):
        return apply_op(
            lambda v, p: v * jnp.log1p(-p) + jnp.log(p),
            _t(value), self.probs_param)

    def entropy(self):
        return apply_op(
            lambda p: -((1 - p) * jnp.log1p(-p) + p * jnp.log(p)) / p,
            self.probs_param)

    def cdf(self, value):
        return apply_op(
            lambda v, p: 1 - jnp.power(1 - p, jnp.floor(v) + 1),
            _t(value), self.probs_param)


class Poisson(Distribution):
    """ref: paddle.distribution.Poisson(rate)."""

    def __init__(self, rate):
        self.rate = _pt(rate)
        super().__init__(jnp.shape(_arr(self.rate)))

    @property
    def mean(self):
        return apply_op(lambda r: r, self.rate)

    @property
    def variance(self):
        return apply_op(lambda r: r, self.rate)

    def sample(self, shape=()):
        shp = self._extend_shape(shape)
        draw = jax.random.poisson(next_rng_key(),
                                  jnp.broadcast_to(_arr(self.rate), shp))
        return Tensor(draw.astype(get_default_dtype()))

    def log_prob(self, value):
        return apply_op(
            lambda v, r: _xlogy(v, r) - r - jss.gammaln(v + 1.0),
            _t(value), self.rate)

    def entropy(self):
        # exact truncated sum for small rate; Stirling series for large
        def _ent(r):
            n = 32
            ks = jnp.arange(n, dtype=r.dtype)
            lp = (_xlogy(ks, r[..., None]) - r[..., None]
                  - jss.gammaln(ks + 1.0))
            small = -jnp.sum(jnp.exp(lp) * lp, -1)
            large = (0.5 * jnp.log(2 * math.pi * math.e * r)
                     - 1 / (12 * r) - 1 / (24 * r ** 2))
            return jnp.where(r < 16.0, small, large)
        return apply_op(_ent, self.rate)


class Binomial(Distribution):
    """ref: paddle.distribution.Binomial(total_count, probs)."""

    def __init__(self, total_count, probs):
        self.total_count = int(total_count)
        self.probs_param = _pt(probs)
        super().__init__(jnp.shape(_arr(self.probs_param)))

    @property
    def mean(self):
        return apply_op(lambda p: self.total_count * p, self.probs_param)

    @property
    def variance(self):
        return apply_op(lambda p: self.total_count * p * (1 - p),
                        self.probs_param)

    def sample(self, shape=()):
        shp = self._extend_shape(shape)
        p = jnp.broadcast_to(_arr(self.probs_param), shp)
        draw = jax.random.binomial(next_rng_key(), self.total_count, p)
        return Tensor(draw.astype(get_default_dtype()))

    def log_prob(self, value):
        n = float(self.total_count)

        def _lp(v, p):
            comb = (jss.gammaln(jnp.asarray(n + 1.0)) - jss.gammaln(v + 1.0)
                    - jss.gammaln(n - v + 1.0))
            return comb + _xlogy(v, p) + _xlogy(n - v, 1 - p)
        return apply_op(_lp, _t(value), self.probs_param)

    def entropy(self):
        # exact sum over the (static) support
        n = self.total_count

        def _ent(p):
            ks = jnp.arange(n + 1, dtype=p.dtype)
            pb = p[..., None]
            comb = (jss.gammaln(jnp.asarray(n + 1.0))
                    - jss.gammaln(ks + 1.0) - jss.gammaln(n - ks + 1.0))
            lp = comb + _xlogy(ks, pb) + _xlogy(n - ks, 1 - pb)
            return -jnp.sum(jnp.exp(lp) * lp, -1)
        return apply_op(_ent, self.probs_param)
