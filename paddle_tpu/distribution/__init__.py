"""paddle.distribution parity (ref: python/paddle/distribution/__init__.py).

Probability distributions with TPU-shaped sampling (fused Gumbel-argmax
categorical draws, pathwise gradients, jit-safe rng via the global
generator), transforms, and a KL-divergence registry.
"""
from .distribution import Distribution, ExponentialFamily  # noqa: F401
from .continuous import (  # noqa: F401
    Beta, Cauchy, Dirichlet, Exponential, Gamma, Gumbel, Laplace, LogNormal,
    Normal, StudentT, Uniform,
)
from .discrete import (  # noqa: F401
    Bernoulli, Binomial, Categorical, Geometric, Multinomial, Poisson,
)
from .transform import (  # noqa: F401
    AbsTransform, AffineTransform, ChainTransform, ExpTransform,
    Independent, IndependentTransform, PowerTransform, ReshapeTransform,
    SigmoidTransform, SoftmaxTransform, StackTransform,
    StickBreakingTransform, TanhTransform, Transform,
    TransformedDistribution,
)
from .kl import kl_divergence, register_kl  # noqa: F401

__all__ = [
    "Distribution", "ExponentialFamily",
    "Beta", "Cauchy", "Dirichlet", "Exponential", "Gamma", "Gumbel",
    "Laplace", "LogNormal", "Normal", "StudentT", "Uniform",
    "Bernoulli", "Binomial", "Categorical", "Geometric", "Multinomial",
    "Poisson",
    "Transform", "AbsTransform", "AffineTransform", "ChainTransform",
    "ExpTransform", "PowerTransform", "SigmoidTransform",
    "SoftmaxTransform", "StackTransform", "StickBreakingTransform",
    "TanhTransform", "ReshapeTransform", "IndependentTransform",
    "TransformedDistribution", "Independent",
    "kl_divergence", "register_kl",
]
