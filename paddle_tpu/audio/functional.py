"""paddle.audio.functional parity (ref: python/paddle/audio/functional/
{window,functional}.py): window functions, mel filterbanks, unit
conversions.

All closed-form jnp — filterbanks are built once (host numpy) and applied
as a single matmul against the power spectrogram, which is the
MXU-friendly formulation.
"""
from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from ..tensor import Tensor

__all__ = [
    "get_window", "hz_to_mel", "mel_to_hz", "mel_frequencies",
    "fft_frequencies", "compute_fbank_matrix", "power_to_db",
    "create_dct",
]


def _window_np(name, win_length, fftbins=True, param=None):
    n = int(win_length)
    if name in ("hann", "hanning"):
        return np.hanning(n + 1)[:-1] if fftbins else np.hanning(n)
    if name in ("hamming",):
        return np.hamming(n + 1)[:-1] if fftbins else np.hamming(n)
    if name in ("blackman",):
        return np.blackman(n + 1)[:-1] if fftbins else np.blackman(n)
    if name in ("bartlett", "triang"):
        return np.bartlett(n + 1)[:-1] if fftbins else np.bartlett(n)
    if name in ("rect", "boxcar", "ones"):
        return np.ones(n)
    if name in ("kaiser",):
        beta = 12.0 if param is None else float(param)
        return (np.kaiser(n + 1, beta)[:-1] if fftbins
                else np.kaiser(n, beta))
    if name in ("gaussian",):
        std = 7.0 if param is None else float(param)
        k = np.arange(n) - (n - 1) / 2
        return np.exp(-0.5 * (k / std) ** 2)
    if name in ("exponential",):
        tau = (n / 8.0) if param is None else float(param)
        k = np.arange(n)
        return np.exp(-np.abs(k - (n - 1) / 2) / tau)
    if name in ("taylor",):
        # 4-term Taylor window, 30 dB sidelobe (the reference's default)
        nbar, sll = 4, 30.0
        b = 10 ** (sll / 20)
        a = np.arccosh(b) / np.pi
        s2 = nbar ** 2 / (a ** 2 + (nbar - 0.5) ** 2)
        ma = np.arange(1, nbar)
        fm = np.empty(nbar - 1)
        signs = np.empty_like(ma, float)
        signs[::2] = 1
        signs[1::2] = -1
        m2 = ma ** 2
        for mi, _ in enumerate(ma):
            numer = signs[mi] * np.prod(
                1 - m2[mi] / s2 / (a ** 2 + (ma - 0.5) ** 2))
            denom = 2 * np.prod([1 - m2[mi] / m2[j]
                                 for j in range(len(ma)) if j != mi])
            fm[mi] = numer / denom
        k = np.arange(n)
        w = np.ones(n)
        for mi, m in enumerate(ma):
            w += 2 * fm[mi] * np.cos(2 * np.pi * m * (k - (n - 1) / 2) / n)
        return w / w.max()
    raise ValueError(f"unsupported window {name!r}")


def get_window(window, win_length, fftbins=True, dtype="float32"):
    """ref: paddle.audio.functional.get_window."""
    if isinstance(window, tuple):
        name = window[0]
        param = window[1] if len(window) > 1 else None
    else:
        name, param = window, None
    from ..framework import convert_dtype
    w = _window_np(name, win_length, fftbins, param)
    return Tensor(jnp.asarray(w, dtype=convert_dtype(dtype)))


def hz_to_mel(freq, htk=False):
    """ref: paddle.audio.functional.hz_to_mel (slaney default)."""
    scalar = not hasattr(freq, "__len__") and not isinstance(freq, Tensor)
    f = np.asarray(freq._value if isinstance(freq, Tensor) else freq,
                   dtype=np.float64)
    if htk:
        out = 2595.0 * np.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        mels = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        mels = np.where(f >= min_log_hz,
                        min_log_mel + np.log(np.maximum(f, 1e-10)
                                             / min_log_hz) / logstep,
                        mels)
        out = mels
    if scalar:
        return float(out)
    return Tensor(jnp.asarray(out, jnp.float32)) if isinstance(freq, Tensor) \
        else out


def mel_to_hz(mel, htk=False):
    """ref: paddle.audio.functional.mel_to_hz."""
    scalar = not hasattr(mel, "__len__") and not isinstance(mel, Tensor)
    m = np.asarray(mel._value if isinstance(mel, Tensor) else mel,
                   dtype=np.float64)
    if htk:
        out = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        freqs = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        freqs = np.where(m >= min_log_mel,
                         min_log_hz * np.exp(logstep * (m - min_log_mel)),
                         freqs)
        out = freqs
    if scalar:
        return float(out)
    return Tensor(jnp.asarray(out, jnp.float32)) if isinstance(mel, Tensor) \
        else out


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False,
                    dtype="float32"):
    """ref: paddle.audio.functional.mel_frequencies."""
    lo = hz_to_mel(float(f_min), htk)
    hi = hz_to_mel(float(f_max), htk)
    mels = np.linspace(lo, hi, n_mels)
    from ..framework import convert_dtype
    return Tensor(jnp.asarray(mel_to_hz(mels, htk),
                              dtype=convert_dtype(dtype)))


def fft_frequencies(sr, n_fft, dtype="float32"):
    """ref: paddle.audio.functional.fft_frequencies."""
    from ..framework import convert_dtype
    return Tensor(jnp.asarray(
        np.linspace(0, float(sr) / 2, 1 + n_fft // 2),
        dtype=convert_dtype(dtype)))


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    """ref: paddle.audio.functional.compute_fbank_matrix →
    [n_mels, 1 + n_fft//2]."""
    if f_max is None:
        f_max = float(sr) / 2
    fftfreqs = np.linspace(0, float(sr) / 2, 1 + n_fft // 2)
    lo = hz_to_mel(float(f_min), htk)
    hi = hz_to_mel(float(f_max), htk)
    mel_f = mel_to_hz(np.linspace(lo, hi, n_mels + 2), htk)
    fdiff = np.diff(mel_f)
    ramps = mel_f[:, None] - fftfreqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = np.maximum(0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        weights = weights * enorm[:, None]
    from ..framework import convert_dtype
    return Tensor(jnp.asarray(weights, dtype=convert_dtype(dtype)))


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
    """ref: paddle.audio.functional.power_to_db."""
    from ..autograd import apply_op
    from .layers import _t

    def f(s):
        log_spec = 10.0 * jnp.log10(jnp.maximum(amin, s))
        log_spec = log_spec - 10.0 * math.log10(max(amin, ref_value))
        if top_db is not None:
            log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
        return log_spec
    return apply_op(f, _t(spect))


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    """ref: paddle.audio.functional.create_dct → [n_mels, n_mfcc]
    (type-II DCT basis)."""
    n = np.arange(n_mels, dtype=np.float64)
    k = np.arange(n_mfcc, dtype=np.float64)[None, :]
    basis = np.cos(math.pi / n_mels * (n[:, None] + 0.5) * k)
    if norm == "ortho":
        basis[:, 0] *= 1.0 / math.sqrt(2)
        basis *= math.sqrt(2.0 / n_mels)
    else:
        basis *= 2.0
    from ..framework import convert_dtype
    return Tensor(jnp.asarray(basis, dtype=convert_dtype(dtype)))
