"""paddle.audio.features parity (ref: python/paddle/audio/features/layers.py):
Spectrogram, MelSpectrogram, LogMelSpectrogram, MFCC as nn.Layers.

The whole chain is stft -> |.|^p -> fbank matmul -> dct matmul: two matmuls
and an FFT, fully jittable, so feature extraction runs on-device inside the
training step (the reference extracts on CPU workers; TPU-side extraction
avoids the host->device feature transfer entirely).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..autograd import apply_op
from ..nn.layer import Layer
from ..tensor import Tensor, to_tensor
from . import functional as AF

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


class Spectrogram(Layer):
    """ref: paddle.audio.features.Spectrogram — [B, T] ->
    [B, n_fft//2+1, num_frames] power spectrogram."""

    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.fft_window = AF.get_window(window, self.win_length,
                                        fftbins=True, dtype=dtype)

    def forward(self, x):
        from ..signal import stft
        spec = stft(x, self.n_fft, self.hop_length, self.win_length,
                    window=self.fft_window, center=self.center,
                    pad_mode=self.pad_mode)
        return apply_op(
            lambda s: jnp.abs(s) ** self.power, spec)


class MelSpectrogram(Layer):
    """ref: paddle.audio.features.MelSpectrogram."""

    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 dtype="float32"):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                        window, power, center, pad_mode,
                                        dtype)
        self.n_mels = n_mels
        self.fbank_matrix = AF.compute_fbank_matrix(
            sr, n_fft, n_mels, f_min, f_max, htk, norm, dtype)

    def forward(self, x):
        spec = self._spectrogram(x)                  # [B, F, T]
        return apply_op(lambda fb, s: jnp.einsum("mf,...ft->...mt", fb, s),
                        self.fbank_matrix, spec)


class LogMelSpectrogram(Layer):
    """ref: paddle.audio.features.LogMelSpectrogram."""

    def __init__(self, sr=22050, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 ref_value=1.0, amin=1e-10, top_db=None, dtype="float32"):
        super().__init__()
        self._melspectrogram = MelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, dtype)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        mel = self._melspectrogram(x)
        return AF.power_to_db(mel, self.ref_value, self.amin, self.top_db)


class MFCC(Layer):
    """ref: paddle.audio.features.MFCC — [B, T] -> [B, n_mfcc, num_frames]."""

    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self._log_melspectrogram = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, ref_value, amin,
            top_db, dtype)
        self.dct_matrix = AF.create_dct(n_mfcc, n_mels, dtype=dtype)

    def forward(self, x):
        logmel = self._log_melspectrogram(x)          # [B, M, T]
        return apply_op(lambda d, s: jnp.einsum("mc,...mt->...ct", d, s),
                        self.dct_matrix, logmel)
