"""paddle.audio parity (ref: python/paddle/audio/__init__.py): feature
layers + functional helpers. Dataset/backends (soundfile IO) are gated —
this framework ships the on-device compute path."""
from . import datasets  # noqa: F401
from . import functional  # noqa: F401
from . import layers  # noqa: F401
from .layers import MFCC, LogMelSpectrogram, MelSpectrogram, Spectrogram  # noqa: F401

# the reference exposes the layers under paddle.audio.features as well
features = layers

__all__ = ["datasets", "functional", "layers", "features", "Spectrogram",
           "MelSpectrogram", "LogMelSpectrogram", "MFCC"]
