"""paddle.audio.datasets parity (ref: python/paddle/audio/datasets/
{esc50,tess}.py).

Real parsers over the released on-disk layouts (stdlib `wave` reads the
16-bit PCM wavs — no soundfile dependency), with deterministic synthetic
fallbacks when no data_file is given. feat_type routes through this
package's jax-based feature extractors, so features are computed
on-device and jit-compatible downstream.
"""
from __future__ import annotations

import csv
import os
import wave

import numpy as np

from ..io.dataset import Dataset

__all__ = ["ESC50", "TESS"]


def load_wav(path, normalize=True):
    """(samples[float32 mono], sample_rate) from a PCM wav via stdlib
    `wave` (16/8/32-bit widths; channels averaged to mono)."""
    with wave.open(str(path), "rb") as w:
        sr = w.getframerate()
        n = w.getnframes()
        ch = w.getnchannels()
        width = w.getsampwidth()
        raw = w.readframes(n)
    dt = {1: np.uint8, 2: np.int16, 4: np.int32}.get(width)
    if dt is None:
        raise ValueError(f"unsupported wav sample width {width} in {path}")
    x = np.frombuffer(raw, dtype=dt).astype(np.float32)
    if width == 1:
        x = x - 128.0
    if ch > 1:
        x = x.reshape(-1, ch).mean(axis=1)
    if normalize:
        x = x / float(np.iinfo(dt).max if width > 1 else 127.0)
    return x, sr


def _synthetic_wave(n, length, n_classes, seed, sr=16000):
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, n_classes, size=n).astype(np.int64)
    t = np.arange(length, dtype=np.float32) / sr
    # class-dependent fundamental so features carry signal
    waves = np.stack([
        np.sin(2 * np.pi * (200 + 40 * int(l)) * t)
        + 0.1 * rng.randn(length).astype(np.float32)
        for l in labels]).astype(np.float32)
    return waves, labels


class _AudioDataset(Dataset):
    """Shared feat_type routing (ref: paddle.audio.datasets.dataset.
    AudioClassificationDataset feat_type/archive handling)."""

    def __init__(self, feat_type="raw", **feat_kwargs):
        super().__init__()
        if feat_type not in ("raw", "spectrogram", "melspectrogram",
                             "logmelspectrogram", "mfcc"):
            raise ValueError(f"unknown feat_type {feat_type!r}")
        self.feat_type = feat_type
        self.feat_kwargs = feat_kwargs
        self._extractors = {}            # keyed by sample rate: a mel
        # filterbank built for one sr is silently wrong for another

    def _features(self, x, sr):
        if self.feat_type == "raw":
            return x
        ext = self._extractors.get(sr)
        if ext is None:
            from . import features as F
            cls = {"spectrogram": F.Spectrogram,
                   "melspectrogram": F.MelSpectrogram,
                   "logmelspectrogram": F.LogMelSpectrogram,
                   "mfcc": F.MFCC}[self.feat_type]
            kw = dict(self.feat_kwargs)
            if self.feat_type != "spectrogram":
                kw.setdefault("sr", sr)
            ext = self._extractors[sr] = cls(**kw)
        from ..tensor import Tensor
        out = ext(Tensor(x[None, :]))
        return np.asarray(out._value)[0]

    def _load_sample(self, idx):
        raise NotImplementedError

    def __getitem__(self, idx):
        x, sr, label = self._load_sample(idx)
        return self._features(x, sr), np.int64(label)

    def __len__(self):
        return len(self.samples)


class ESC50(_AudioDataset):
    """ESC-50 environmental sound classification (ref:
    python/paddle/audio/datasets/esc50.py).

    data_file: the extracted ESC-50 release root (holding
    meta/esc50.csv + audio/*.wav). Five released folds: `split` picks
    the held-out fold (mode='dev' yields it, mode='train' the rest) —
    the reference's cross-validation contract. Without data_file:
    synthetic class-toned waves with the same (feature, label) shape."""

    NUM_CLASSES = 50

    def __init__(self, mode="train", split=1, feat_type="raw",
                 data_file=None, n=100, sample_length=8000,
                 **feat_kwargs):
        super().__init__(feat_type=feat_type, **feat_kwargs)
        if data_file is not None:
            meta = os.path.join(data_file, "meta", "esc50.csv")
            audio_dir = os.path.join(data_file, "audio")
            with open(meta, newline="") as f:
                rows = list(csv.DictReader(f))
            if not rows:
                raise ValueError(f"empty meta csv {meta}")
            keep = [r for r in rows
                    if (int(r["fold"]) == int(split)) == (mode == "dev")]
            self.samples = [(os.path.join(audio_dir, r["filename"]),
                             int(r["target"])) for r in keep]
            self._synthetic = None
            return
        waves, labels = _synthetic_wave(
            n, sample_length, self.NUM_CLASSES,
            20 if mode == "train" else 21)
        self._synthetic = (waves, labels)
        self.samples = list(range(n))

    def _load_sample(self, idx):
        if self._synthetic is not None:
            return self._synthetic[0][idx], 16000, self._synthetic[1][idx]
        path, label = self.samples[idx]
        x, sr = load_wav(path)
        return x, sr, label


# TESS filenames: {actor}_{word}_{emotion}.wav — label = emotion
_TESS_EMOTIONS = ("angry", "disgust", "fear", "happy", "neutral",
                  "ps", "sad")


class TESS(_AudioDataset):
    """Toronto Emotional Speech Set (ref:
    python/paddle/audio/datasets/tess.py) — 7 emotion classes from the
    `..._emotion.wav` filename suffix.

    data_file: the extracted TESS directory tree (wavs anywhere below).
    n_folds/split give the reference's modulo-fold train/dev split.
    Without data_file: synthetic."""

    NUM_CLASSES = len(_TESS_EMOTIONS)

    def __init__(self, mode="train", n_folds=5, split=1, feat_type="raw",
                 data_file=None, n=70, sample_length=8000, **feat_kwargs):
        super().__init__(feat_type=feat_type, **feat_kwargs)
        if data_file is not None:
            wavs = []
            for root, _, files in sorted(os.walk(data_file)):
                for f in sorted(files):
                    if f.lower().endswith(".wav"):
                        emotion = os.path.splitext(f)[0].split("_")[-1]
                        emotion = emotion.lower()
                        if emotion == "pleasant" or emotion == "surprise":
                            emotion = "ps"
                        if emotion in _TESS_EMOTIONS:
                            wavs.append(
                                (os.path.join(root, f),
                                 _TESS_EMOTIONS.index(emotion)))
            if not wavs:
                raise ValueError(
                    f"no `*_emotion.wav` files under {data_file}")
            keep = [(i % n_folds + 1 == int(split)) == (mode == "dev")
                    for i in range(len(wavs))]
            self.samples = [w for w, k in zip(wavs, keep) if k]
            self._synthetic = None
            return
        waves, labels = _synthetic_wave(
            n, sample_length, self.NUM_CLASSES,
            22 if mode == "train" else 23)
        self._synthetic = (waves, labels)
        self.samples = list(range(n))

    def _load_sample(self, idx):
        if self._synthetic is not None:
            return self._synthetic[0][idx], 16000, self._synthetic[1][idx]
        path, label = self.samples[idx]
        x, sr = load_wav(path)
        return x, sr, label
