"""`Tensor`: the user-facing array type.

The reference's Tensor is an eager VarBase over device memory with a C++
autograd tape (ref: paddle/fluid/eager/eager_tensor.h, python/paddle/tensor).
Here a Tensor wraps a `jax.Array` (already asynchronous / device-resident),
carries `stop_gradient` + `.grad` for eager-tape parity, and is registered as
a pytree node so whole models/state-dicts flow through jit/grad/pjit
transparently.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

import itertools

from . import framework
from .autograd import apply_op, backward as _backward

_hook_id_counter = itertools.count()

_tensor_method_registry = {}


def register_tensor_method(name, fn=None):
    """Attach `fn` as Tensor.<name> (used by the ops modules)."""
    def deco(f):
        setattr(Tensor, name, f)
        _tensor_method_registry[name] = f
        return f
    return deco(fn) if fn is not None else deco


class Tensor:
    __slots__ = ("_value", "stop_gradient", "_grad_value", "_retain_grads",
                 "_grad_node", "_grad_hooks", "name", "__weakref__")
    __array_priority__ = 100  # numpy defers binary ops to us

    def __init__(self, value, stop_gradient: bool = True, name: str = None):
        if isinstance(value, Tensor):
            value = value._value
        elif not isinstance(value, jax.Array):
            value = jnp.asarray(value)
        self._value = value
        self.stop_gradient = stop_gradient
        self._grad_value = None
        self._retain_grads = False
        self._grad_node = None
        self._grad_hooks = None
        self.name = name

    # -- basic properties ---------------------------------------------------
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def ndim(self):
        return self._value.ndim

    @property
    def dtype(self):
        return self._value.dtype

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def place(self):
        try:
            d = list(self._value.devices())[0]
            return framework.Place(d.platform, d.id)
        except Exception:
            return framework.CPUPlace()

    @property
    def is_leaf(self):
        return True

    def numel(self):
        return Tensor(jnp.asarray(self.size))

    # -- host interop -------------------------------------------------------
    def numpy(self):
        return np.asarray(self._value)

    def item(self, *idx):
        a = self._value
        return a[idx].item() if idx else a.item()

    def tolist(self):
        return np.asarray(self._value).tolist()

    def __array__(self, dtype=None):
        a = np.asarray(self._value)
        return a.astype(dtype) if dtype is not None else a

    def __jax_array__(self):
        return self._value

    # -- grad ---------------------------------------------------------------
    @property
    def grad(self):
        if self._grad_value is None:
            return None
        return Tensor(self._grad_value, stop_gradient=True)

    @grad.setter
    def grad(self, g):
        self._grad_value = None if g is None else (
            g._value if isinstance(g, Tensor) else jnp.asarray(g))

    def backward(self, grad_tensor=None, retain_graph=False):
        _backward(self, grad_tensor, retain_graph=retain_graph)

    def clear_grad(self):
        self._grad_value = None

    clear_gradient = clear_grad

    def retain_grads(self):
        self._retain_grads = True

    def register_hook(self, hook):
        """ref: Tensor.register_hook — `hook(grad) -> Tensor | None` runs
        when this tensor's gradient is computed in backward; a non-None
        return replaces the gradient (both for `.grad` and for further
        propagation). Returns a removable handle."""
        if self.stop_gradient:
            raise RuntimeError(
                "register_hook: cannot register a hook on a tensor with "
                "stop_gradient=True")
        if self._grad_hooks is None:
            self._grad_hooks = {}
        hooks = self._grad_hooks
        hid = next(_hook_id_counter)  # monotonic: stale handles can never
        hooks[hid] = hook             # alias a later registration's id

        class _Handle:
            def remove(h, _hooks=hooks, _id=hid):
                # keyed removal: idempotent, never touches another handle's
                # registration of the same callable
                _hooks.pop(_id, None)
        return _Handle()

    def detach(self):
        return Tensor(self._value, stop_gradient=True, name=self.name)

    def detach_(self):
        self.stop_gradient = True
        return self

    def clone(self):
        return apply_op(lambda x: x + 0, self)

    # -- dtype / device -----------------------------------------------------
    def astype(self, dtype):
        dt = framework.convert_dtype(dtype)
        return apply_op(lambda x: x.astype(dt), self)

    cast = astype

    def to(self, *args, **kwargs):
        t = self
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, (str, np.dtype)) and str(a) in \
                    framework._DTYPE_ALIASES or isinstance(a, type):
                t = t.astype(a)
        return t

    def cpu(self):
        cpu = jax.devices("cpu")[0]
        return Tensor(jax.device_put(self._value, cpu),
                      stop_gradient=self.stop_gradient)

    def pin_memory(self):
        return self

    def cuda(self, device_id=None, blocking=True):
        """ref: Tensor.cuda — maps to the default accelerator here."""
        return Tensor(jax.device_put(self._value, jax.devices()[0]),
                      stop_gradient=self.stop_gradient)

    def element_size(self):
        return int(jnp.dtype(self._value.dtype).itemsize)

    def dim(self):
        return self._value.ndim

    ndimension = dim

    def contiguous(self):
        return self  # jax arrays have no strided views

    def is_contiguous(self):
        return True

    def apply_(self, func):
        """ref: Tensor.apply_ — elementwise python callable, in place.
        Host-evaluated like the reference (documented as slow there too)."""
        import numpy as np
        host = np.asarray(self._value)
        self._value = jnp.asarray(np.vectorize(func)(host),
                                  dtype=self._value.dtype)
        return self

    def apply(self, func):
        out = Tensor(self._value, stop_gradient=True)
        return out.apply_(func)

    # -- python protocol ----------------------------------------------------
    def __len__(self):
        if not self._value.shape:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __bool__(self):
        return bool(self._value)

    def __int__(self):
        return int(self._value)

    def __float__(self):
        return float(self._value)

    def __index__(self):
        return int(self._value)

    def __hash__(self):
        return id(self)

    def __repr__(self):
        grad_txt = "" if self.stop_gradient else ", stop_gradient=False"
        return (f"Tensor(shape={self.shape}, dtype={self.dtype.name}"
                f"{grad_txt},\n       {np.asarray(self._value)!r})")

    def __format__(self, spec):
        return format(self.item() if self.size == 1 else np.asarray(self._value), spec)

    # -- indexing -----------------------------------------------------------
    def __getitem__(self, idx):
        return apply_op(lambda x, i: x[i], self, idx)

    def __setitem__(self, idx, value):
        # In-place scatter; a stop-gradient barrier (ref allows grad through
        # setitem, functional users should use put_along_axis / scatter).
        if isinstance(value, Tensor):
            value = value._value
        idx = jax.tree_util.tree_map(
            lambda x: x._value if isinstance(x, Tensor) else x, idx,
            is_leaf=lambda x: isinstance(x, Tensor))
        self._value = self._value.at[idx].set(value)

    # -- arithmetic operators (tape-aware via apply_op) ---------------------
    def __add__(self, o):
        return apply_op(jnp.add, self, o)

    __radd__ = __add__

    def __sub__(self, o):
        return apply_op(jnp.subtract, self, o)

    def __rsub__(self, o):
        return apply_op(lambda x, y: jnp.subtract(y, x), self, o)

    def __mul__(self, o):
        return apply_op(jnp.multiply, self, o)

    __rmul__ = __mul__

    def __truediv__(self, o):
        return apply_op(jnp.true_divide, self, o)

    def __rtruediv__(self, o):
        return apply_op(lambda x, y: jnp.true_divide(y, x), self, o)

    def __floordiv__(self, o):
        return apply_op(jnp.floor_divide, self, o, differentiable=False)

    def __rfloordiv__(self, o):
        return apply_op(lambda x, y: jnp.floor_divide(y, x), self, o,
                        differentiable=False)

    def __mod__(self, o):
        return apply_op(jnp.mod, self, o)

    def __rmod__(self, o):
        return apply_op(lambda x, y: jnp.mod(y, x), self, o)

    def __pow__(self, o):
        return apply_op(jnp.power, self, o)

    def __rpow__(self, o):
        return apply_op(lambda x, y: jnp.power(y, x), self, o)

    def __matmul__(self, o):
        return apply_op(jnp.matmul, self, o)

    def __rmatmul__(self, o):
        return apply_op(lambda x, y: jnp.matmul(y, x), self, o)

    def __neg__(self):
        return apply_op(jnp.negative, self)

    def __abs__(self):
        return apply_op(jnp.abs, self)

    def __invert__(self):
        return apply_op(jnp.logical_not, self, differentiable=False)

    # comparisons (non-differentiable)
    def __eq__(self, o):
        return apply_op(jnp.equal, self, o, differentiable=False)

    def __ne__(self, o):
        return apply_op(jnp.not_equal, self, o, differentiable=False)

    def __lt__(self, o):
        return apply_op(jnp.less, self, o, differentiable=False)

    def __le__(self, o):
        return apply_op(jnp.less_equal, self, o, differentiable=False)

    def __gt__(self, o):
        return apply_op(jnp.greater, self, o, differentiable=False)

    def __ge__(self, o):
        return apply_op(jnp.greater_equal, self, o, differentiable=False)

    def __and__(self, o):
        return apply_op(jnp.logical_and, self, o, differentiable=False)

    def __or__(self, o):
        return apply_op(jnp.logical_or, self, o, differentiable=False)

    def __xor__(self, o):
        return apply_op(jnp.logical_xor, self, o, differentiable=False)

    # -- in-place (eager convenience; rebinds the buffer) -------------------
    def _inplace(self, new):
        self._value = new._value if isinstance(new, Tensor) else jnp.asarray(new)
        return self

    def add_(self, o):
        return self._inplace(self + o)

    def subtract_(self, o):
        return self._inplace(self - o)

    def multiply_(self, o):
        return self._inplace(self * o)

    def scale_(self, s, bias=0.0):
        return self._inplace(self * s + bias)

    def zero_(self):
        return self._inplace(jnp.zeros_like(self._value))

    def fill_(self, v):
        return self._inplace(jnp.full_like(self._value, v))

    def copy_(self, src):
        return self._inplace(src)

    set_value = copy_

    def get_tensor(self):
        return self

    def __deepcopy__(self, memo):
        cls = type(self)
        obj = cls.__new__(cls)
        memo[id(self)] = obj
        for klass in cls.__mro__:
            for slot in getattr(klass, "__slots__", ()):
                if slot == "__weakref__":
                    continue
                try:
                    v = getattr(self, slot)
                    # jax arrays are immutable; share them — but the hook
                    # registry is mutable and must not be shared
                    if slot == "_grad_hooks" and v is not None:
                        v = dict(v)
                    object.__setattr__(obj, slot, v)
                except AttributeError:
                    pass
        return obj


def _tensor_flatten(t: Tensor):
    return (t._value,), (t.stop_gradient,)


def _tensor_unflatten(aux, children):
    return Tensor(children[0], stop_gradient=aux[0])


jax.tree_util.register_pytree_node(Tensor, _tensor_flatten, _tensor_unflatten)


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """ref: paddle.to_tensor. Python ints -> int64, floats -> default float
    dtype (float32), matching the reference's promotion rules."""
    if isinstance(data, Tensor):
        t = data.astype(dtype) if dtype is not None else Tensor(data._value)
        t.stop_gradient = stop_gradient
        return t
    dt = framework.convert_dtype(dtype)
    if dt is None:
        if isinstance(data, bool):
            dt = np.dtype("bool")
        elif isinstance(data, int):
            dt = np.dtype("int64")
        elif isinstance(data, float):
            dt = framework.get_default_dtype()
        elif isinstance(data, (list, tuple)):
            probe = np.asarray(data)
            if probe.dtype == np.float64:
                dt = framework.get_default_dtype()
            else:
                dt = probe.dtype
        elif isinstance(data, np.ndarray) and data.dtype == np.float64:
            dt = data.dtype  # keep f64 for explicit numpy input
    arr = jnp.asarray(data, dtype=dt)
    return Tensor(arr, stop_gradient=stop_gradient)
