"""DataLoader (ref: python/paddle/io/dataloader/dataloader_iter.py + the C++
reader ops in paddle/fluid/operators/reader/).

Single-process path collates numpy batches directly. num_workers>0 uses the
native C++ prefetch ring buffer (csrc/, loaded via ctypes) with Python
thread workers feeding it — on TPU hosts the bottleneck is HBM feed, so the
loader also exposes `device_prefetch` double-buffering: batch N+1 is
transferred to device while step N runs.
"""
from __future__ import annotations

import itertools
import threading
import time
import queue as _queue

import numpy as np

from ..tensor import Tensor
from .dataset import Dataset, IterableDataset
from .sampler import BatchSampler


def default_collate_fn(batch):
    sample = batch[0]
    if isinstance(sample, (np.ndarray, np.generic)):
        arrs = [np.asarray(b) for b in batch]
        if (len(arrs) > 1 and arrs[0].ndim > 0
                and all(a.shape == arrs[0].shape
                        and a.dtype == arrs[0].dtype for a in arrs[1:])):
            from .native import gather_rows
            return gather_rows(arrs)  # one native memcpy sweep, no GIL
        return np.stack(arrs)
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(b._value) for b in batch])
    if isinstance(sample, (int, float)):
        return np.asarray(batch)
    if isinstance(sample, (list, tuple)):
        transposed = list(zip(*batch))
        return type(sample)(default_collate_fn(list(s)) for s in transposed)
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    return np.asarray(batch)


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False, use_process_workers=None):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        # True: spawn worker PROCESSES + shared-memory transport (ref:
        # paddle's dataloader/worker.py — the GIL cannot feed a
        # TPU-rate consumer through Python decode/augment). Default
        # (None/False) keeps the thread+C++-ring prefetcher: spawn
        # re-imports the framework per worker (~seconds), which only
        # pays for itself on decode/augment-heavy input pipelines —
        # exactly where the reference's worker processes earn their
        # keep (bench.py --input-pipeline measures the crossover).
        self.use_process_workers = use_process_workers
        if use_process_workers and num_workers == 0:
            # __iter__ takes the num_workers==0 inline path before
            # _use_processes() ever runs — without this check the
            # opt-in would be silently ignored (every other invalid
            # combination raises; ADVICE r5 #3)
            raise ValueError(
                "use_process_workers=True requires num_workers >= 1 "
                "(num_workers=0 is the inline single-process path; the "
                "spawn-worker opt-in would be silently ignored)")
        self.use_shared_memory = use_shared_memory
        self.persistent_workers = persistent_workers
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset=dataset, shuffle=shuffle,
                batch_size=batch_size, drop_last=drop_last)

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def _gen_batches(self):
        if self._iterable:
            it = iter(self.dataset)
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch:
                    return
                if len(batch) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(batch)
        else:
            for indices in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in indices])

    def __iter__(self):
        # batch-wait telemetry: the time the consumer spends blocked in
        # next() is THE input-bound-run diagnostic (an input-starved
        # accelerator shows up here, not in step_time). One histogram
        # observe per batch, host-side only (docs/observability.md).
        from ..observability.metrics import get_registry
        reg = get_registry()
        # role label keeps eval/predict loaders out of the train
        # batch-wait series (hapi stamps _obs_role; standalone loaders
        # default to the train diagnostic)
        role = getattr(self, "_obs_role", "train")
        hist = reg.histogram(
            "dataloader_batch_wait_seconds",
            help="time the consuming loop waited for the next batch",
            labels={"role": role})
        ctr = reg.counter("dataloader_batches_total",
                          help="batches produced by DataLoader",
                          labels={"role": role})
        it = self._iter_batches()
        while True:
            t0 = time.perf_counter()
            try:
                batch = next(it)
            except StopIteration:
                return
            hist.observe(time.perf_counter() - t0)
            ctr.inc()
            yield batch

    def _iter_batches(self):
        if self.num_workers == 0:
            for b in self._gen_batches():
                yield _to_tensors(b)
            return
        if self._use_processes():
            pool = self._process_pool()
            try:
                for b in pool.run_epoch(iter(self.batch_sampler)):
                    yield _to_tensors(b)
            finally:
                if not self.persistent_workers:
                    pool.shutdown()
                    self._pool = None
            return
        yield from self._prefetch_iter(self._gen_batches())

    def _process_pool(self):
        from .process_worker import ProcessPrefetcher
        pool = getattr(self, "_pool", None)
        if pool is not None and not pool._closed:
            return pool  # persistent_workers: reuse across epochs
        # base seed ties worker augmentation randomness to paddle.seed
        # (reproducible runs) while varying across pools, so a fresh
        # non-persistent pool does not replay epoch 1's augmentations
        import jax

        from .. import framework
        seed = int(jax.random.randint(framework.next_rng_key(), (),
                                      0, 2 ** 31 - 1))
        pool = self._pool = ProcessPrefetcher(
            self.dataset, self.collate_fn, self.num_workers,
            prefetch_factor=self.prefetch_factor,
            worker_init_fn=self.worker_init_fn, seed=seed,
            timeout=self.timeout)
        return pool

    def _use_processes(self):
        """Process workers: opted in, map-style dataset, shared memory
        wanted, and everything the spawn must carry pickles."""
        if not self.use_process_workers:
            return False
        if self._iterable or not self.use_shared_memory:
            raise ValueError(
                "use_process_workers=True needs a map-style dataset and "
                "use_shared_memory=True (IterableDataset streams through "
                "the thread prefetcher)")
        from .process_worker import can_use_process_workers
        ok = can_use_process_workers(self.dataset, self.collate_fn) and \
            (self.worker_init_fn is None or
             can_use_process_workers(self.worker_init_fn, None))
        if not ok:
            raise ValueError(
                "use_process_workers=True but the dataset / collate_fn / "
                "worker_init_fn does not pickle (spawn workers require "
                "it); use module-level functions instead of lambdas or "
                "pass use_process_workers=False")
        return True

    def _prefetch_iter(self, gen):
        """Thread prefetch backed by the C++ ring buffer when available."""
        from .native import NativePrefetcher
        depth = max(2, self.num_workers * self.prefetch_factor)
        native = NativePrefetcher.create(depth)
        done = object()

        def producer(put):
            # put returns False once the consumer closed the queue — stop
            # quietly instead of retrying into a dead queue
            try:
                for item in gen:
                    if not put(item):
                        return
                put(done)
            except BaseException as e:  # propagate worker errors to consumer
                put(_WorkerError(e))

        if native is not None:
            t = threading.Thread(target=producer, args=(native.put,),
                                 daemon=True)
            t.start()
            try:
                while True:
                    item = native.get()
                    if item is done or item is native.CLOSED:
                        break
                    if isinstance(item, _WorkerError):
                        raise item.exc
                    yield _to_tensors(item)
            finally:
                # early exit included: wake the (possibly push-blocked)
                # producer, join it, and only then free the native queue.
                # If the producer is still alive after the join timeout
                # (stuck in dataset code, not yet in push), destroying
                # would free memory under a live thread — leak the handle
                # instead; the daemon thread's eventual push fails safely
                # against the closed-but-alive queue.
                native.close()
                t.join(timeout=10)
                if not t.is_alive():
                    native.destroy()
            return
        # pure-python fallback
        q = _queue.Queue(maxsize=depth)

        def py_put(item):
            q.put(item)
            return True

        t = threading.Thread(target=producer, args=(py_put,), daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is done:
                break
            if isinstance(item, _WorkerError):
                raise item.exc
            yield _to_tensors(item)
        t.join()


class _WorkerError:
    """Carries a worker exception across the prefetch queue."""

    def __init__(self, exc):
        self.exc = exc


def _to_tensors(batch):
    if isinstance(batch, np.ndarray):
        return Tensor(batch)
    if isinstance(batch, (list, tuple)):
        return type(batch)(_to_tensors(b) for b in batch)
    if isinstance(batch, dict):
        return {k: _to_tensors(v) for k, v in batch.items()}
    return batch


def device_prefetch(iterable, sharding=None, size=2):
    """Double-buffered host->device feed (ref: buffered_reader.cc's
    pinned-staging + async H2D copy pair).

    jax.device_put is asynchronous: issuing batch N+1's transfer before
    yielding batch N overlaps the copy with the running step. `size` is the
    number of in-flight device batches (2 = classic double buffering);
    `sharding` optionally places batches (e.g. NamedSharding over 'dp')."""
    import collections
    import jax

    def put(batch):
        def one(x):
            if isinstance(x, Tensor):
                x = x._value
            if hasattr(x, "ndim"):
                return jax.device_put(x, sharding)
            return x
        if isinstance(batch, (list, tuple)):
            return type(batch)(one(b) for b in batch)
        if isinstance(batch, dict):
            return {k: one(v) for k, v in batch.items()}
        return one(batch)

    buf = collections.deque()
    it = iter(iterable)
    try:
        for batch in it:
            buf.append(put(batch))
            if len(buf) >= size:
                yield buf.popleft()
        while buf:
            yield buf.popleft()
    finally:
        buf.clear()


class WorkerInfo:
    """ref: paddle.io.dataloader.worker.WorkerInfo."""

    def __init__(self, id, num_workers, seed, dataset):
        self.id = id
        self.num_workers = num_workers
        self.seed = seed
        self.dataset = dataset

    def __repr__(self):
        return (f"WorkerInfo(id={self.id}, "
                f"num_workers={self.num_workers}, seed={self.seed})")


_worker_info = None  # set inside process workers (io/process_worker.py)


def get_worker_info():
    """ref: paddle.io.get_worker_info — WorkerInfo inside a DataLoader
    worker process (spawn-based pool, io/process_worker.py), None in
    the main process / thread-prefetch path."""
    return _worker_info


def default_convert_fn(batch):
    """ref: paddle.io.dataloader.collate.default_convert_fn — convert
    without batching. namedtuples rebuild field-wise like the
    reference."""
    if isinstance(batch, tuple) and hasattr(batch, "_fields"):
        return type(batch)(*(default_convert_fn(b) for b in batch))
    if isinstance(batch, (list, tuple)):
        return type(batch)(default_convert_fn(b) for b in batch)
    if isinstance(batch, dict):
        return {k: default_convert_fn(v) for k, v in batch.items()}
    if isinstance(batch, (int, float)):
        return np.asarray(batch)
    return batch
