"""Datasets (ref: python/paddle/io/dataloader/dataset.py)."""
from __future__ import annotations

import bisect

import numpy as np

from ..tensor import Tensor


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        lens = {len(t) for t in tensors}
        assert len(lens) == 1, "tensors must share dim0"
        # Datasets are the host-side stage of the pipeline: keep numpy views
        # so per-sample indexing never touches the device (the reference's
        # Tensors are host-memory here too).
        self._arrays = [np.asarray(t._value) if isinstance(t, Tensor)
                        else np.asarray(t) for t in tensors]

    def __getitem__(self, idx):
        return tuple(a[idx] for a in self._arrays)

    def __len__(self):
        return len(self._arrays[0])


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        off = idx - (self.cumulative_sizes[ds_idx - 1] if ds_idx else 0)
        return self.datasets[ds_idx][off]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    lengths = list(lengths)
    if all(isinstance(l, float) for l in lengths):
        n = len(dataset)
        counts = [int(np.floor(n * f)) for f in lengths]
        rem = n - sum(counts)
        for i in range(rem):
            counts[i % len(counts)] += 1
        lengths = counts
    assert sum(lengths) == len(dataset)
    perm = np.random.permutation(len(dataset))
    out, off = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[off:off + l].tolist()))
        off += l
    return out
