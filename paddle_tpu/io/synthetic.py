"""Synthetic decode/augment-heavy dataset for input-pipeline load tests.

The reference benchmarks its DataLoader worker processes against JPEG
decode + augment (ref: paddle/fluid/dataloader benchmarks; DALI-class
pipelines). With zero egress there are no real JPEGs here, so this
emulates the same CPU profile in pure numpy: PRNG pixel synthesis
(stands in for Huffman decode), bilinear resize, random crop, flip,
fp32 normalize — a few ms of GIL-holding work per image, which is what
makes thread workers starve a TPU-rate consumer and process workers
(io/process_worker.py) the fix. Picklable by construction so spawn
workers can import it.
"""
from __future__ import annotations

import numpy as np

from .dataset import Dataset

__all__ = ["SyntheticImageDataset"]


class SyntheticImageDataset(Dataset):
    """item i -> augmented [3, out] float32 image, deterministic in i."""

    def __init__(self, n=2048, src=320, out=224):
        self.n = int(n)
        self.src = int(src)
        self.out = int(out)

    def __len__(self):
        return self.n

    def _bilinear_resize(self, img, size):
        h, w, _ = img.shape
        ys = np.linspace(0, h - 1, size)
        xs = np.linspace(0, w - 1, size)
        y0 = np.floor(ys).astype(np.int64)
        x0 = np.floor(xs).astype(np.int64)
        y1 = np.minimum(y0 + 1, h - 1)
        x1 = np.minimum(x0 + 1, w - 1)
        wy = (ys - y0)[:, None, None]
        wx = (xs - x0)[None, :, None]
        f = img.astype(np.float32)
        top = f[y0][:, x0] * (1 - wx) + f[y0][:, x1] * wx
        bot = f[y1][:, x0] * (1 - wx) + f[y1][:, x1] * wx
        return top * (1 - wy) + bot * wy

    def __getitem__(self, i):
        rng = np.random.default_rng(i)
        # "decode": synthesize the source image (CPU-bound PRNG fill)
        img = rng.integers(0, 256, (self.src, self.src, 3),
                           dtype=np.uint8)
        # augment: resize -> random crop -> flip -> normalize
        scale = self._bilinear_resize(img, self.out + 32)
        oy, ox = rng.integers(0, 33, 2)
        crop = scale[oy:oy + self.out, ox:ox + self.out]
        if rng.random() < 0.5:
            crop = crop[:, ::-1]
        x = crop.astype(np.float32) / 255.0
        x = (x - np.float32(0.45)) / np.float32(0.225)
        return np.ascontiguousarray(x.transpose(2, 0, 1))
