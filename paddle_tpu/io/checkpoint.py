"""Checkpoint manager (SURVEY §2.11 / §5).

ref parity: the reference's fleet checkpointing (paddle.distributed.fleet
save/load + incubate.distributed.utils) keeps rolling checkpoints and
supports exact resume (params + opt state + lr + scaler + rng). Here:

- CheckpointManager: save(step, state) with an async background thread
  (train loop never blocks on disk), keep_max rolling retention +
  best-metric pinning, latest()/best() lookup, exact-resume payloads.
- Backend: orbax when available (async sharded saves on real TPU pods),
  else the built-in serialization (np .pdparams-style pickle).
"""
from __future__ import annotations

import json
import os
import shutil
import threading
import time

import jax
import numpy as np

from .. import serialization

__all__ = ["CheckpointManager"]


def _host_tree(tree):
    """device_get arrays; Tensors -> numpy (consolidates shardings)."""
    from ..tensor import Tensor

    def one(x):
        if isinstance(x, Tensor):
            x = x._value
        if isinstance(x, jax.Array):
            return np.asarray(jax.device_get(x))
        return x
    return jax.tree_util.tree_map(
        one, tree, is_leaf=lambda t: isinstance(t, Tensor))


class CheckpointManager:
    """Rolling, optionally-async checkpoint directory:

        mgr = CheckpointManager("ckpts", keep_max=3, async_save=True)
        mgr.save(step, {"model": net.state_dict(), "opt": opt_state, ...},
                 metric=val_acc)
        ...
        state = mgr.restore()           # latest
        state = mgr.restore(best=True)  # best metric ever
    """

    def __init__(self, directory, keep_max=5, async_save=False,
                 mode="max"):
        self.dir = str(directory)
        self.keep_max = keep_max
        self.async_save = async_save
        self.mode = mode
        os.makedirs(self.dir, exist_ok=True)
        self._lock = threading.Lock()
        self._pending: threading.Thread | None = None
        self._index = self._load_index()

    # -- index -------------------------------------------------------------
    def _index_path(self):
        return os.path.join(self.dir, "index.json")

    def _load_index(self):
        try:
            with open(self._index_path()) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return {"steps": [], "best_step": None, "best_metric": None}

    def _write_index(self):
        tmp = self._index_path() + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._index, f)
        os.replace(tmp, self._index_path())

    def _step_dir(self, step):
        return os.path.join(self.dir, f"step_{step}")

    # -- save --------------------------------------------------------------
    def save(self, step, state, metric=None):
        """Snapshot `state` (any pytree: params/opt/lr/rng/scaler) at
        `step`. Device arrays are fetched to host synchronously (cheap —
        they were about to be donated anyway); disk write happens on the
        background thread when async_save."""
        host = _host_tree(state)
        self.wait()  # one in-flight save at a time, like orbax
        if self.async_save:
            self._pending = threading.Thread(
                target=self._write_guarded, args=(step, host, metric),
                daemon=True)
            self._pending.start()
        else:
            self._write(step, host, metric)

    def _write_guarded(self, step, host_state, metric):
        try:
            self._write(step, host_state, metric)
        except BaseException as e:  # surfaced by the next wait()/save()
            self._error = e

    def _write(self, step, host_state, metric):
        d = self._step_dir(step)
        tmp = d + ".tmp"
        os.makedirs(tmp, exist_ok=True)
        serialization.save(host_state, os.path.join(tmp, "state.pdparams"))
        meta = {"step": step, "metric": metric, "time": time.time()}
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump(meta, f)
        if os.path.exists(d):
            shutil.rmtree(d)
        os.replace(tmp, d)
        with self._lock:
            idx = self._index
            if step not in idx["steps"]:
                idx["steps"].append(step)
                idx["steps"].sort()
            if metric is not None:
                better = (idx["best_metric"] is None
                          or (metric > idx["best_metric"]
                              if self.mode == "max"
                              else metric < idx["best_metric"]))
                if better:
                    idx["best_metric"] = metric
                    idx["best_step"] = step
            self._gc()
            self._write_index()

    def _gc(self):
        idx = self._index
        keep = set(idx["steps"][-self.keep_max:])
        if idx["best_step"] is not None:
            keep.add(idx["best_step"])
        for s in list(idx["steps"]):
            if s not in keep:
                idx["steps"].remove(s)
                shutil.rmtree(self._step_dir(s), ignore_errors=True)

    def wait(self):
        """Block until the in-flight async save lands (call before exit).
        Re-raises any error the background write hit — a checkpoint the
        caller believes exists must exist."""
        if self._pending is not None:
            self._pending.join()
            self._pending = None
        err = getattr(self, "_error", None)
        if err is not None:
            self._error = None
            raise RuntimeError("async checkpoint save failed") from err

    # -- restore -----------------------------------------------------------
    def latest_step(self):
        with self._lock:
            return self._index["steps"][-1] if self._index["steps"] else None

    def best_step(self):
        with self._lock:
            return self._index["best_step"]

    def all_steps(self):
        with self._lock:
            return list(self._index["steps"])

    def restore(self, step=None, best=False):
        """Load a snapshot (default: latest). Returns the saved pytree with
        numpy leaves, or None when the directory is empty."""
        self.wait()
        if best:
            step = self.best_step()
            if step is None:
                raise ValueError(
                    "restore(best=True) but no checkpoint was saved with a "
                    "metric - pass metric= to save(), or restore latest")
        if step is None:
            step = self.latest_step()
        if step is None:
            return None
        return serialization.load(
            os.path.join(self._step_dir(step), "state.pdparams"),
            return_numpy=True)
