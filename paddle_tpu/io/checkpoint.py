"""Checkpoint manager (SURVEY §2.11 / §5).

ref parity: the reference's fleet checkpointing (paddle.distributed.fleet
save/load + incubate.distributed.utils) keeps rolling checkpoints and
supports exact resume (params + opt state + lr + scaler + rng). Here:

- CheckpointManager: save(step, state) with an async background thread
  (train loop never blocks on disk), keep_max rolling retention +
  best-metric pinning, latest()/best() lookup, exact-resume payloads.
- Crash-safe finalize (docs/robustness.md): every save ends by writing
  a COMPLETE marker after the payload's atomic rename; latest()/best()/
  restore() consider only finalized dirs and fall back past corrupt
  ones, so a preemption or crash at ANY byte of a save costs that save,
  never the ability to restore an older one.
- Backend: sharded=True routes every jax.Array leaf through orbax
  (per-shard tensorstore writes driven by the array's NamedSharding — the
  full tree is NEVER gathered to one host; on a pod each host writes only
  its addressable shards, the moral equivalent of fleet's sharded
  save/load). Non-array leaves (steps, rng seeds, scaler scalars) ride in
  a pickled skeleton next to it. restore(target=...) places arrays
  straight onto the target shardings. sharded=False (default) is the
  plain single-host pickle.
"""
from __future__ import annotations

import json
import os
import shutil
import threading
import time

import jax
import numpy as np

from .. import serialization
from . import atomic

__all__ = ["CheckpointManager"]


def _host_tree(tree):
    """device_get arrays; Tensors -> numpy (consolidates shardings)."""
    from ..tensor import Tensor

    def one(x):
        if isinstance(x, Tensor):
            x = x._value
        if isinstance(x, jax.Array):
            return np.asarray(jax.device_get(x))
        return x
    return jax.tree_util.tree_map(
        one, tree, is_leaf=lambda t: isinstance(t, Tensor))


class _ArrayRef:
    """Pickle-able placeholder marking an array's position in the state
    skeleton; `key` addresses the array in the orbax store."""
    __slots__ = ("key",)

    def __init__(self, key):
        self.key = key


def _split_arrays(state, refs_from=None):
    """(skeleton, arrays): skeleton is `state` with every jax.Array /
    Tensor leaf replaced by an _ArrayRef; arrays is a flat {key: jax.Array}
    dict (device-resident, shardings intact — nothing gathered).

    refs_from: an existing skeleton whose _ArrayRef positions dictate which
    leaves of `state` are treated as arrays (used for restore targets,
    where a leaf may be an abstract ShapeDtypeStruct)."""
    from ..tensor import Tensor

    unwrapped = jax.tree_util.tree_map(
        lambda t: t._value if isinstance(t, Tensor) else t, state,
        is_leaf=lambda t: isinstance(t, Tensor))
    counter = [0]
    arrays = {}

    def fresh(x):
        if isinstance(x, (jax.Array, jax.ShapeDtypeStruct)):
            key = f"a{counter[0]}"
            counter[0] += 1
            arrays[key] = x
            return _ArrayRef(key)
        return x

    def from_ref(x, ref):
        if isinstance(ref, _ArrayRef):
            arrays[ref.key] = x  # reuse the SAVED key so lookups line up
            return ref
        return x

    if refs_from is None:
        skeleton = jax.tree_util.tree_map(fresh, unwrapped)
    else:
        skeleton = jax.tree_util.tree_map(
            from_ref, unwrapped, refs_from,
            is_leaf=lambda t: isinstance(t, _ArrayRef))
    return skeleton, arrays


def _merge_arrays(skeleton, arrays):
    return jax.tree_util.tree_map(
        lambda x: arrays[x.key] if isinstance(x, _ArrayRef) else x,
        skeleton, is_leaf=lambda t: isinstance(t, _ArrayRef))


class CheckpointManager:
    """Rolling, optionally-async checkpoint directory:

        mgr = CheckpointManager("ckpts", keep_max=3, async_save=True)
        mgr.save(step, {"model": net.state_dict(), "opt": opt_state, ...},
                 metric=val_acc)
        ...
        state = mgr.restore()           # latest
        state = mgr.restore(best=True)  # best metric ever
    """

    def __init__(self, directory, keep_max=5, async_save=False,
                 mode="max", sharded=False):
        self.dir = os.path.abspath(str(directory))
        self.keep_max = keep_max
        self.async_save = async_save
        self.mode = mode
        self.sharded = sharded
        self._ckptr = None
        if sharded:
            import orbax.checkpoint  # noqa: F401  (fail fast if absent)
        os.makedirs(self.dir, exist_ok=True)
        self._lock = threading.Lock()
        self._pending: threading.Thread | None = None
        self._index = self._load_index()

    # -- index -------------------------------------------------------------
    def _index_path(self):
        return os.path.join(self.dir, "index.json")

    def _load_index(self):
        try:
            with open(self._index_path()) as f:
                idx = json.load(f)
        except (OSError, json.JSONDecodeError):
            return {"steps": [], "best_step": None, "best_metric": None,
                    "format": 2, "legacy_steps": []}
        if "format" not in idx:
            # index written before the COMPLETE-marker format: those
            # steps were finalized by the old atomic-rename contract,
            # so grandfather them — an upgrade must not silently turn
            # every existing checkpoint unrestorable
            idx["legacy_steps"] = list(idx.get("steps", []))
            idx["format"] = 2
        idx.setdefault("legacy_steps", [])
        return idx

    def _write_index(self):
        atomic.atomic_replace(self._index_path(),
                              json.dumps(self._index))

    def _step_dir(self, step):
        return os.path.join(self.dir, f"step_{step}")

    # -- save --------------------------------------------------------------
    def save(self, step, state, metric=None):
        """Snapshot `state` (any pytree: params/opt/lr/rng/scaler) at
        `step`.

        sharded=False: device arrays are fetched to host synchronously
        (cheap — they were about to be donated anyway); disk write happens
        on the background thread when async_save.
        sharded=True: jax.Array leaves are written per-shard by orbax with
        no host gather of the full tree; the write itself runs on the
        background thread when async_save (arrays are immutable, so the
        snapshot is consistent even while training continues — but see
        Engine donation: pass a non-donated copy or save before step)."""
        if self.sharded:
            skeleton, arrays = _split_arrays(state)
            self.wait()
            if self.async_save:
                self._pending = threading.Thread(
                    target=self._write_guarded,
                    args=(step, (skeleton, arrays), metric), daemon=True)
                self._pending.start()
            else:
                self._write(step, (skeleton, arrays), metric)
            return
        host = _host_tree(state)
        self.wait()  # one in-flight save at a time, like orbax
        if self.async_save:
            self._pending = threading.Thread(
                target=self._write_guarded, args=(step, host, metric),
                daemon=True)
            self._pending.start()
        else:
            self._write(step, host, metric)

    def _write_guarded(self, step, host_state, metric):
        try:
            self._write(step, host_state, metric)
        except BaseException as e:  # surfaced by the next wait()/save()
            self._error = e

    def _write(self, step, host_state, metric):
        d = self._step_dir(step)
        tmp = d + ".tmp"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp, exist_ok=True)
        if self.sharded:
            skeleton, arrays = host_state
            serialization.save(skeleton, os.path.join(tmp, "skeleton.pd"))
            ckptr = self._orbax()
            ckptr.save(os.path.join(tmp, "arrays"), arrays)
            ckptr.wait_until_finished()
        else:
            serialization.save(host_state,
                               os.path.join(tmp, "state.pdparams"))
        meta = {"step": step, "metric": metric, "time": time.time()}
        # plain write is safe HERE: meta.json lands inside the
        # unpublished <step>.tmp dir — nothing reads it until the
        # directory rename below publishes the whole artifact
        # tpulint: disable-next-line=DUR01
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump(meta, f)
        if os.path.exists(d):
            shutil.rmtree(d)
        # the DIRECTORY swap is itself the atomic publish — the
        # file-shaped atomic_replace helper doesn't apply, and the
        # durability claim is the COMPLETE marker _finalize() writes
        # (via io/atomic) strictly after this rename
        # tpulint: disable-next-line=DUR01
        os.replace(tmp, d)
        # crash-safe finalize: the COMPLETE marker lands strictly AFTER
        # the payload rename. A crash (or preemption deadline) anywhere
        # in _write leaves either no step dir, or a dir without the
        # marker — and restore/latest skip unmarked dirs instead of
        # loading a torn state file. The torn_ckpt injector simulates
        # exactly that crash: payload truncated, marker suppressed.
        from ..resilience import faults as _faults
        torn = _faults.pull("torn_ckpt", step)
        if torn is not None:
            state_file = os.path.join(
                d, "skeleton.pd" if self.sharded else "state.pdparams")
            keep = int(torn.get("keep_bytes",
                                os.path.getsize(state_file) // 2))
            with open(state_file, "r+b") as f:
                f.truncate(keep)
        else:
            self._finalize(d, step)
        with self._lock:
            idx = self._index
            if step not in idx["steps"]:
                idx["steps"].append(step)
                idx["steps"].sort()
            if metric is not None:
                better = (idx["best_metric"] is None
                          or (metric > idx["best_metric"]
                              if self.mode == "max"
                              else metric < idx["best_metric"]))
                if better:
                    idx["best_metric"] = metric
                    idx["best_step"] = step
            self._gc()
            self._write_index()

    def _gc(self):
        # retention counts FINALIZED checkpoints only: an unfinalized
        # (torn/crashed) dir is garbage, and letting it occupy a
        # keep_max slot could age out every restorable checkpoint —
        # the exact crash-safety the marker exists to provide
        idx = self._index
        final = [s for s in idx["steps"]
                 if self._finalized_unlocked(s)]
        keep = set(final[-self.keep_max:])
        if idx["best_step"] is not None \
                and self._finalized_unlocked(idx["best_step"]):
            keep.add(idx["best_step"])
        for s in list(idx["steps"]):
            if s not in keep:
                idx["steps"].remove(s)
                if s in idx.get("legacy_steps", ()):
                    idx["legacy_steps"].remove(s)
                shutil.rmtree(self._step_dir(s), ignore_errors=True)

    def wait(self):
        """Block until the in-flight async save lands (call before exit).
        Re-raises any error the background write hit — a checkpoint the
        caller believes exists must exist."""
        if self._pending is not None:
            self._pending.join()
            self._pending = None
        err = getattr(self, "_error", None)
        if err is not None:
            self._error = None
            raise RuntimeError("async checkpoint save failed") from err

    # -- finalize marker (shared discipline: io/atomic.py) -----------------
    _MARKER = atomic.MARKER_NAME

    def _marker_path(self, d):
        return os.path.join(d, self._MARKER)

    def _finalize(self, d, step):
        """Write the COMPLETE marker and make it durable (the shared
        io.atomic discipline — the fleet journal's segment rotation
        reuses the same helper). Only a dir carrying this marker is
        eligible for latest()/best()/restore — the contract that makes
        every save crash-safe."""
        atomic.write_marker(self._marker_path(d),
                            {"step": step, "time": time.time()})

    def _finalized_unlocked(self, step):
        return (os.path.exists(self._marker_path(self._step_dir(step)))
                or step in self._index.get("legacy_steps", ()))

    def is_finalized(self, step):
        with self._lock:
            return self._finalized_unlocked(step)

    # -- restore -----------------------------------------------------------
    def latest_step(self):
        """Newest FINALIZED step (unfinalized/torn dirs — a crash mid-
        save, a stale index entry — are skipped, not crashed on)."""
        with self._lock:
            steps = list(self._index["steps"])
        for s in reversed(steps):
            if self.is_finalized(s):
                return s
        return None

    def best_step(self):
        with self._lock:
            s = self._index["best_step"]
        return s if s is not None and self.is_finalized(s) else None

    def all_steps(self):
        with self._lock:
            return list(self._index["steps"])

    def finalized_steps(self):
        with self._lock:
            steps = list(self._index["steps"])
        return [s for s in steps if self.is_finalized(s)]

    def restore(self, step=None, best=False, target=None):
        """Load a snapshot (default: latest). Returns the saved pytree with
        numpy leaves, or None when the directory holds nothing usable.

        Resilience contract: with step=None, unfinalized dirs are never
        candidates, and a finalized-but-unreadable one (bit rot, manual
        tampering) is skipped with a warning, falling back to the next-
        older finalized step. An EXPLICIT step= asks for that exact
        payload, so its failures raise.

        sharded manager: `target` may be a pytree matching the saved state
        whose array leaves are jax.ShapeDtypeStruct(shape, dtype,
        sharding=NamedSharding(...)) (or live arrays to copy the spec
        from) — each restored array is then materialized directly onto its
        target sharding, shard by shard, never as one host copy."""
        self.wait()
        if best:
            step = self.best_step()
            if step is None:
                raise ValueError(
                    "restore(best=True) but no finalized checkpoint was "
                    "saved with a metric - pass metric= to save(), or "
                    "restore latest")
        if step is not None:
            return self._restore_one(step, target)
        last_err = None
        for s in reversed(self.finalized_steps()):
            try:
                return self._restore_one(s, target)
            except Exception as e:  # noqa: BLE001 — corrupt payload class
                last_err = e
                import warnings
                warnings.warn(
                    f"checkpoint step_{s} is finalized but unreadable "
                    f"({type(e).__name__}: {e}); falling back to an "
                    "older checkpoint")
        if last_err is not None:
            import warnings
            warnings.warn("no readable checkpoint found (all finalized "
                          "candidates failed to load)")
        return None

    def _restore_one(self, step, target):
        if self.sharded:
            return self._restore_sharded(step, target)
        return serialization.load(
            os.path.join(self._step_dir(step), "state.pdparams"),
            return_numpy=True)

    def _orbax(self):
        """One StandardCheckpointer per manager — constructing one per call
        leaks its async worker machinery over a long run."""
        if self._ckptr is None:
            import orbax.checkpoint as ocp
            self._ckptr = ocp.StandardCheckpointer()
        return self._ckptr

    def close(self):
        if self._ckptr is not None:
            self._ckptr.close()
            self._ckptr = None

    def _restore_sharded(self, step, target):
        d = self._step_dir(step)
        skeleton = serialization.load(os.path.join(d, "skeleton.pd"),
                                      return_numpy=False)
        ckptr = self._orbax()
        abstract = None
        if target is not None:
            _, tgt_arrays = _split_arrays(target, refs_from=skeleton)
            abstract = jax.tree_util.tree_map(
                lambda a: a if isinstance(a, jax.ShapeDtypeStruct)
                else jax.ShapeDtypeStruct(a.shape, a.dtype,
                                          sharding=a.sharding),
                tgt_arrays)
        arrays = ckptr.restore(os.path.join(d, "arrays"), abstract)
        return _merge_arrays(skeleton, arrays)
