"""ctypes bridge to the C++ IO runtime (csrc/libptio.so).

The native library provides a lock-free-ish ring buffer of pinned host
buffers (the TPU equivalent of the reference's shared-memory reader queue in
paddle/fluid/operators/reader/buffered_reader.cc). Python objects can't
cross the ctypes boundary, so the prefetcher stores numpy payloads in a
Python-side slot table and pushes slot ids through the native queue — the
native side provides the blocking/backpressure machinery.

Falls back to None (pure-python queue) when the .so isn't built.
"""
from __future__ import annotations

import ctypes
import os
import threading

_LIB = None
_TRIED = False


def _load():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for cand in (os.path.join(here, "..", "csrc", "build", "libptio.so"),
                 os.path.join(here, "lib", "libptio.so")):
        cand = os.path.abspath(cand)
        if os.path.exists(cand):
            try:
                lib = ctypes.CDLL(cand)
                lib.ptio_queue_create.restype = ctypes.c_void_p
                lib.ptio_queue_create.argtypes = [ctypes.c_int]
                lib.ptio_queue_push.argtypes = [ctypes.c_void_p, ctypes.c_long]
                lib.ptio_queue_push.restype = ctypes.c_int
                lib.ptio_queue_pop.argtypes = [ctypes.c_void_p]
                lib.ptio_queue_pop.restype = ctypes.c_long
                lib.ptio_queue_destroy.argtypes = [ctypes.c_void_p]
                _LIB = lib
                break
            except OSError:
                continue
    return _LIB


class NativePrefetcher:
    """Bounded queue whose blocking machinery lives in C++."""

    @classmethod
    def create(cls, depth):
        lib = _load()
        if lib is None:
            return None
        return cls(lib, depth)

    def __init__(self, lib, depth):
        self._lib = lib
        self._q = lib.ptio_queue_create(depth)
        self._slots = {}
        self._next = 0
        self._lock = threading.Lock()

    def put(self, item):
        with self._lock:
            sid = self._next
            self._next += 1
            self._slots[sid] = item
        self._lib.ptio_queue_push(self._q, sid)

    def get(self):
        sid = self._lib.ptio_queue_pop(self._q)
        with self._lock:
            return self._slots.pop(sid)

    def close(self):
        if self._q:
            self._lib.ptio_queue_destroy(self._q)
            self._q = None
