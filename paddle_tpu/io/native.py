"""ctypes bridge to the C++ IO runtime (csrc/libptio.so).

The native library provides the host-side runtime the reference implements
in C++ (paddle/fluid/operators/reader/buffered_reader.cc and the
shared-memory DataLoader queue): bounded blocking queues whose
wait/notify machinery runs outside the GIL, an aligned reusable buffer
pool for staging batches, and GIL-free memcpy/row-gather for collation.
Python objects can't cross the ctypes boundary, so the prefetcher stores
numpy payloads in a Python-side slot table and pushes slot ids through the
native queue.

Builds csrc/ automatically on first use when a compiler is available;
falls back to None (pure-python queue) otherwise.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_LIB = None
_TRIED = False
_PKG = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO = os.path.dirname(_PKG)
# source checkout build, or a prebuilt .so shipped inside the package
_CANDIDATES = (os.path.join(_REPO, "csrc", "build", "libptio.so"),
               os.path.join(_PKG, "lib", "libptio.so"))


def _build():
    src_dir = os.path.join(_REPO, "csrc")
    if not os.path.exists(os.path.join(src_dir, "ptio.cc")):
        return None
    try:
        r = subprocess.run(["make", "-C", src_dir], capture_output=True,
                           timeout=60, text=True)
    except Exception:
        return None
    so = _CANDIDATES[0]
    if r.returncode != 0 or not os.path.exists(so):
        import warnings
        warnings.warn("native IO build failed, using pure-python fallback:\n"
                      + (r.stderr or "")[-500:])
        return None
    return so


def _load():
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    so = next((c for c in _CANDIDATES if os.path.exists(c)), None) or _build()
    if so is None:
        return None
    try:
        lib = ctypes.CDLL(so)
    except OSError:
        return None
    lib.ptio_queue_create.restype = ctypes.c_void_p
    lib.ptio_queue_create.argtypes = [ctypes.c_int]
    lib.ptio_queue_push.argtypes = [ctypes.c_void_p, ctypes.c_long]
    lib.ptio_queue_push.restype = ctypes.c_int
    lib.ptio_queue_pop.argtypes = [ctypes.c_void_p]
    lib.ptio_queue_pop.restype = ctypes.c_long
    lib.ptio_queue_size.argtypes = [ctypes.c_void_p]
    lib.ptio_queue_size.restype = ctypes.c_int
    lib.ptio_queue_close.argtypes = [ctypes.c_void_p]
    lib.ptio_queue_destroy.argtypes = [ctypes.c_void_p]
    lib.ptio_pool_create.restype = ctypes.c_void_p
    lib.ptio_pool_create.argtypes = [ctypes.c_int, ctypes.c_size_t]
    lib.ptio_pool_acquire.restype = ctypes.c_void_p
    lib.ptio_pool_acquire.argtypes = [ctypes.c_void_p]
    lib.ptio_pool_release.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.ptio_pool_release.restype = ctypes.c_int
    lib.ptio_pool_buffer_bytes.argtypes = [ctypes.c_void_p]
    lib.ptio_pool_buffer_bytes.restype = ctypes.c_size_t
    lib.ptio_pool_close.argtypes = [ctypes.c_void_p]
    lib.ptio_pool_destroy.argtypes = [ctypes.c_void_p]
    lib.ptio_memcpy.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                ctypes.c_size_t]
    lib.ptio_gather_rows.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_void_p),
                                     ctypes.c_int, ctypes.c_size_t]
    _LIB = lib
    return _LIB


def native_available():
    return _load() is not None


class NativePrefetcher:
    """Bounded queue whose blocking machinery lives in C++ (outside the
    GIL). put() returns False once the queue is closed (consumer gone);
    get() returns the sentinel `NativePrefetcher.CLOSED` after close."""

    CLOSED = object()

    @classmethod
    def create(cls, depth):
        lib = _load()
        if lib is None:
            return None
        return cls(lib, depth)

    def __init__(self, lib, depth):
        self._lib = lib
        self._q = lib.ptio_queue_create(depth)
        self._slots = {}
        self._next = 0
        self._lock = threading.Lock()

    def put(self, item) -> bool:
        if self._q is None:
            return False
        with self._lock:
            sid = self._next
            self._next += 1
            self._slots[sid] = item
        if not self._lib.ptio_queue_push(self._q, sid):
            with self._lock:
                self._slots.pop(sid, None)
            return False
        return True

    def get(self):
        if self._q is None:
            return self.CLOSED
        sid = self._lib.ptio_queue_pop(self._q)
        if sid < 0:
            return self.CLOSED
        with self._lock:
            return self._slots.pop(sid)

    def close(self):
        """Wake every blocked producer/consumer; the queue stays alive so
        racing put/get calls stay safe. Call destroy() after joining all
        user threads to free the native object."""
        if self._q is not None:
            self._lib.ptio_queue_close(self._q)

    def destroy(self):
        """CONTRACT: no other thread may still call put/get (close first,
        then join) — the handle is freed here."""
        if self._q is not None:
            q, self._q = self._q, None
            self._lib.ptio_queue_destroy(q)


class BufferPool:
    """Aligned reusable staging buffers (ref: pinned-memory
    buffered_reader staging). acquire() -> (address, capacity_bytes)."""

    @classmethod
    def create(cls, n_buffers, nbytes):
        lib = _load()
        if lib is None:
            return None
        return cls(lib, n_buffers, nbytes)

    def __init__(self, lib, n_buffers, nbytes):
        self._lib = lib
        self._p = lib.ptio_pool_create(n_buffers, nbytes)
        self._nbytes = nbytes

    def acquire(self):
        if self._p is None:
            return None
        addr = self._lib.ptio_pool_acquire(self._p)
        return (addr, self._nbytes) if addr else None

    def release(self, addr):
        if self._p is not None:
            self._lib.ptio_pool_release(self._p, addr)

    def close(self):
        """Wake blocked acquirers; buffers stay valid until destroy()."""
        if self._p is not None:
            self._lib.ptio_pool_close(self._p)

    def destroy(self):
        """CONTRACT: no thread blocked in acquire, no buffer in use."""
        if self._p is not None:
            p, self._p = self._p, None
            self._lib.ptio_pool_destroy(p)


def gather_rows(samples, out=None, pool_addr=None):
    """Collate equal-shape C-contiguous numpy samples into one batch array
    with a single native gather (no Python-level copy loop).

    samples: list of np.ndarray with identical shape/dtype.
    out: optional preallocated [n, ...] array; pool_addr: optional raw
    staging address from BufferPool to gather into (returns a view)."""
    lib = _load()
    n = len(samples)
    first = np.ascontiguousarray(samples[0])
    row_bytes = first.nbytes
    shape = (n,) + first.shape
    rows = [np.ascontiguousarray(s) for s in samples]
    if lib is None:
        if out is not None:
            np.stack(rows, out=out)
            return out
        return np.stack(rows)
    ptrs = (ctypes.c_void_p * n)(
        *[r.ctypes.data_as(ctypes.c_void_p).value for r in rows])
    if pool_addr is not None:
        buf = (ctypes.c_char * (row_bytes * n)).from_address(pool_addr)
        batch = np.frombuffer(buf, dtype=first.dtype).reshape(shape)
        dst = pool_addr
    else:
        batch = out if out is not None else np.empty(shape, first.dtype)
        dst = batch.ctypes.data_as(ctypes.c_void_p)
    lib.ptio_gather_rows(dst, ptrs, n, row_bytes)
    return batch
