"""Data IO (ref: python/paddle/io/*)."""
from .dataset import (  # noqa: F401
    ChainDataset, ComposeDataset, ConcatDataset, Dataset, IterableDataset,
    Subset, TensorDataset, random_split,
)
from .sampler import (  # noqa: F401
    BatchSampler, DistributedBatchSampler, RandomSampler, Sampler,
    SequenceSampler, SubsetRandomSampler, WeightedRandomSampler,
)
from .dataloader import (  # noqa: F401
    DataLoader, default_collate_fn, default_convert_fn, device_prefetch,
    get_worker_info,
)
from .checkpoint import CheckpointManager  # noqa: F401
