"""Process-based DataLoader workers over POSIX shared memory.

ref parity: python/paddle/io/dataloader/worker.py (_worker_loop: worker
PROCESSES pull index batches from an index queue, write sample tensors
into shared memory, and push descriptors back) + the C++ shared-memory
queue of paddle/fluid/dataloader. Thread workers cannot feed a
TPU-rate consumer through GIL-heavy decode/augment Python; processes
sidestep the GIL entirely.

TPU-native shape of the same idea:
- workers are `spawn` processes (fork after jax/XLA initialisation is
  unsafe) running ONLY numpy/dataset code — jax is never imported in a
  worker;
- each result batch's arrays are written into one
  multiprocessing.shared_memory segment; only (name, shapes, dtypes)
  descriptors ride the control queue, so the parent never unpickles
  payload bytes — it maps the segment, copies out with one GIL-free
  memcpy, and unlinks immediately (no lifetime coupling to user code);
- an index queue bounds work-in-flight (prefetch backpressure), a
  reorder buffer restores determinism (ref: _task_info reordering in
  dataloader_iter.py), and dead workers are detected instead of
  hanging the consumer;
- the pool outlives an epoch when persistent_workers=True (tasks and
  results carry an epoch id; stale results are dropped and their
  segments freed);
- worker_init_fn / get_worker_info() match the reference contract.
"""
from __future__ import annotations

import multiprocessing as mp
import pickle
import queue as _queue
from multiprocessing import shared_memory

import numpy as np

__all__ = ["ProcessPrefetcher", "can_use_process_workers"]

_SENTINEL = None
_LIVENESS_POLL_S = 5.0


def _flatten_arrays(obj, out):
    """Split a collated batch into (template, [arrays]): arrays are
    replaced by positional placeholders so only metadata pickles."""
    if isinstance(obj, np.ndarray):
        out.append(obj)
        return _ArrRef(len(out) - 1)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_flatten_arrays(x, out) for x in obj)
    if isinstance(obj, dict):
        return {k: _flatten_arrays(v, out) for k, v in obj.items()}
    return obj


class _ArrRef:
    __slots__ = ("i",)

    def __init__(self, i):
        self.i = i


def _unflatten(obj, arrays):
    if isinstance(obj, _ArrRef):
        return arrays[obj.i]
    if isinstance(obj, (list, tuple)):
        return type(obj)(_unflatten(x, arrays) for x in obj)
    if isinstance(obj, dict):
        return {k: _unflatten(v, arrays) for k, v in obj.items()}
    return obj


def _worker_loop(dataset, collate_fn, index_q, result_q, worker_id,
                 num_workers, worker_init_fn, seed):
    from . import dataloader as _dl
    _dl._worker_info = _dl.WorkerInfo(
        id=worker_id, num_workers=num_workers, seed=seed + worker_id,
        dataset=dataset)
    # persistent workers keep this RNG state across epochs, so epoch
    # N+1's augmentations differ from epoch N's (same contract as the
    # reference's persistent pool)
    np.random.seed((seed + worker_id) % (2 ** 31))
    if worker_init_fn is not None:
        worker_init_fn(worker_id)
    while True:
        task = index_q.get()
        if task is _SENTINEL:
            return
        epoch, seq, indices = task
        try:
            batch = collate_fn([dataset[i] for i in indices])
            arrays = []
            template = _flatten_arrays(batch, arrays)
            total = sum(int(a.nbytes) for a in arrays)
            if total:
                shm = shared_memory.SharedMemory(create=True,
                                                 size=max(total, 1))
                off = 0
                descs = []
                for a in arrays:
                    a = np.ascontiguousarray(a)
                    shm.buf[off:off + a.nbytes] = \
                        a.view(np.uint8).reshape(-1).data
                    descs.append((off, a.shape, a.dtype.str))
                    off += a.nbytes
                name = shm.name
                shm.close()  # parent owns the segment lifetime now
            else:
                name, descs = None, []
            result_q.put((epoch, seq, None, (template, name, descs)))
        except BaseException as e:  # propagate to the parent loudly
            try:
                result_q.put((epoch, seq, pickle.dumps(e), None))
            except Exception:
                result_q.put((epoch, seq, pickle.dumps(
                    RuntimeError(f"worker {worker_id}: {e!r}")), None))


def _free_segment(name):
    if not name:
        return
    try:
        s = shared_memory.SharedMemory(name=name)
        s.close()
        s.unlink()
    except (FileNotFoundError, OSError):
        pass


def _map_result(template, name, descs):
    if name is None:
        return _unflatten(template, [])
    shm = shared_memory.SharedMemory(name=name)
    try:
        arrays = []
        for off, shape, dtype in descs:
            n = int(np.prod(shape)) * np.dtype(dtype).itemsize
            # one memcpy out of the segment (np.array releases the GIL
            # for the copy): the segment is then freed immediately,
            # with no lifetime coupling to escaping user arrays. At
            # TPU-feed rates this costs a few % of one core; the
            # decode/augment work the processes parallelize costs
            # hundreds of % — that is the trade.
            arrays.append(np.array(np.ndarray(
                shape, dtype, buffer=shm.buf[off:off + n])))
        return _unflatten(template, arrays)
    finally:
        shm.close()
        try:
            shm.unlink()
        except (FileNotFoundError, OSError):
            pass


def can_use_process_workers(dataset, collate_fn):
    """Process workers need a picklable dataset + collate (spawn)."""
    try:
        pickle.dumps(dataset)
        pickle.dumps(collate_fn)
        return True
    except Exception:
        return False


class ProcessPrefetcher:
    """A spawn-worker pool. `run_epoch(batches)` pulls index batches
    from `batches`, fans them out, and yields collated numpy batches
    IN ORDER. The pool survives across epochs (persistent_workers);
    call shutdown() when done."""

    def __init__(self, dataset, collate_fn, num_workers,
                 prefetch_factor=2, worker_init_fn=None, seed=0,
                 timeout=0):
        ctx = mp.get_context("spawn")
        self._index_q = ctx.Queue()
        self._result_q = ctx.Queue()
        self._timeout = float(timeout) or None
        self._procs = [
            ctx.Process(
                target=_worker_loop,
                args=(dataset, collate_fn, self._index_q, self._result_q,
                      w, num_workers, worker_init_fn, seed),
                daemon=True)
            for w in range(num_workers)]
        for p in self._procs:
            p.start()
        self._inflight_cap = max(2, num_workers * prefetch_factor)
        self._epoch = 0
        self._closed = False

    def _check_alive(self):
        dead = [p for p in self._procs if not p.is_alive()]
        if dead:
            codes = [p.exitcode for p in dead]
            self.shutdown()
            raise RuntimeError(
                f"{len(dead)} DataLoader worker process(es) died "
                f"unexpectedly (exit codes {codes}) — commonly the OOM "
                "killer on oversized batches; reduce batch_size or "
                "num_workers")

    def _get_result(self):
        """result_q.get with liveness polling: a dead worker raises
        instead of hanging the consumer forever."""
        import time
        deadline = (time.monotonic() + self._timeout
                    if self._timeout else None)
        while True:
            poll = _LIVENESS_POLL_S
            if deadline is not None:
                poll = min(poll, max(0.1, deadline - time.monotonic()))
            try:
                return self._result_q.get(timeout=poll)
            except _queue.Empty:
                self._check_alive()
                if deadline is not None and time.monotonic() >= deadline:
                    self.shutdown()
                    raise TimeoutError(
                        f"DataLoader worker result not ready within "
                        f"timeout={self._timeout}s")

    def run_epoch(self, batches):
        if self._closed:
            raise RuntimeError("ProcessPrefetcher already shut down")
        epoch = self._epoch = self._epoch + 1
        batches = enumerate(batches)
        # out-of-order results land here; payloads are freed on ANY
        # exit path (early break / worker error) via the finally
        pending = self._pending = {}
        inflight = 0
        next_seq = 0
        exhausted = False
        try:
            while True:
                while inflight < self._inflight_cap and not exhausted:
                    try:
                        seq, idxs = next(batches)
                    except StopIteration:
                        exhausted = True
                        break
                    self._index_q.put((epoch, seq, list(idxs)))
                    inflight += 1
                if inflight == 0:
                    return
                while next_seq not in pending:
                    r_epoch, seq, err, payload = self._get_result()
                    if r_epoch != epoch:  # abandoned earlier epoch
                        if err is None and payload:
                            _free_segment(payload[1])
                        continue
                    pending[seq] = (err, payload)
                err, payload = pending.pop(next_seq)
                next_seq += 1
                inflight -= 1
                if err is not None:
                    raise pickle.loads(err)
                batch = _map_result(*payload)
                payload = None
                yield batch
        finally:
            for err, payload in pending.values():
                if err is None and payload:
                    _free_segment(payload[1])
            pending.clear()

    def shutdown(self):
        if self._closed:
            return
        self._closed = True
        for _ in self._procs:
            try:
                self._index_q.put(_SENTINEL)
            except Exception:
                pass
        for p in self._procs:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()
        # drain any landed-but-unconsumed segments so they don't leak
        try:
            while True:
                _, _, err, payload = self._result_q.get_nowait()
                if err is None and payload:
                    _free_segment(payload[1])
        except (_queue.Empty, OSError, ValueError):
            pass
