"""Crash-safe file-write discipline — ONE implementation.

Every durable artifact in this repo (checkpoints, flight-recorder
dumps, the fleet router's write-ahead journal segments) follows the
same three rules, extracted here so the discipline cannot drift
between subsystems:

- **atomic replace**: payload bytes land in a ``<path>.tmp`` sibling,
  are fsynced, and only then ``os.replace``d onto the final name — a
  reader can observe the old file or the new file, never a torn one.
  The parent directory is fsynced after the rename so the *name*
  itself survives a power cut (best-effort on filesystems that
  refuse directory fds).
- **COMPLETE marker**: multi-file artifacts (checkpoint step dirs,
  journal segments) additionally write a small marker file strictly
  AFTER the payload is in place; consumers treat only marked
  artifacts as finalized, so a crash at ANY byte of a save costs that
  save, never the ability to read an older one
  (docs/robustness.md "Crash-safe checkpoints").
- **never clobber**: postmortem artifacts (flight dumps) pick a fresh
  numbered name instead of overwriting an earlier incident's record.

Stdlib-only by contract: paddle_tpu.observability.flightrec loads
this module straight from its file in lean bench workers (see
bench._obs_mod), so nothing here may import jax, numpy, or any
sibling package.
"""
from __future__ import annotations

import json
import os

__all__ = ["MARKER_NAME", "atomic_replace", "fsync_dir", "marker_path",
           "publish_dir", "unique_path", "write_marker"]

#: canonical marker filename for directory-shaped artifacts
#: (checkpoint step dirs); file-shaped artifacts (journal segments)
#: use ``<file>.complete`` sidecars via marker_path().
MARKER_NAME = "COMPLETE"


def fsync_dir(path):
    """Best-effort fsync of a DIRECTORY, making a just-renamed entry
    durable. Some filesystems (and some containerized mounts) refuse
    O_DIRECTORY opens — the rename itself is still atomic there, so
    failure is swallowed, not raised."""
    try:
        fd = os.open(path, os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
    except OSError:
        return False
    try:
        os.fsync(fd)
        return True
    except OSError:
        return False
    finally:
        os.close(fd)


def atomic_replace(path, data, fsync=True):
    """Write `data` (bytes or str) to `path` atomically: tmp sibling,
    optional fsync, os.replace, parent-dir fsync. Returns `path`.
    A crash anywhere leaves either the previous file or the new one —
    never a prefix."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(data)
        if fsync:
            f.flush()
            os.fsync(f.fileno())
    os.replace(tmp, path)
    if fsync:
        fsync_dir(os.path.dirname(os.path.abspath(path)))
    return path


def marker_path(target):
    """The COMPLETE-marker path for an artifact: ``<dir>/COMPLETE``
    for a directory, ``<file>.complete`` sidecar for a file."""
    if os.path.isdir(target):
        return os.path.join(target, MARKER_NAME)
    return target + ".complete"


def write_marker(path, meta=None, fsync=True):
    """Write a finalize marker at `path` (use marker_path() to derive
    it) carrying `meta` as JSON. fsynced by default — the marker IS
    the durability claim, so it must not itself be lost to a cut."""
    with open(path, "w") as f:
        json.dump(meta or {}, f)
        if fsync:
            f.flush()
            os.fsync(f.fileno())
    if fsync:
        fsync_dir(os.path.dirname(os.path.abspath(path)))
    return path


def has_marker(target):
    return os.path.exists(marker_path(target))


def publish_dir(staging, final, fsync=True):
    """Atomically publish a fully-staged DIRECTORY artifact: fsync
    every regular file in `staging` (a crash after the rename must not
    reveal torn payload bytes under the final name), rename it onto
    `final`, fsync the parent, then write the COMPLETE marker strictly
    last. A crash at ANY point leaves either no `final` entry or an
    unmarked one — consumers that require the marker (has_marker) can
    never load a half-written artifact. `final` must not already
    exist (callers stage into a sibling and pick fresh names; this is
    the never-clobber rule for directory artifacts). Returns `final`.
    """
    if fsync:
        for base, _dirs, files in os.walk(staging):
            for name in files:
                fd = os.open(os.path.join(base, name), os.O_RDONLY)
                try:
                    os.fsync(fd)
                finally:
                    os.close(fd)
            fsync_dir(base)
    os.rename(staging, final)
    parent = os.path.dirname(os.path.abspath(final))
    if fsync:
        fsync_dir(parent)
    write_marker(marker_path(final), {"published": True}, fsync=fsync)
    return final


def unique_path(directory, stem, ext=".json"):
    """A fresh ``<dir>/<stem><ext>`` that never clobbers an existing
    file (numeric ``_2``, ``_3``... suffixes). `stem` is sanitized to
    [alnum - _] so an arbitrary reason string cannot escape the dir."""
    safe = "".join(c if (c.isalnum() or c in "-_") else "_"
                   for c in str(stem)) or "unknown"
    path = os.path.join(directory, f"{safe}{ext}")
    n = 2
    while os.path.exists(path):
        path = os.path.join(directory, f"{safe}_{n}{ext}")
        n += 1
    return path
