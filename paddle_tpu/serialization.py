"""paddle.save / paddle.load parity (ref: python/paddle/framework/io.py).

State dicts are stored as an .npz (arrays) plus a pickled structure skeleton
— same role as .pdparams. Nested dicts/lists, Tensors, scalars and LR
scheduler states round-trip.
"""
from __future__ import annotations

import io
import os
import pickle

import jax.numpy as jnp
import numpy as np

from .tensor import Tensor

_MAGIC = b"PTPU1\n"


def _pack(obj, arrays, path=""):
    if isinstance(obj, Tensor):
        key = f"t{len(arrays)}"
        arrays[key] = np.asarray(obj._value)
        return {"__tensor__": key,
                "stop_gradient": obj.stop_gradient}
    if isinstance(obj, (np.ndarray, jnp.ndarray)):
        key = f"t{len(arrays)}"
        arrays[key] = np.asarray(obj)
        return {"__ndarray__": key}
    if isinstance(obj, dict):
        return {"__dict__": {k: _pack(v, arrays) for k, v in obj.items()}}
    if isinstance(obj, (list, tuple)):
        return {"__seq__": [_pack(v, arrays) for v in obj],
                "tuple": isinstance(obj, tuple)}
    return {"__leaf__": obj}


def _unpack(spec, arrays, return_numpy=False):
    if "__tensor__" in spec:
        arr = arrays[spec["__tensor__"]]
        if return_numpy:
            return arr
        return Tensor(jnp.asarray(arr), stop_gradient=spec.get("stop_gradient", True))
    if "__ndarray__" in spec:
        return arrays[spec["__ndarray__"]]
    if "__dict__" in spec:
        return {k: _unpack(v, arrays, return_numpy)
                for k, v in spec["__dict__"].items()}
    if "__seq__" in spec:
        seq = [_unpack(v, arrays, return_numpy) for v in spec["__seq__"]]
        return tuple(seq) if spec.get("tuple") else seq
    return spec["__leaf__"]


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    arrays = {}
    spec = _pack(obj, arrays)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    with open(path, "wb") as f:
        f.write(_MAGIC)
        f.write(pickle.dumps(spec, protocol=protocol))
        f.write(b"\n__NPZ__\n")
        f.write(buf.getvalue())


def load(path, return_numpy=False, **configs):
    # sniff the header, then keep reading from the SAME handle
    with open(path, "rb") as f:
        head = f.read(len(_MAGIC))
        if not head.startswith(_MAGIC):
            if head[:1] == b"\x80":
                # a plain pickle: a reference-framework .pdparams/.pdopt
                # checkpoint — delegate to the compat reader so
                # paddle.load("model.pdparams") parity is real
                from .compat import load_pdparams
                return load_pdparams(path, return_numpy=return_numpy)
            raise ValueError(f"{path} is not a paddle_tpu checkpoint")
        body = f.read()
    sep = b"\n__NPZ__\n"
    idx = body.index(sep)
    spec = pickle.loads(body[:idx])
    arrays = dict(np.load(io.BytesIO(body[idx + len(sep):]), allow_pickle=False))
    return _unpack(spec, arrays, return_numpy=return_numpy)
