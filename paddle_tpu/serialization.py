"""paddle.save / paddle.load parity (ref: python/paddle/framework/io.py).

State dicts are stored as an .npz (arrays) plus a pickled structure skeleton
— same role as .pdparams. Nested dicts/lists, Tensors, scalars and LR
scheduler states round-trip.
"""
from __future__ import annotations

import io
import os
import pickle

import jax.numpy as jnp
import numpy as np

from .tensor import Tensor

_MAGIC = b"PTPU1\n"

# npz only round-trips native numpy dtypes: ml_dtypes arrays (bfloat16,
# float8_*) reload as void ("|V2") which JAX rejects — store their raw
# bits under a same-width uint view plus a dtype tag instead
try:
    import ml_dtypes as _mld
    _EXT_DTYPES = {}
    for _n in ("bfloat16", "float8_e4m3fn", "float8_e5m2",
               "float8_e4m3b11fnuz", "int4", "uint4"):
        try:
            _dt = np.dtype(getattr(_mld, _n))
            _EXT_DTYPES[_dt] = np.dtype(f"uint{8 * _dt.itemsize}")
        except (AttributeError, TypeError):
            pass
except ImportError:  # pragma: no cover
    _mld = None
    _EXT_DTYPES = {}


def _store_array(a, arrays):
    key = f"t{len(arrays)}"
    bits = _EXT_DTYPES.get(a.dtype)
    if bits is not None:
        # .reshape: numpy's view() promotes 0-d arrays of user-defined
        # dtypes to (1,) — pin the original shape
        arrays[key] = np.ascontiguousarray(a).view(bits).reshape(a.shape)
        return key, a.dtype.name
    arrays[key] = a
    return key, None


def _restore_array(arr, dtype_name):
    if dtype_name is not None:
        dt = np.dtype(getattr(_mld, dtype_name))
        return arr.view(dt).reshape(arr.shape)
    return arr


def _pack(obj, arrays, path=""):
    if isinstance(obj, Tensor):
        key, ext = _store_array(np.asarray(obj._value), arrays)
        spec = {"__tensor__": key,
                "stop_gradient": obj.stop_gradient}
        if ext:
            spec["dtype"] = ext
        return spec
    if isinstance(obj, (np.ndarray, jnp.ndarray)):
        key, ext = _store_array(np.asarray(obj), arrays)
        spec = {"__ndarray__": key}
        if ext:
            spec["dtype"] = ext
        return spec
    if isinstance(obj, dict):
        return {"__dict__": {k: _pack(v, arrays) for k, v in obj.items()}}
    if isinstance(obj, (list, tuple)):
        return {"__seq__": [_pack(v, arrays) for v in obj],
                "tuple": isinstance(obj, tuple)}
    return {"__leaf__": obj}


def _unpack(spec, arrays, return_numpy=False):
    if "__tensor__" in spec:
        arr = _restore_array(arrays[spec["__tensor__"]], spec.get("dtype"))
        if return_numpy:
            return arr
        return Tensor(jnp.asarray(arr), stop_gradient=spec.get("stop_gradient", True))
    if "__ndarray__" in spec:
        return _restore_array(arrays[spec["__ndarray__"]], spec.get("dtype"))
    if "__dict__" in spec:
        return {k: _unpack(v, arrays, return_numpy)
                for k, v in spec["__dict__"].items()}
    if "__seq__" in spec:
        seq = [_unpack(v, arrays, return_numpy) for v in spec["__seq__"]]
        return tuple(seq) if spec.get("tuple") else seq
    return spec["__leaf__"]


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    arrays = {}
    spec = _pack(obj, arrays)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    with open(path, "wb") as f:
        f.write(_MAGIC)
        f.write(pickle.dumps(spec, protocol=protocol))
        f.write(b"\n__NPZ__\n")
        f.write(buf.getvalue())


def load(path, return_numpy=False, **configs):
    # sniff the header, then keep reading from the SAME handle
    with open(path, "rb") as f:
        head = f.read(len(_MAGIC))
        if not head.startswith(_MAGIC):
            if head[:1] == b"\x80":
                # a plain pickle: a reference-framework .pdparams/.pdopt
                # checkpoint — delegate to the compat reader so
                # paddle.load("model.pdparams") parity is real
                from .compat import load_pdparams
                return load_pdparams(path, return_numpy=return_numpy)
            raise ValueError(f"{path} is not a paddle_tpu checkpoint")
        body = f.read()
    sep = b"\n__NPZ__\n"
    idx = body.index(sep)
    spec = pickle.loads(body[:idx])
    arrays = dict(np.load(io.BytesIO(body[idx + len(sep):]), allow_pickle=False))
    return _unpack(spec, arrays, return_numpy=return_numpy)


def load_into(model, path, strict=True):
    """Load a checkpoint file into a Layer: sniffs both paddle_tpu saves
    and reference-framework .pdparams pickles (compat path). strict
    refuses a partial load — missing parameters would silently stay at
    their prior values. The check runs BEFORE any mutation, so a
    refused load leaves the model untouched. Returns (missing,
    unexpected) key lists."""
    state = load(str(path))
    if isinstance(state, dict) and set(state) >= {"params"} and \
            all(k in ("params", "buffers", "specs") for k in state):
        state = {**state.get("params", {}), **state.get("buffers", {})}
    if strict:
        missing = [k for k in model.state_dict() if k not in state]
        if missing:
            raise ValueError(
                f"checkpoint {path} is missing parameters "
                f"{missing[:8]}{'...' if len(missing) > 8 else ''} — "
                "refusing a partial load (it would silently mix prior "
                "and pretrained weights); pass strict=False to allow")
    return model.set_state_dict(state)
