"""Sparse 3-D convolutions over voxel grids
(ref: python/paddle/sparse/nn/layer/conv.py Conv3D / SubmConv3D, NDHWC
SparseCooTensor inputs with dense channel values).

TPU-native design: the reference's GPU rulebook (hash-table neighbor
search feeding gather-GEMM-scatter CUDA kernels) splits naturally here —
the irregular index work builds a HOST-side numpy rulebook over the
concrete COO coordinates (exactly where spconv/torchsparse build theirs
on CPU), and the FLOP-heavy part runs on device as one gather + matmul +
scatter-add per kernel offset, which XLA maps onto the MXU. The rulebook
is data-dependent, so these layers are eager ops (like every COO
constructor in this package); the per-offset matmuls are jnp and fully
differentiable w.r.t. values, weight, and bias.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..nn.layer import Layer
from ..tensor import Tensor
from ..autograd import apply_op


def _triple(v):
    if isinstance(v, (tuple, list)):
        if len(v) != 3:
            raise ValueError(f"expected 3 values, got {v}")
        return tuple(int(x) for x in v)
    return (int(v),) * 3


def _rulebook_subm(coords, offsets):
    """Submanifold: outputs sit exactly on the input sites; offset k
    contributes input site (p + off_k) to output site p when present."""
    table = {tuple(c): i for i, c in enumerate(map(tuple, coords))}
    pairs = []
    for off in offsets:
        out_rows, in_rows = [], []
        for i, c in enumerate(coords):
            nb = (c[0], c[1] + off[0], c[2] + off[1], c[3] + off[2])
            j = table.get(nb)
            if j is not None:
                out_rows.append(i)
                in_rows.append(j)
        pairs.append((np.asarray(out_rows, np.int32),
                      np.asarray(in_rows, np.int32)))
    return coords, pairs


def _rulebook_full(coords, offsets, stride, padding, spatial):
    """Standard sparse conv: an input site feeds every output site o
    with i = o*stride - pad + off; the active output set is derived
    from the inputs (any site receiving >= 1 contribution)."""
    out_spatial = tuple(
        (spatial[a] + 2 * padding[a] - (offsets[-1][a] + 1)) // stride[a]
        + 1 for a in range(3))
    out_table = {}
    out_coords = []
    buckets = [([], []) for _ in offsets]   # one pass, no k3^2 rescan
    for i, c in enumerate(coords):
        for k, off in enumerate(offsets):
            num = (c[1] + padding[0] - off[0], c[2] + padding[1] - off[1],
                   c[3] + padding[2] - off[2])
            if any(n % s for n, s in zip(num, stride)):
                continue
            o = tuple(n // s for n, s in zip(num, stride))
            if any(v < 0 or v >= m for v, m in zip(o, out_spatial)):
                continue
            key = (c[0],) + o
            j = out_table.get(key)
            if j is None:
                j = out_table[key] = len(out_coords)
                out_coords.append(key)
            buckets[k][0].append(j)
            buckets[k][1].append(i)
    pairs = [(np.asarray(oi, np.int32), np.asarray(ii, np.int32))
             for oi, ii in buckets]
    return (np.asarray(out_coords, np.int64).reshape(-1, 4), pairs,
            out_spatial)


class _SparseConvBase(Layer):
    SUBM = False

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__()
        if groups != 1:
            raise NotImplementedError(
                "sparse conv groups != 1 is not supported")
        if data_format != "NDHWC":
            raise ValueError("sparse convs are NDHWC (reference layout)")
        self.in_channels = int(in_channels)
        self.out_channels = int(out_channels)
        self.kernel_size = _triple(kernel_size)
        self.stride = _triple(stride)
        self.padding = _triple(padding)
        self.dilation = _triple(dilation)
        if self.SUBM and self.stride != (1, 1, 1):
            raise ValueError(
                "SubmConv3D requires stride 1 (outputs live on the "
                "input sites)")
        from ..nn.initializer import XavierUniform
        self.weight = self.create_parameter(
            self.kernel_size + (self.in_channels, self.out_channels),
            attr=weight_attr,
            default_initializer=None if weight_attr else XavierUniform())
        if bias_attr is False:
            self.bias = None
            self._parameters["bias"] = None
        else:
            self.bias = self.create_parameter(
                (self.out_channels,), attr=bias_attr, is_bias=True)
        self._rb_cache = {}

    def _offsets(self):
        kd, kh, kw = self.kernel_size
        dd, dh, dw = self.dilation
        # centered for subm (outputs on input sites), origin-based for
        # full conv (the i = o*stride - pad + off convention)
        if self.SUBM:
            return [((d - kd // 2) * dd, (h - kh // 2) * dh,
                     (w - kw // 2) * dw)
                    for d in range(kd) for h in range(kh)
                    for w in range(kw)]
        return [(d * dd, h * dh, w * dw)
                for d in range(kd) for h in range(kh) for w in range(kw)]

    def forward(self, x):
        from . import SparseCooTensor, sparse_coo_tensor
        if not isinstance(x, SparseCooTensor):
            raise TypeError("sparse conv expects a SparseCooTensor")
        shape = tuple(int(s) for s in x.shape)
        if len(shape) != 5 or shape[-1] != self.in_channels:
            raise ValueError(
                f"expected [N, D, H, W, {self.in_channels}] input, got "
                f"{shape}")
        coords = np.asarray(x._bcoo.indices)            # [nnz, 4]
        if len(coords) != len({tuple(c) for c in coords.tolist()}):
            # duplicate sites would make the subm rulebook read only the
            # last duplicate (and full conv double-count); the reference
            # requires coalesced inputs too
            raise ValueError(
                "sparse conv input has duplicate coordinates — call "
                ".coalesce() first")
        offsets = self._offsets()
        spatial = shape[1:4]
        cache_key = (coords.tobytes(), spatial)
        cached = self._rb_cache.get(cache_key)
        if cached is None:
            # rulebook construction is host-side Python; identical
            # coordinates across steps (deep backbones, repeated
            # batches) reuse it — spconv's indice_key, keyed by content
            if self.SUBM:
                out_coords, pairs = _rulebook_subm(coords, offsets)
                out_spatial = spatial
            else:
                out_coords, pairs, out_spatial = _rulebook_full(
                    coords, offsets, self.stride, self.padding, spatial)
            if len(self._rb_cache) > 8:
                self._rb_cache.clear()
            self._rb_cache[cache_key] = (out_coords, pairs, out_spatial)
        else:
            out_coords, pairs, out_spatial = cached
        n_out = len(out_coords)
        k3 = len(offsets)

        def f(v, w, *maybe_b):
            wk = w.reshape((k3, self.in_channels, self.out_channels))
            out = jnp.zeros((n_out, self.out_channels), v.dtype)
            for k, (oi, ii) in enumerate(pairs):
                if len(oi) == 0:
                    continue
                out = out.at[oi].add(v[ii] @ wk[k])
            if maybe_b:
                out = out + maybe_b[0]
            return out

        args = [x.values(), self.weight]   # tape-linked when upstream
        if self.bias is not None:          # was a differentiable op
            args.append(self.bias)
        out_vals = apply_op(f, *args)
        out_shape = (shape[0],) + tuple(out_spatial) + (self.out_channels,)
        out = sparse_coo_tensor(
            np.asarray(out_coords).T, out_vals, out_shape)
        out._values_t = out_vals
        return out

    def extra_repr(self):
        return (f"in={self.in_channels}, out={self.out_channels}, "
                f"kernel={self.kernel_size}, stride={self.stride}, "
                f"padding={self.padding}, subm={self.SUBM}")


class Conv3D(_SparseConvBase):
    """ref: paddle.sparse.nn.Conv3D — standard sparse conv (the active
    set dilates by the kernel support)."""

    SUBM = False


class SubmConv3D(_SparseConvBase):
    """ref: paddle.sparse.nn.SubmConv3D — submanifold conv: outputs
    only on input sites, so sparsity never dilates through depth (the
    property that makes deep point-cloud backbones feasible)."""

    SUBM = True
