"""paddle.sparse parity subset (ref: python/paddle/sparse/__init__.py).

TPU-native design: sparse tensors wrap `jax.experimental.sparse.BCOO` —
XLA's batched-COO format whose matmuls lower to gather/segment-sum (and,
for structured patterns, MXU-friendly dots). COO and CSR constructors are
supported; CSR converts to BCOO internally and keeps its compressed attrs
for API parity. Elementwise ops act on `values` only (zero-preserving ops,
like the reference). 3-D point-cloud convs (Conv3D/SubmConv3D)
build a host-side rulebook over the concrete COO coordinates (the
spconv/torchsparse recipe) and run gather-matmul-scatter on device —
see conv.py.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..autograd import apply_op
from ..tensor import Tensor, to_tensor

__all__ = [
    "sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
    "SparseCsrTensor", "is_sparse", "is_sparse_coo", "is_sparse_csr",
    "add", "subtract", "multiply", "divide", "matmul", "masked_matmul",
    "relu", "tanh", "sqrt", "sin", "abs", "pow", "neg", "cast",
    "transpose", "coalesce", "nn",
]


def _arr(x):
    if isinstance(x, Tensor):
        return x._value
    return jnp.asarray(x)


class SparseCooTensor:
    """COO sparse tensor over BCOO (ref: paddle's SparseCooTensor)."""

    def __init__(self, bcoo, values_t=None):
        self._bcoo = bcoo
        # optional tape-linked values Tensor: ops that produce this
        # sparse tensor from a differentiable computation store their
        # output Tensor here so eager backward chains THROUGH stacked
        # sparse ops (the raw BCOO data array carries no tape link)
        self._values_t = values_t

    # -- paddle surface -------------------------------------------------
    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    def indices(self):
        return Tensor(self._bcoo.indices.T)  # paddle: [ndim, nnz]

    def values(self):
        if self._values_t is not None:
            return self._values_t
        return Tensor(self._bcoo.data)

    def nnz(self):
        return int(self._bcoo.nse)

    def to_dense(self):
        # self.values() (not a fresh Tensor) so the tape link survives:
        # conv(x).to_dense().sum().backward() must reach the weights
        return apply_op(lambda d: jsparse.BCOO(
            (d, self._bcoo.indices), shape=self._bcoo.shape).todense(),
            self.values())

    def to_sparse_csr(self):
        dense = np.asarray(self.to_dense()._value)
        return _dense_to_csr(dense)

    def coalesce(self):
        out = SparseCooTensor(self._bcoo.sum_duplicates())
        if self._values_t is not None:
            # re-derive the summed values differentiably off the tape
            uniq = out._bcoo.indices
            inv = {tuple(c): i for i, c in
                   enumerate(np.asarray(uniq).tolist())}
            seg = np.asarray([inv[tuple(c)] for c in
                              np.asarray(self._bcoo.indices).tolist()],
                             np.int32)
            n_out = int(uniq.shape[0])
            out._values_t = apply_op(
                lambda v: jnp.zeros((n_out,) + v.shape[1:],
                                    v.dtype).at[seg].add(v),
                self.values())
        return out

    def with_values(self, values):
        out = SparseCooTensor(jsparse.BCOO(
            (_arr(values), self._bcoo.indices), shape=self._bcoo.shape))
        if isinstance(values, Tensor):
            out._values_t = values   # every producer keeps the tape link
        return out

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


class SparseCsrTensor(SparseCooTensor):
    """CSR view (ref: paddle's SparseCsrTensor): keeps crows/cols for API
    parity, computes on the BCOO equivalent."""

    def __init__(self, bcoo, crows, cols):
        super().__init__(bcoo)
        self._crows = jnp.asarray(crows)
        self._cols = jnp.asarray(cols)

    def crows(self):
        return Tensor(self._crows)

    def cols(self):
        return Tensor(self._cols)

    def to_sparse_coo(self, sparse_dim=None):
        return SparseCooTensor(self._bcoo)

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


def _dense_to_csr(dense):
    rows, cols = np.nonzero(dense)
    vals = dense[rows, cols]
    n = dense.shape[0]
    crows = np.zeros(n + 1, np.int64)
    for r in rows:
        crows[r + 1] += 1
    crows = np.cumsum(crows)
    idx = np.stack([rows, cols], -1)
    bcoo = jsparse.BCOO((jnp.asarray(vals), jnp.asarray(idx)),
                        shape=dense.shape)
    return SparseCsrTensor(bcoo, crows, cols)


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    """ref: paddle.sparse.sparse_coo_tensor — indices [ndim, nnz]."""
    idx = np.asarray(_arr(to_tensor(indices))).astype(np.int32)
    vals = _arr(to_tensor(values))
    if dtype is not None:
        from ..framework import convert_dtype
        vals = vals.astype(convert_dtype(dtype))
    if shape is None:
        shape = tuple(int(m) + 1 for m in idx.max(axis=1))
    bcoo = jsparse.BCOO((vals, jnp.asarray(idx.T)), shape=tuple(shape))
    return SparseCooTensor(bcoo)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    """ref: paddle.sparse.sparse_csr_tensor (2-D)."""
    crows_np = np.asarray(_arr(to_tensor(crows))).astype(np.int64)
    cols_np = np.asarray(_arr(to_tensor(cols))).astype(np.int64)
    vals = _arr(to_tensor(values))
    if dtype is not None:
        from ..framework import convert_dtype
        vals = vals.astype(convert_dtype(dtype))
    rows = np.repeat(np.arange(len(crows_np) - 1), np.diff(crows_np))
    idx = np.stack([rows, cols_np], -1)
    bcoo = jsparse.BCOO((vals, jnp.asarray(idx.astype(np.int32))),
                        shape=tuple(shape))
    return SparseCsrTensor(bcoo, crows_np, cols_np)


def is_sparse(x):
    return isinstance(x, SparseCooTensor)


def is_sparse_coo(x):
    return isinstance(x, SparseCooTensor) and not isinstance(
        x, SparseCsrTensor)


def is_sparse_csr(x):
    return isinstance(x, SparseCsrTensor)


# ---------------------------------------------------------------------------
# elementwise (zero-preserving unary ops act on values; binary ops require
# matching sparsity like the reference)
# ---------------------------------------------------------------------------
def _unary(fn, x):
    if not isinstance(x, SparseCooTensor):
        raise TypeError("expected a sparse tensor")
    return x.with_values(apply_op(fn, x.values()))


def relu(x, name=None):
    return _unary(jax.nn.relu, x)


def tanh(x, name=None):
    return _unary(jnp.tanh, x)


def sqrt(x, name=None):
    return _unary(jnp.sqrt, x)


def sin(x, name=None):
    return _unary(jnp.sin, x)


def abs(x, name=None):  # noqa: A001
    return _unary(jnp.abs, x)


def neg(x, name=None):
    return _unary(jnp.negative, x)


def pow(x, factor, name=None):  # noqa: A001
    return _unary(lambda v: jnp.power(v, factor), x)


def cast(x, index_dtype=None, value_dtype=None):
    vals = x._bcoo.data
    if value_dtype is not None:
        from ..framework import convert_dtype
        vals = vals.astype(convert_dtype(value_dtype))
    return x.with_values(vals)


def _binary(fn, x, y):
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        # same-pattern fast path; general case goes dense->sparse
        if (x._bcoo.indices.shape == y._bcoo.indices.shape
                and bool(jnp.all(x._bcoo.indices == y._bcoo.indices))):
            return x.with_values(fn(x._bcoo.data, y._bcoo.data))
        out = fn(_arr(x.to_dense()), _arr(y.to_dense()))
        return _from_dense_coo(out)
    raise TypeError("sparse binary ops expect two sparse tensors")


def _from_dense_coo(dense):
    d = np.asarray(dense)
    idx = np.stack(np.nonzero(d), 0)
    return sparse_coo_tensor(idx, d[tuple(idx)], d.shape)


def add(x, y, name=None):
    return _binary(jnp.add, x, y)


def subtract(x, y, name=None):
    return _binary(jnp.subtract, x, y)


def multiply(x, y, name=None):
    return _binary(jnp.multiply, x, y)


def divide(x, y, name=None):
    return _binary(jnp.divide, x, y)


# ---------------------------------------------------------------------------
# matmul
# ---------------------------------------------------------------------------
def matmul(x, y, name=None):
    """ref: paddle.sparse.matmul — sparse @ dense -> dense (grads flow
    through the dense operand and the sparse values)."""
    if isinstance(x, SparseCooTensor) and not isinstance(y, SparseCooTensor):
        bcoo = x._bcoo

        def f(vals, dense):
            m = jsparse.BCOO((vals, bcoo.indices), shape=bcoo.shape)
            return m @ dense
        return apply_op(f, x.values(), to_tensor(y) if not isinstance(
            y, Tensor) else y)
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        out = _arr(x.to_dense()) @ _arr(y.to_dense())
        return _from_dense_coo(out)
    raise TypeError("matmul: x must be sparse")


def masked_matmul(x, y, mask, name=None):
    """ref: paddle.sparse.masked_matmul — dense @ dense evaluated only at
    `mask`'s sparsity pattern (sampled-dense-dense matmul). One gather per
    side + a row-dot — never materializes the dense product."""
    xa, ya = _arr(_t_dense(x)), _arr(_t_dense(y))
    idx = mask._bcoo.indices  # [nnz, 2]

    def f(a, b):
        rows = a[idx[:, 0]]          # [nnz, K]
        cols = b[:, idx[:, 1]].T     # [nnz, K]
        vals = jnp.sum(rows * cols, -1)
        return vals
    vals = apply_op(f, _t_dense(x), _t_dense(y))
    return mask.with_values(vals)


def _t_dense(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def transpose(x, perm, name=None):
    dense = _arr(x.to_dense())
    return _from_dense_coo(jnp.transpose(dense, perm))


def coalesce(x, name=None):
    return x.coalesce()


# ---------------------------------------------------------------------------
# sparse.nn subset
# ---------------------------------------------------------------------------
class _SparseReLU:
    """ref: paddle.sparse.nn.ReLU."""

    def __call__(self, x):
        return relu(x)


from .conv import Conv3D, SubmConv3D  # noqa: E402


class _nn:
    ReLU = _SparseReLU
    Conv3D = Conv3D
    SubmConv3D = SubmConv3D


nn = _nn()
