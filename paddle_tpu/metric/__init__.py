"""Metrics (ref: python/paddle/metric/metrics.py)."""
from __future__ import annotations

import numpy as np

from ..tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _np(x):
    return np.asarray(x._value) if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None, *args, **kwargs):
        super().__init__()
        self.topk = topk if isinstance(topk, (tuple, list)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        p = _np(pred)
        l = _np(label)
        idx = np.argsort(-p, axis=-1)[..., : self.maxk]
        if l.ndim == p.ndim and l.shape[-1] == 1:
            l = l[..., 0]
        correct = idx == l[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = _np(correct)
        accs = []
        for k in self.topk:
            num = c[..., :k].sum()
            accs.append(num)
        total = int(np.prod(c.shape[:-1]))
        self.total = [t + a for t, a in zip(self.total, accs)]
        self.count = [c_ + total for c_ in self.count]
        return [t / max(c_, 1) for t, c_ in zip(self.total, self.count)]

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int64).reshape(-1)
        l = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int64).reshape(-1)
        l = _np(labels).astype(np.int64).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc", *args,
                 **kwargs):
        super().__init__()
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2 and p.shape[1] == 2:
            p = p[:, 1]
        l = _np(labels).reshape(-1)
        bins = np.clip((p.reshape(-1) * self.num_thresholds).astype(np.int64),
                       0, self.num_thresholds)
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if not tot_pos or not tot_neg:
            return 0.0
        # trapezoidal AUC over thresholds (descending), anchored at (0,0)
        pos = np.concatenate([[0.0], self._stat_pos[::-1].cumsum()])
        neg = np.concatenate([[0.0], self._stat_neg[::-1].cumsum()])
        tpr = pos / tot_pos
        fpr = neg / tot_neg
        return float(np.trapezoid(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    p = _np(input)
    l = _np(label).reshape(-1)
    idx = np.argsort(-p, axis=-1)[:, :k]
    correct_ = (idx == l[:, None]).any(axis=1).mean()
    return Tensor(np.float32(correct_))
