"""paddle_tpu — a TPU-native deep learning framework.

A ground-up rebuild of the reference framework (diyun0916/Paddle /
PaddlePaddle) for TPU: jax/XLA is the compiler+runtime for compute, Pallas
for custom kernels, jax.sharding for the Fleet-style distributed stack, and
a C++ runtime for host-side IO. The public API mirrors `import paddle` so
reference training scripts port by changing the import.
"""
from __future__ import annotations

from . import framework
from .framework import (  # noqa: F401
    bfloat16, bool_, complex128, complex64, float16, float32, float64, int8,
    int16, int32, int64, uint8, uint16, uint32, uint64,
    CPUPlace, CUDAPlace, Place, TPUPlace,
    get_default_dtype, set_default_dtype, seed, get_flags, set_flags,
    get_device, set_device, device_count, is_compiled_with_cuda,
    is_compiled_with_tpu, in_dynamic_mode, rng_scope, iinfo, finfo,
)
from .autograd import (  # noqa: F401
    no_grad, enable_grad, is_grad_enabled, set_grad_enabled, grad,
    jacobian, hessian,
)
from .tensor import Tensor, to_tensor  # noqa: F401
from .tensor_ops import *  # noqa: F401,F403
from .tensor_ops import linalg  # noqa: F401
from . import autograd  # noqa: F401

# dtype alias matching `paddle.bool`
bool = bool_  # noqa: A001


def cast(x, dtype):
    """ref: paddle.cast."""
    return x.astype(dtype)

from .version import full_version as __version__  # noqa: E402


def _lazy_import():
    # Heavier subpackages import on first access to keep `import paddle_tpu`
    # fast for array-only users.
    pass


from . import nn  # noqa: E402,F401
from . import optimizer  # noqa: E402,F401
from . import metric  # noqa: E402,F401
from . import io  # noqa: E402,F401
from . import amp  # noqa: E402,F401
from . import vision  # noqa: E402,F401
from . import jit  # noqa: E402,F401
from . import distributed  # noqa: E402,F401
from .hapi.model import Model  # noqa: E402,F401
from .hapi.summary import summary, flops  # noqa: E402,F401
from .serialization import save, load  # noqa: E402,F401
from .functional_transforms import value_and_grad, functional_grad, vmap, checkpoint  # noqa: E402,F401
from . import profiler  # noqa: F401
from . import utils  # noqa: F401
from . import text  # noqa: F401
from . import incubate  # noqa: E402,F401
from . import distribution  # noqa: E402,F401
from . import fft  # noqa: E402,F401
from . import signal  # noqa: E402,F401
from . import audio  # noqa: E402,F401
from . import sparse  # noqa: E402,F401
from . import geometric  # noqa: E402,F401
from . import quantization  # noqa: E402,F401
from . import onnx  # noqa: E402,F401
from . import hub  # noqa: E402,F401
from . import static  # noqa: E402,F401
from . import compat  # noqa: E402,F401
from . import device  # noqa: E402,F401
from . import resilience  # noqa: E402,F401
from . import observability  # noqa: E402,F401
from . import serving_fleet  # noqa: E402,F401
from . import version  # noqa: E402,F401
from .framework import (  # noqa: E402,F401
    get_rng_state, set_rng_state, get_cuda_rng_state, set_cuda_rng_state,
    LazyGuard, disable_static, enable_static, is_compiled_with_xpu,
    is_compiled_with_rocm,
)
from .hapi import callbacks  # noqa: E402,F401  (ref: paddle.callbacks)
from .distributed.parallel import DataParallel  # noqa: E402,F401
from . import inference  # noqa: E402,F401


def batch(reader, batch_size, drop_last=False):
    """ref: paddle.batch — legacy reader decorator (pre-DataLoader
    scripts): wraps a sample generator into a batch generator."""
    if int(batch_size) <= 0:
        raise ValueError(
            f"batch_size should be a positive value, got {batch_size}")

    def batched():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batched


# reference top-level aliases completing the namespace sweep
from .tensor_ops.linalg import cond, norm  # noqa: E402,F401
from .tensor_ops.linalg import inv as inverse  # noqa: E402,F401
from .tensor_ops.linalg import matmul as mm, mv  # noqa: E402,F401
from .tensor_ops import concat as cat  # noqa: E402,F401


def numel(x):
    """ref: paddle.numel — element count as a 0-d int64 Tensor (delegates
    to Tensor.size)."""
    from .tensor import Tensor as _T
    import jax.numpy as _jnp
    return _T(_jnp.asarray(int(x.size), _jnp.int64))


def rank(x):
    """ref: paddle.rank — ndim as a 0-d Tensor."""
    from .tensor import Tensor as _T
    import jax.numpy as _jnp
    return _T(_jnp.asarray(x.ndim, _jnp.int64))


def shape(x):
    """ref: paddle.shape — runtime shape as an int tensor."""
    from .tensor import Tensor as _T
    import jax.numpy as _jnp
    return _T(_jnp.asarray([int(s) for s in x.shape], _jnp.int64))
