"""paddle.geometric parity — graph message passing on TPU.

ref: python/paddle/geometric/math.py (segment_sum/mean/max/min) and
python/paddle/geometric/message_passing/send_recv.py (send_u_recv,
send_ue_recv, send_uv).

TPU-first design: everything lowers to `jax.ops.segment_*`, which XLA
compiles to sorted-scatter HLO — no atomics (the reference's CUDA
kernels rely on atomicAdd; TPU has none, and XLA's scatter emits a
deterministic combiner instead, so results are bit-reproducible).
Under `jit`, pass `out_size` (static) — the output row count must be a
compile-time constant on TPU; eager calls may omit it and we read
`max(ids)+1` off-device, matching the reference's dynamic behavior.

Empty-segment semantics match the reference: rows with no incoming
messages are 0 (the reference's CUDA kernels memset the output), not
the -inf/+inf identities jax uses for max/min.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..autograd import apply_op
from ..tensor import Tensor, to_tensor

__all__ = ["segment_sum", "segment_mean", "segment_max", "segment_min",
           "send_u_recv", "send_ue_recv", "send_uv"]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _ids(x):
    a = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    if not jnp.issubdtype(a.dtype, jnp.integer):
        raise TypeError(f"segment/index ids must be integer, got {a.dtype}")
    return a.astype(jnp.int32)


def _num_segments(ids, out_size):
    if out_size is not None:
        return int(out_size)
    # eager path: one device->host sync, same dynamic semantics as the
    # reference; under jit this raises a ConcretizationError on purpose —
    # pass out_size there (TPU needs static shapes)
    return int(jnp.max(ids)) + 1 if ids.size else 0


def _reduce(msg, ids, n, op):
    """Scatter-reduce `msg` rows into `n` output rows by `ids`, with the
    reference's empty-segment semantics (rows receiving nothing are 0 —
    the CUDA kernels memset the output; jax's max/min identities are
    ±inf, and its mean would be 0/0)."""
    if op == "sum":
        return jax.ops.segment_sum(msg, ids, num_segments=n)
    counts = jax.ops.segment_sum(jnp.ones(ids.shape, jnp.int32), ids,
                                 num_segments=n)
    if op == "mean":
        s = jax.ops.segment_sum(msg, ids, num_segments=n)
        denom = jnp.maximum(counts, 1).astype(msg.dtype)
        return s / denom.reshape((-1,) + (1,) * (msg.ndim - 1))
    out = (jax.ops.segment_max if op == "max" else jax.ops.segment_min)(
        msg, ids, num_segments=n)
    mask = (counts > 0).reshape((-1,) + (1,) * (out.ndim - 1))
    return jnp.where(mask, out, jnp.zeros_like(out))


def _segment(op, data, segment_ids, out_size, name=None):
    ids = _ids(segment_ids)
    n = _num_segments(ids, out_size)
    return apply_op(lambda a: _reduce(a, ids, n, op), _t(data))


def segment_sum(data, segment_ids, out_size=None, name=None):
    """ref: paddle.geometric.segment_sum — sum rows of `data` grouped by
    `segment_ids` into `max(id)+1` (or `out_size`) output rows."""
    return _segment("sum", data, segment_ids, out_size, name)


def segment_mean(data, segment_ids, out_size=None, name=None):
    """ref: paddle.geometric.segment_mean (empty segments -> 0)."""
    return _segment("mean", data, segment_ids, out_size, name)


def segment_max(data, segment_ids, out_size=None, name=None):
    """ref: paddle.geometric.segment_max (empty segments -> 0)."""
    return _segment("max", data, segment_ids, out_size, name)


def segment_min(data, segment_ids, out_size=None, name=None):
    """ref: paddle.geometric.segment_min (empty segments -> 0)."""
    return _segment("min", data, segment_ids, out_size, name)


_REDUCES = ("sum", "mean", "max", "min")
_MESSAGES = ("add", "sub", "mul", "div")


def _combine(xs, ye, message_op):
    if message_op == "add":
        return xs + ye
    if message_op == "sub":
        return xs - ye
    if message_op == "mul":
        return xs * ye
    if message_op == "div":
        return xs / ye
    raise ValueError(f"message_op must be one of {_MESSAGES}")


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """ref: paddle.geometric.send_u_recv — gather node features at
    `src_index`, scatter-reduce them to `dst_index` rows.
    out[i] = reduce_{e: dst[e]==i} x[src[e]]."""
    if reduce_op not in _REDUCES:
        raise ValueError(f"reduce_op must be one of {_REDUCES}")
    src = _ids(src_index)
    dst = _ids(dst_index)
    n = out_size
    if n is None:
        xa = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        n = xa.shape[0]
    return apply_op(
        lambda a: _reduce(jnp.take(a, src, axis=0), dst, n, reduce_op),
        _t(x))


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """ref: paddle.geometric.send_ue_recv — combine source-node features
    with edge features (`message_op`), then scatter-reduce to dst rows.
    out[i] = reduce_{e: dst[e]==i} (x[src[e]] message_op y[e])."""
    if reduce_op not in _REDUCES:
        raise ValueError(f"reduce_op must be one of {_REDUCES}")
    src = _ids(src_index)
    dst = _ids(dst_index)
    n = out_size
    if n is None:
        xa = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        n = xa.shape[0]
    return apply_op(
        lambda a, e: _reduce(_combine(jnp.take(a, src, axis=0), e,
                                      message_op), dst, n, reduce_op),
        _t(x), _t(y))


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """ref: paddle.geometric.send_uv — per-edge message from both
    endpoints: out[e] = x[src[e]] message_op y[dst[e]]."""
    src = _ids(src_index)
    dst = _ids(dst_index)

    def fn(a, b):
        return _combine(jnp.take(a, src, axis=0),
                        jnp.take(b, dst, axis=0), message_op)

    return apply_op(fn, _t(x), _t(y))
