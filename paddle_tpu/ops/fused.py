"""Fused ops (XLA fuses these already; kept as named entry points so models
and benchmarks can opt into Pallas variants when they land)."""
from __future__ import annotations

import jax
import jax.numpy as jnp


def fused_rms_norm(x, weight, eps=1e-6):
    ms = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x.astype(jnp.float32) * jax.lax.rsqrt(ms + eps)).astype(x.dtype) * weight


def fused_softmax_cross_entropy(logits, labels):
    """Per-example CE over int labels without materialising log-probs twice."""
    m = jax.scipy.special.logsumexp(logits.astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(
        logits.astype(jnp.float32), labels[..., None].astype(jnp.int32), axis=-1)[..., 0]
    return m - picked
