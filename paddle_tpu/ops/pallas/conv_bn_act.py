"""Fused 1x1-conv + BatchNorm scale/shift + ReLU (+ residual add) Pallas
TPU kernel — the diagnosed ResNet-50 HBM-bandwidth wall.

Why: BENCH_r05 puts ResNet-50 at 0.76x the A100 share at MFU 0.139 with
the roofline pinned on the bottleneck 1x1 convs (SURVEY §6, VERDICT r5
weak #2): each is a skinny matmul whose output makes extra full HBM
round trips through the BN normalize, the ReLU, and the residual add.
In NHWC a 1x1 conv IS a [M, Cin] @ [Cin, Cout] matmul (M = N*H*W), so
this kernel computes

    y = relu((x @ w) * scale + shift [+ res])

in ONE pass: the [M, Cout] conv output never round-trips between the
matmul and the pointwise tail. `scale`/`shift` are the BN affine folded
per channel:

    scale_c = gamma_c / sqrt(var_c + eps)
    shift_c = beta_c  - mean_c * scale_c

with (mean, var) either the running stats (inference / use_global_stats)
or the batch stats of the conv output. For train mode the batch stats
are obtained WITHOUT materializing the conv output via
:func:`conv1x1_batch_stats`: mean is linear (mean_M(x) @ w) and the
second moment comes from the Gram matrix G = X^T X / M as
w_o^T G w_o — an extra M*Cin^2 FLOPs, i.e. Cin/Cout of the conv itself
(cheap exactly where the bottleneck expands, Cout = 4*Cin).

Backward is plain jnp under jax.custom_vjp (XLA-fused; the matmul
grads dominate anyway) and recomputes x@w instead of saving it — the
whole point is that the forward never wrote it.

Grid: M is tiled [block_m, :]; the weight [Cin, Cout] and the folded
[1, Cout] vectors are resident per step. Falls back to the jnp
reference whenever the shape doesn't tile (M % 8, Cin/Cout % 128, or a
weight too big for VMEM). Validated in interpret mode on CPU
(tests/test_fused_conv_bn_act.py).
ref parity: the reference serves this fusion via conv_bn_fuse_pass +
cuDNN fused conv epilogues; training-side it is CINN's job. Here it is
one Pallas kernel on the MXU.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["fused_conv1x1_bn_act", "conv1x1_batch_stats"]

_VMEM_W_CAP = 4 << 20  # fp32 bytes the resident [Cin, Cout] tile may take


def _fwd_kernel(x_ref, w_ref, s_ref, b_ref, y_ref, *, relu):
    acc = jnp.dot(x_ref[:], w_ref[:], preferred_element_type=jnp.float32)
    y = acc * s_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)
    if relu:
        y = jnp.maximum(y, 0.0)
    y_ref[:] = y.astype(y_ref.dtype)


def _fwd_kernel_res(x_ref, w_ref, s_ref, b_ref, r_ref, y_ref, *, relu):
    acc = jnp.dot(x_ref[:], w_ref[:], preferred_element_type=jnp.float32)
    y = acc * s_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)
    y = y + r_ref[:].astype(jnp.float32)
    if relu:
        y = jnp.maximum(y, 0.0)
    y_ref[:] = y.astype(y_ref.dtype)


def _pick_block_m(m, cin, cout):
    """Rows per grid step: x/out/res tiles <= ~2 MB fp32 each, rows a
    multiple of 8 (fp32 sublane), and the row count must tile."""
    per_row = 4 * max(cin, cout)
    cap = max(8, min(1024, (2 << 20) // max(1, per_row) // 8 * 8))
    while m % cap:
        # re-round after halving: an odd-multiple cap (e.g. 336 -> 168
        # -> 84) would otherwise violate the sublane constraint
        cap = (cap // 2) // 8 * 8
        if cap < 8:
            return 0
    return cap


def _supported(m, cin, cout):
    return (cin % 128 == 0 and cout % 128 == 0
            and 4 * cin * cout <= _VMEM_W_CAP)


def _reference(x2, w, scale, shift, res2, relu):
    acc = jnp.dot(x2.astype(jnp.float32), w.astype(jnp.float32),
                  preferred_element_type=jnp.float32)
    y = acc * scale.astype(jnp.float32) + shift.astype(jnp.float32)
    if res2 is not None:
        y = y + res2.astype(jnp.float32)
    if relu:
        y = jnp.where(y > 0, y, 0.0)
    return y.astype(x2.dtype)


def _fwd_call(x2, w, scale, shift, res2, relu, block_m, interpret):
    m, cin = x2.shape
    cout = w.shape[1]
    grid = (m // block_m,)
    row = lambda i: (i, 0)
    full = lambda i: (0, 0)
    in_specs = [
        pl.BlockSpec((block_m, cin), row),
        pl.BlockSpec((cin, cout), full),
        pl.BlockSpec((1, cout), full),
        pl.BlockSpec((1, cout), full),
    ]
    if res2 is not None:
        in_specs.append(pl.BlockSpec((block_m, cout), row))
        kern = functools.partial(_fwd_kernel_res, relu=relu)
        args = (x2, w, scale[None, :], shift[None, :], res2)
    else:
        kern = functools.partial(_fwd_kernel, relu=relu)
        args = (x2, w, scale[None, :], shift[None, :])
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((block_m, cout), row),
        out_shape=jax.ShapeDtypeStruct((m, cout), x2.dtype),
        interpret=interpret,
    )(*args)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def fused_conv1x1_bn_act(x2, w, scale, shift, res2=None, relu=True,
                         block_m=0, interpret=False):
    """y = relu((x2 @ w) * scale + shift [+ res2]) in one HBM pass.

    x2: [M, Cin] (NHWC flattened over N*H*W); w: [Cin, Cout];
    scale/shift: [Cout] folded BN affine; res2: optional [M, Cout]
    residual added before the ReLU. Falls back to the jnp reference
    (same math, XLA-fused) when the shape doesn't tile.
    """
    return _fwd_impl(x2, w, scale, shift, res2, relu, block_m, interpret)


def _fwd_impl(x2, w, scale, shift, res2, relu, block_m, interpret):
    m, cin = x2.shape
    cout = w.shape[1]
    bm = block_m or _pick_block_m(m, cin, cout)
    if not bm or not _supported(m, cin, cout):
        return _reference(x2, w, scale, shift, res2, relu)
    return _fwd_call(x2, w, scale, shift, res2, relu, bm, interpret)


def _fused_fwd(x2, w, scale, shift, res2, relu, block_m, interpret):
    y = _fwd_impl(x2, w, scale, shift, res2, relu, block_m, interpret)
    # xw is deliberately NOT saved (never materialized in forward);
    # backward recomputes it with one extra matmul. y carries the ReLU
    # mask: y > 0 <=> pre-activation > 0 for the kept elements. The
    # empty dtype token stands in for res2 so bwd can emit a cotangent
    # of the RESIDUAL'S dtype without keeping the [M, Cout] array alive.
    res_tok = None if res2 is None else jnp.zeros((0,), res2.dtype)
    return y, (x2, w, scale, shift, y, res_tok)


def _fused_bwd(relu, block_m, interpret, saved, dy):
    x2, w, scale, shift, y, res_tok = saved
    dy = dy.astype(jnp.float32)
    if relu:
        dz = jnp.where(y > 0, dy, 0.0)
    else:
        dz = dy
    xw = jnp.dot(x2.astype(jnp.float32), w.astype(jnp.float32),
                 preferred_element_type=jnp.float32)
    dscale = jnp.sum(dz * xw, axis=0)
    dshift = jnp.sum(dz, axis=0)
    dxw = dz * scale.astype(jnp.float32)
    dx = jnp.dot(dxw, w.astype(jnp.float32).T,
                 preferred_element_type=jnp.float32)
    dw = jnp.dot(x2.astype(jnp.float32).T, dxw,
                 preferred_element_type=jnp.float32)
    # custom_vjp checks cotangent avals against the PRIMAL dtypes
    dres = None if res_tok is None else dz.astype(res_tok.dtype)
    return (dx.astype(x2.dtype), dw.astype(w.dtype),
            dscale.astype(scale.dtype), dshift.astype(shift.dtype), dres)


fused_conv1x1_bn_act.defvjp(_fused_fwd, _fused_bwd)


def conv1x1_batch_stats(x2, w):
    """(mean, var) per out-channel of x2 @ w over the M rows, WITHOUT
    materializing the [M, Cout] product:

        mean  = mean_M(x2) @ w                      (linearity)
        E[y²] = diag(wᵀ G w),  G = x2ᵀ x2 / M       (Gram matrix)
        var   = E[y²] - mean²

    Extra FLOPs are M*Cin² for G — Cin/Cout of the conv itself, so this
    is armed only where the 1x1 expands channels (Cout >= Cin: the
    bottleneck's conv3). All fp32; differentiable jnp (the custom-vjp
    kernel chains through scale/shift into these stats).
    """
    xf = x2.astype(jnp.float32)
    wf = w.astype(jnp.float32)
    m = x2.shape[0]
    mean = jnp.dot(jnp.mean(xf, axis=0), wf,
                   preferred_element_type=jnp.float32)
    g = jnp.dot(xf.T, xf, preferred_element_type=jnp.float32) / m
    ex2 = jnp.sum(wf * jnp.dot(g, wf, preferred_element_type=jnp.float32),
                  axis=0)
    var = jnp.maximum(ex2 - jnp.square(mean), 0.0)
    return mean, var
