"""Paged GQA flash-decode, Pallas TPU.

ref parity: the reference's PagedAttention decode kernels
(paddle/fluid/operators/fused/ block-wise attention; vLLM
arXiv:2309.06180) and FlashAttention-class single-row decode.

One grid step = one (slot, kv head, page): the kernel walks a slot's
page list innermost, carrying the online-softmax state (m, l, acc) in
VMEM scratch, so a query row attends its whole paged history without
the [B, S_cap, ...] gather the jnp reference pays. TPU-native points:

- the page table rides scalar prefetch (PrefetchScalarGridSpec): the
  k/v BlockSpec index maps read `pt_ref[b, i]` to pick the page each
  grid step DMAs — HBM pages are read in place, nothing is gathered;
- pages are head-major `[Hkv, P, ps, D]` so one (head, page) block is
  a legal (ps, D) Mosaic tile;
- GQA is free: the query block carries all G query heads of one kv
  head as sublanes (padded to the f32 minimum of 8), so K/V stream
  from HBM exactly once per kv head — the repeat_kv broadcast never
  materializes;
- int8 caches dequantize in-VMEM with the f32 scale sidecar
  `[Hkv, P, ps, 1]` (trailing singleton = legal lane dim);
- dead pages are skipped via the per-slot length in SMEM (same trick
  as flash_attention.py's kv_lens): a slot whose history ends before
  page i contributes no MXU work for it. Unused page-table entries
  point at the trash page (paged_cache.TRASH_PAGE), so skipped blocks
  still DMA a valid page.

All shapes static; per-step state updates happen OUTSIDE the kernel
(paged_cache.write_token_kv) — the kernel is read-only attention.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import _x32_traced

_NEG_INF = -1e30
_Q_SUBLANES = 8  # f32 minimum sublane tile; G query heads pad up to it


def _decode_kernel(pt_ref, lens_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref,
                   o_ref, m_scr, l_scr, acc_scr, *, sm_scale, page_size,
                   quantized):
    b = pl.program_id(0)
    i = pl.program_id(2)
    np_ = pl.num_programs(2)

    @pl.when(i == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # skip pages wholly past the slot's history (and the all-trash rows
    # of inactive slots, whose lens is 0 — they produce a zero row)
    @pl.when(i * page_size < lens_ref[b])
    def _():
        q = q_ref[0, 0].astype(jnp.float32)            # [Gp, D]
        k = k_ref[0, 0].astype(jnp.float32)            # [ps, D]
        v = v_ref[0, 0].astype(jnp.float32)
        if quantized:
            k = k * ks_ref[0, 0]                       # [ps, 1] broadcast
            v = v * vs_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale   # [Gp, ps]
        kpos = i * page_size + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(kpos < lens_ref[b], s, jnp.float32(_NEG_INF))
        m_prev = m_scr[:, :1]
        l_prev = l_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(s > _NEG_INF / 2, p, jnp.float32(0.0))
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:] = jnp.broadcast_to(
            l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True),
            l_scr.shape)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(i == np_ - 1)
    def _():
        l = l_scr[:, :1]
        safe_l = jnp.where(l == 0.0, jnp.float32(1.0), l)
        o_ref[0, 0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)


@_x32_traced
def paged_flash_decode(q, k_pages, v_pages, page_table, lens,
                       k_scale=None, v_scale=None, sm_scale=None,
                       interpret=False):
    """q [B, Hkv, G, D] f32/bf16; k_pages/v_pages [Hkv, P, ps, D]
    (f32/bf16, or int8 with k_scale/v_scale [Hkv, P, ps, 1] f32);
    page_table [B, MP] int32 (every entry a valid page id — unused
    rows point at the trash page); lens [B] int32 valid key counts.
    Returns [B, Hkv, G, D] in q's dtype."""
    b, hkv, g, d = q.shape
    ps = k_pages.shape[2]
    mp = page_table.shape[1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    quantized = k_scale is not None
    gp = max(_Q_SUBLANES, g)
    if gp % _Q_SUBLANES:
        gp = (gp // _Q_SUBLANES + 1) * _Q_SUBLANES
    qp = q.astype(jnp.float32)
    if gp != g:
        qp = jnp.concatenate(
            [qp, jnp.zeros((b, hkv, gp - g, d), jnp.float32)], axis=2)

    pt = jnp.asarray(page_table, jnp.int32)
    lens = jnp.asarray(lens, jnp.int32)
    if not quantized:
        # a dummy scale block keeps the kernel signature uniform (the
        # branch is static, the refs unread; 1 page avoids dead weight)
        k_scale = jnp.zeros((hkv, 1, ps, 1), jnp.float32)
        v_scale = k_scale
    scale_idx = (lambda b_, h_, i_, pt_, lens_:
                 (h_, pt_[b_, i_], 0, 0)) if quantized else \
                (lambda b_, h_, i_, pt_, lens_: (h_, 0, 0, 0))

    kern = functools.partial(_decode_kernel, sm_scale=sm_scale,
                             page_size=ps, quantized=quantized)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, hkv, mp),
        in_specs=[
            pl.BlockSpec((1, 1, gp, d),
                         lambda b_, h_, i_, pt_, lens_: (b_, h_, 0, 0)),
            pl.BlockSpec((1, 1, ps, d),
                         lambda b_, h_, i_, pt_, lens_:
                         (h_, pt_[b_, i_], 0, 0)),
            pl.BlockSpec((1, 1, ps, d),
                         lambda b_, h_, i_, pt_, lens_:
                         (h_, pt_[b_, i_], 0, 0)),
            pl.BlockSpec((1, 1, ps, 1), scale_idx),
            pl.BlockSpec((1, 1, ps, 1), scale_idx),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, gp, d), lambda b_, h_, i_, pt_, lens_: (b_, h_, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((gp, 128), jnp.float32),
            pltpu.VMEM((gp, 128), jnp.float32),
            pltpu.VMEM((gp, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, hkv, gp, d), q.dtype),
        interpret=interpret,
    )(pt, lens, qp, k_pages, v_pages, k_scale, v_scale)
    return out[:, :, :g]
