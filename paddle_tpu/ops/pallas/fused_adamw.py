"""Fused Adam/AdamW parameter update in ONE HBM pass per tensor.

Why: the r4 step anatomy measured the isolated AdamW update at 22.8 ms
on gpt3-345M — ~2x the HBM-bandwidth floor of its 4-read/3-write
traffic (9.7 GB at fp32 -> ~11.8 ms on one v5e). XLA compiles the
per-leaf jnp chain into multiple loop fusions whose intermediate
re-reads pay that factor; this kernel performs the whole update —
moment EMAs, bias correction, coupled or decoupled weight decay,
parameter step — in a single read of (p, m, v, g) and a single write
of (p', m', v'), with input_output_aliasing so no fresh HBM buffers
are allocated. ref parity: paddle/phi/kernels/gpu/adamw_kernel.cu
(the reference fuses exactly this in CUDA).

Scalars that change per step (lr, bias corrections) ride a tiny SMEM
operand; hyperparameters (betas, eps, wd, decay mode) are compile-time
constants. fp32 moments only — bf16 stochastic-rounded moments keep
the jnp path (rounding noise needs the traced RNG stream).
Validated in interpret mode against the optimizer's own jnp math
(tests/test_fused_adamw.py).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["fused_adamw_update", "fused_adamw_supported"]

_LANES = 512
_MIN_SIZE = 1 << 14  # smaller leaves: kernel launch overhead > win


def _kernel(s_ref, p_ref, m_ref, v_ref, g_ref, po_ref, mo_ref, vo_ref,
            *, b1, b2, eps, wd, decoupled):
    lr = s_ref[0]
    bc1 = s_ref[1]
    bc2 = s_ref[2]
    p = p_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    if wd and not decoupled:
        g = g + wd * p
    m = b1 * m_ref[:].astype(jnp.float32) + (1.0 - b1) * g
    v = b2 * v_ref[:].astype(jnp.float32) + (1.0 - b2) * g * g
    denom = jnp.sqrt(v / bc2) + eps
    step = lr * (m / bc1) / denom
    if wd and decoupled:
        step = step + lr * wd * p
    po_ref[:] = (p - step).astype(po_ref.dtype)
    mo_ref[:] = m.astype(mo_ref.dtype)
    vo_ref[:] = v.astype(vo_ref.dtype)


def fused_adamw_supported(p, m, v):
    """Eligible leaf: large, fp32 throughout (a checkpoint-restored
    bf16 moment must fall back regardless of moment_dtype config),
    and already tiling to the 8x512 grid — a non-multiple leaf would
    pay four padded concatenate copies per step, defeating the
    one-pass aliasing the kernel exists for."""
    return (p.dtype == jnp.float32
            and m.dtype == jnp.float32 and v.dtype == jnp.float32
            and p.size >= _MIN_SIZE
            and p.size % (8 * _LANES) == 0)


def fused_adamw_update(p, m, v, g, lr, bc1, bc2, *, beta1, beta2, eps,
                       weight_decay, decoupled, block_rows=256,
                       interpret=False):
    """One-pass update; returns (p_new, m_new, v_new). lr/bc1/bc2 may
    be traced scalars (they ride SMEM); betas/eps/wd are static."""
    shape = p.shape
    n = p.size
    pad = (-n) % (8 * _LANES)
    total = n + pad

    def flat(x):
        x = x.reshape(-1)
        if pad:
            # reachable only when called directly with a non-tiling
            # size (fused_adamw_supported gates this path off in the
            # optimizer): four padded copies per step are the cost
            x = jnp.concatenate([x, jnp.zeros((pad,), x.dtype)])
        return x.reshape(-1, _LANES)

    rows = total // _LANES
    br = min(block_rows, rows)
    while rows % br:
        br //= 2
        if br < 8:
            br = rows  # tiny: single block
            break
    scalars = jnp.stack([jnp.asarray(lr, jnp.float32),
                         jnp.asarray(bc1, jnp.float32),
                         jnp.asarray(bc2, jnp.float32)])
    kern = functools.partial(_kernel, b1=float(beta1), b2=float(beta2),
                             eps=float(eps),
                             wd=float(weight_decay or 0.0),
                             decoupled=bool(decoupled))
    row = lambda i: (i, 0)
    tile = pl.BlockSpec((br, _LANES), row)
    po, mo, vo = pl.pallas_call(
        kern,
        grid=(rows // br,),
        in_specs=[
            pl.BlockSpec((3,), lambda i: (0,),
                         memory_space=pltpu.SMEM),
            tile, tile, tile, tile,
        ],
        out_specs=[tile, tile, tile],
        out_shape=[
            jax.ShapeDtypeStruct((rows, _LANES), p.dtype),
            jax.ShapeDtypeStruct((rows, _LANES), m.dtype),
            jax.ShapeDtypeStruct((rows, _LANES), v.dtype),
        ],
        # true in-place: p/m/v buffers are reused for the outputs —
        # no fresh HBM allocations for the optimizer state
        input_output_aliases={1: 0, 2: 1, 3: 2},
        interpret=interpret,
    )(scalars, flat(p), flat(m), flat(v), flat(g))

    def unflat(x):
        return x.reshape(-1)[:n].reshape(shape)
    return unflat(po), unflat(mo), unflat(vo)
