"""Flash attention, Pallas TPU.

ref parity: paddle/phi/kernels/gpu/flash_attn_kernel.cu (flash-attn v2).
TPU-native: online-softmax tiles sized for the MXU (128x128 blocks held in
VMEM, fp32 accumulators in scratch), grid (batch*heads, q_blocks, k_blocks)
with the k dimension innermost so the running (m, l, acc) state lives in
VMEM scratch across k iterations. Backward is the standard two-kernel
recompute split (dq; then dk/dv) using the saved row logsumexp — no S x S
probability matrix ever hits HBM.

Layout: public entry takes [B, S, H, D] (the reference's layout) and runs
kernels on [B*H, S, D].
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

try:
    from jax._src.config import enable_x64 as _enable_x64_ctx
except ImportError:  # pragma: no cover
    import contextlib
    _enable_x64_ctx = lambda _on: contextlib.nullcontext()


def _x32_traced(fn):
    """Trace pallas kernels in x32 mode.

    The framework enables jax_enable_x64 globally for paddle dtype parity
    (framework.py), but under x64 Python int/float literals in index maps
    and kernels trace as i64/f64, which Mosaic cannot legalize
    ('failed to legalize tpu.truncf / func.return'). All kernel math here
    is explicitly f32/i32, so tracing with x64 off is semantics-preserving.
    """
    @functools.wraps(fn)
    def wrapped(*a, **k):
        with _enable_x64_ctx(False):
            return fn(*a, **k)
    return wrapped

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
_NEG_INF = -1e30
# trailing lane dim for per-row stats (lse, delta): Mosaic requires the last
# block dim to be 128-divisible or equal to the array dim, so per-row vectors
# are carried as [bh, sq, 8] with the value replicated over the 8 lanes.
_LSE_LANES = 8


def _causal_mask(s, qi, ki, block_q, block_k, offset):
    """Bottom-right aligned (matches the jnp reference's tril(k=sk-sq)):
    query row i attends keys <= i + offset, offset = sk - sq."""
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(q_pos + offset >= k_pos, s,
                     jnp.asarray(_NEG_INF, s.dtype))


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, sm_scale, causal, block_q, block_k, offset):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = (ki * block_k < (qi + 1) * block_q + offset) if causal else True

    @pl.when(run)
    def _():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k, offset)
        m_prev = m_scr[:, :1]
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _():
        l = l_scr[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)
        # lse is stored [bh, sq, 8]: the trailing size-8 lane dim exists only
        # to satisfy Mosaic's block-shape rules (a (1, block_q) block is not
        # lowerable); the row value is replicated across it.
        lse_ref[0] = jnp.broadcast_to(
            m_scr[:, :1] + jnp.log(safe_l), (m_scr.shape[0], _LSE_LANES))


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               acc_scr, *, sm_scale, causal, block_q, block_k, offset):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = (ki * block_k < (qi + 1) * block_q + offset) if causal else True

    @pl.when(run)
    def _():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k, offset)
        p = jnp.exp(s - lse_ref[0][:, :1])
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0][:, :1])
        acc_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale

    @pl.when(ki == nk - 1)
    def _():
        dq_ref[0] = acc_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr,
                *, sm_scale, causal, block_q, block_k, offset):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = ((qi + 1) * block_q + offset > ki * block_k) if causal else True

    @pl.when(run)
    def _():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k, offset)
        p = jnp.exp(s - lse_ref[0][:, :1])
        # dV += P^T dO
        dv_scr[:] += jax.lax.dot_general(
            p.astype(do_ref.dtype), do_ref[0],
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0][:, :1])
        # dK += dS^T Q * scale
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale

    @pl.when(qi == nq - 1)
    def _():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


@_x32_traced
def _fwd_call(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    bh, sq, d = q.shape
    sk = k.shape[1]
    grid = (bh, sq // block_q, sk // block_k)
    kern = functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                             block_q=block_q, block_k=block_k,
                             offset=sk - sq)
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LSE_LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, _LSE_LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


@_x32_traced
def _bwd_call(res, g, causal, sm_scale, block_q, block_k, interpret):
    q, k, v, o, lse = res
    do = g
    bh, sq, d = q.shape
    sk = k.shape[1]
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[..., None], (bh, sq, _LSE_LANES))

    dq_kern = functools.partial(_dq_kernel, sm_scale=sm_scale, causal=causal,
                                block_q=block_q, block_k=block_k,
                                offset=sk - sq)
    dq = pl.pallas_call(
        dq_kern,
        grid=(bh, sq // block_q, sk // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LSE_LANES), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LSE_LANES), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dkv_kern = functools.partial(_dkv_kernel, sm_scale=sm_scale,
                                 causal=causal, block_q=block_q,
                                 block_k=block_k, offset=sk - sq)
    dk, dv = pl.pallas_call(
        dkv_kern,
        grid=(bh, sk // block_k, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LSE_LANES), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, _LSE_LANES), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_bhsd(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    o, _ = _fwd_call(q, k, v, causal, sm_scale, block_q, block_k, interpret)
    return o


def _flash_fwd_rule(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    o, lse = _fwd_call(q, k, v, causal, sm_scale, block_q, block_k, interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd_rule(causal, sm_scale, block_q, block_k, interpret, res, g):
    return _bwd_call(res, g, causal, sm_scale, block_q, block_k, interpret)


_flash_bhsd.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q, k, v, causal=False, sm_scale=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    interpret=False):
    """[B, S, H, D] differentiable flash attention."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if sq % block_q or sk % block_k:
        raise ValueError(
            f"flash_attention requires seq lens divisible by the block "
            f"sizes, got sq={sq} (block {block_q}), sk={sk} "
            f"(block {block_k}); pad or use F.scaled_dot_product_attention")

    def fold(x):
        return jnp.swapaxes(x, 1, 2).reshape(x.shape[0] * x.shape[2],
                                             x.shape[1], x.shape[3])

    o = _flash_bhsd(fold(q), fold(k), fold(v), causal, sm_scale,
                    block_q, block_k, interpret)
    return jnp.swapaxes(o.reshape(b, h, sq, d), 1, 2)
