"""Flash attention, Pallas TPU.

ref parity: paddle/phi/kernels/gpu/flash_attn_kernel.cu (flash-attn v2:
causal + padding masks + dropout, fwd and bwd).
TPU-native: online-softmax tiles sized for the MXU (128x128 blocks held in
VMEM, fp32 accumulators in scratch), grid (batch*heads, q_blocks, k_blocks)
with the k dimension innermost so the running (m, l, acc) state lives in
VMEM scratch across k iterations. Backward is the standard two-kernel
recompute split (dq; then dk/dv) using the saved row logsumexp — no S x S
probability matrix ever hits HBM.

Feature set (all in-kernel, static shapes):
- causal masking (bottom-right aligned for uneven q/kv lengths);
- per-sequence KV padding lengths (`kv_lens` [B] int32, read from SMEM) —
  the TPU shape of the reference's varlen/padding mask support;
- dropout on the attention probabilities, flash-attn v2 style (the softmax
  denominator uses the un-dropped p; the same mask is REGENERATED in the
  backward kernels from a counter-based hash of (seed, batch-head,
  element position) — no mask tensor is ever stored);
- flash decode: single-query attention against a long padded KV cache
  (`flash_decode`), the generation-time path.

Layout: public entry takes [B, S, H, D] (the reference's layout) and runs
kernels on [B*H, S, D].
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

try:
    from jax._src.config import enable_x64 as _enable_x64_ctx
except ImportError:  # pragma: no cover
    import contextlib
    _enable_x64_ctx = lambda _on: contextlib.nullcontext()


def _x32_traced(fn):
    """Trace pallas kernels in x32 mode.

    The framework enables jax_enable_x64 globally for paddle dtype parity
    (framework.py), but under x64 Python int/float literals in index maps
    and kernels trace as i64/f64, which Mosaic cannot legalize
    ('failed to legalize tpu.truncf / func.return'). All kernel math here
    is explicitly f32/i32, so tracing with x64 off is semantics-preserving.
    """
    @functools.wraps(fn)
    def wrapped(*a, **k):
        with _enable_x64_ctx(False):
            return fn(*a, **k)
    return wrapped


#   measured on v5e (b8 h16 d64, fwd+bwd, causal): 512x512 blocks beat both
#   128x128 (2.2-4.5x) and XLA's fused attention (1.2x @1k ... 1.8x @4k) —
#   large tiles keep the MXU busy across the k-scan and amortize the
#   per-block rescale
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
_NEG_INF = -1e30


def _fit_block(seq, want, head_dim):
    """Pick the kernel block for one sequence axis.

    seq <= want: the whole sequence is one block. Otherwise: halve `want`
    (scaled down for wide heads so bwd tiles stay within VMEM — the 512
    default was measured at d=64) until it divides seq, floored at 128;
    if nothing >= 128 divides seq the caller's validity check rejects the
    shape (tiny tiles would silently run orders of magnitude slower than
    the XLA fallback)."""
    want = max(128, (want * 64) // max(head_dim, 64))
    if seq <= want:
        return seq
    b = want
    while b > 128 and seq % b:
        b //= 2
    return b
# trailing lane dim for per-row stats (lse, delta): Mosaic requires the last
# block dim to be 128-divisible or equal to the array dim, so per-row vectors
# are carried as [bh, sq, 8] with the value replicated over the 8 lanes.
_LSE_LANES = 8


def _positions(shape, qi, ki, block_q, block_k):
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, shape, 0)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    return q_pos, k_pos


def _mask_s(s, qi, ki, block_q, block_k, offset, causal, kv_len):
    """Apply causal and/or kv-length masking to the score tile."""
    q_pos, k_pos = _positions(s.shape, qi, ki, block_q, block_k)
    neg = jnp.asarray(_NEG_INF, s.dtype)
    if causal:
        s = jnp.where(q_pos + offset >= k_pos, s, neg)
    if kv_len is not None:
        s = jnp.where(k_pos < kv_len, s, neg)
    return s


def _dropout_keep(seed, b, qi, ki, shape, block_q, block_k, sk, rate):
    """Deterministic keep-mask tile from a murmur3-finalizer hash of the
    GLOBAL element position — bwd kernels regenerate the identical mask
    from the same (seed, b, position) regardless of their grid order.
    Plain uint32 vector ops: lowers on Mosaic AND runs in interpret mode
    (pltpu.prng_* has no interpret path)."""
    q_pos, k_pos = _positions(shape, qi, ki, block_q, block_k)
    gid = (q_pos * sk + k_pos).astype(jnp.uint32)
    x = gid ^ (seed.astype(jnp.uint32) * jnp.uint32(0x9E3779B9)
               + jnp.uint32(b).astype(jnp.uint32) * jnp.uint32(0x85EBCA6B))
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    # 24-bit threshold compare
    thresh = jnp.uint32(int(rate * (1 << 24)))
    return (x >> 8) >= thresh


def _fwd_kernel(lens_ref, seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, sm_scale, causal, block_q,
                block_k, offset, use_lens, dropout_p, sk):
    b = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = (ki * block_k < (qi + 1) * block_q + offset) if causal else True
    if use_lens:
        # skip key blocks that are entirely padding (decode over a long
        # padded cache would otherwise burn full MXU work per dead block)
        run = run & (ki * block_k < lens_ref[b])

    @pl.when(run)
    def _():
        q = q_ref[0]
        k = k_ref[0]
        s = jax.lax.dot_general(
            q, k, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        kv_len = lens_ref[b] if use_lens else None
        s = _mask_s(s, qi, ki, block_q, block_k, offset, causal, kv_len)
        m_prev = m_scr[:, :1]
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        # hard-masked entries must contribute exactly 0 even in a fully
        # masked row (where m_new == _NEG_INF would otherwise make p = 1);
        # with l = 0 the final tick's safe_l guard then emits a 0 output row
        p = jnp.where(s > _NEG_INF / 2, p, jnp.float32(0.0))
        alpha = jnp.exp(m_prev - m_new)
        # denominator from the UN-dropped p (flash-attn v2 dropout order)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        if dropout_p:
            keep = _dropout_keep(seed_ref[0], b, qi, ki, p.shape,
                                 block_q, block_k, sk, dropout_p)
            p = jnp.where(keep, p / (1.0 - dropout_p), jnp.float32(0.0))
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _():
        l = l_scr[:, :1]
        safe_l = jnp.where(l == 0.0, jnp.float32(1.0), l)
        o_ref[0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)
        # lse is stored [bh, sq, 8]: the trailing size-8 lane dim exists only
        # to satisfy Mosaic's block-shape rules (a (1, block_q) block is not
        # lowerable); the row value is replicated across it.
        lse_ref[0] = jnp.broadcast_to(
            m_scr[:, :1] + jnp.log(safe_l), (m_scr.shape[0], _LSE_LANES))


def _recompute_p(q_ref, k_ref, lse_ref, qi, ki, *, sm_scale, causal,
                 block_q, block_k, offset, kv_len):
    s = jax.lax.dot_general(
        q_ref[0], k_ref[0], dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * sm_scale
    s = _mask_s(s, qi, ki, block_q, block_k, offset, causal, kv_len)
    p = jnp.exp(s - lse_ref[0][:, :1])
    # masked entries contribute no gradient (matches fwd's hard zero)
    return jnp.where(s > _NEG_INF / 2, p, jnp.float32(0.0))


def _dq_kernel(lens_ref, seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
               delta_ref, dq_ref, acc_scr, *, sm_scale, causal, block_q,
               block_k, offset, use_lens, dropout_p, sk):
    b = pl.program_id(0)
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run = (ki * block_k < (qi + 1) * block_q + offset) if causal else True
    if use_lens:
        run = run & (ki * block_k < lens_ref[b])

    @pl.when(run)
    def _():
        kv_len = lens_ref[b] if use_lens else None
        p = _recompute_p(q_ref, k_ref, lse_ref, qi, ki, sm_scale=sm_scale,
                         causal=causal, block_q=block_q, block_k=block_k,
                         offset=offset, kv_len=kv_len)
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout_p:
            keep = _dropout_keep(seed_ref[0], b, qi, ki, p.shape,
                                 block_q, block_k, sk, dropout_p)
            dp = jnp.where(keep, dp / (1.0 - dropout_p), jnp.float32(0.0))
        ds = p * (dp - delta_ref[0][:, :1])
        acc_scr[:] += jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale

    @pl.when(ki == nk - 1)
    def _():
        dq_ref[0] = acc_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(lens_ref, seed_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                delta_ref, dk_ref, dv_ref, dk_scr, dv_scr, *, sm_scale,
                causal, block_q, block_k, offset, use_lens, dropout_p, sk):
    b = pl.program_id(0)
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = ((qi + 1) * block_q + offset > ki * block_k) if causal else True
    if use_lens:
        run = run & (ki * block_k < lens_ref[b])

    @pl.when(run)
    def _():
        kv_len = lens_ref[b] if use_lens else None
        p = _recompute_p(q_ref, k_ref, lse_ref, qi, ki, sm_scale=sm_scale,
                         causal=causal, block_q=block_q, block_k=block_k,
                         offset=offset, kv_len=kv_len)
        if dropout_p:
            keep = _dropout_keep(seed_ref[0], b, qi, ki, p.shape,
                                 block_q, block_k, sk, dropout_p)
            scale = 1.0 / (1.0 - dropout_p)
            p_d = jnp.where(keep, p * scale, jnp.float32(0.0))
        else:
            p_d = p
        # dV += P_dropped^T dO
        dv_scr[:] += jax.lax.dot_general(
            p_d.astype(do_ref.dtype), do_ref[0],
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout_p:
            dp = jnp.where(keep, dp / (1.0 - dropout_p), jnp.float32(0.0))
        ds = p * (dp - delta_ref[0][:, :1])
        # dK += dS^T Q * scale
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0],
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale

    @pl.when(qi == nq - 1)
    def _():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _row_specs(block_q, index=lambda b, i, j: (b, i, 0)):
    return pl.BlockSpec((1, block_q, _LSE_LANES), index)


def _smem_full(n):
    # rank-1 SMEM blocks must cover the whole array on real TPU lowering;
    # kernels index by their batch-head program id
    return pl.BlockSpec((n,), lambda *_: (0,), memory_space=pltpu.SMEM)


@_x32_traced
def _fwd_call(q, k, v, lens, seed, causal, sm_scale, dropout_p, block_q,
              block_k, interpret):
    bh, sq, d = q.shape
    sk = k.shape[1]
    grid = (bh, sq // block_q, sk // block_k)
    use_lens = lens is not None
    kern = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal, block_q=block_q,
        block_k=block_k, offset=sk - sq, use_lens=use_lens,
        dropout_p=dropout_p, sk=sk)
    lens_in = lens if use_lens else jnp.zeros((bh,), jnp.int32)
    seed_in = seed if seed is not None else jnp.zeros((1,), jnp.int32)
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            _smem_full(bh),
            _smem_full(1),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            _row_specs(block_q),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, _LSE_LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(lens_in, seed_in, q, k, v)


@_x32_traced
def _bwd_call(res, g, causal, sm_scale, dropout_p, block_q, block_k,
              interpret):
    q, k, v, o, lse, lens, seed = res
    do = g
    bh, sq, d = q.shape
    sk = k.shape[1]
    use_lens = lens is not None
    lens_in = lens if use_lens else jnp.zeros((bh,), jnp.int32)
    seed_in = seed if seed is not None else jnp.zeros((1,), jnp.int32)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[..., None], (bh, sq, _LSE_LANES))

    common = dict(sm_scale=sm_scale, causal=causal, block_q=block_q,
                  block_k=block_k, offset=sk - sq, use_lens=use_lens,
                  dropout_p=dropout_p, sk=sk)
    dq_kern = functools.partial(_dq_kernel, **common)
    dq = pl.pallas_call(
        dq_kern,
        grid=(bh, sq // block_q, sk // block_k),
        in_specs=[
            _smem_full(bh),
            _smem_full(1),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            _row_specs(block_q),
            _row_specs(block_q),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(lens_in, seed_in, q, k, v, do, lse, delta)

    dkv_kern = functools.partial(_dkv_kernel, **common)
    dk, dv = pl.pallas_call(
        dkv_kern,
        grid=(bh, sk // block_k, sq // block_q),
        in_specs=[
            _smem_full(bh),
            _smem_full(1),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            _row_specs(block_q, lambda b, j, i: (b, i, 0)),
            _row_specs(block_q, lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(lens_in, seed_in, q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash_bhsd(q, k, v, lens, seed, causal, sm_scale, dropout_p, block_q,
                block_k, interpret):
    o, _ = _fwd_call(q, k, v, lens, seed, causal, sm_scale, dropout_p,
                     block_q, block_k, interpret)
    return o


def _flash_fwd_rule(q, k, v, lens, seed, causal, sm_scale, dropout_p,
                    block_q, block_k, interpret):
    o, lse = _fwd_call(q, k, v, lens, seed, causal, sm_scale, dropout_p,
                       block_q, block_k, interpret)
    return o, (q, k, v, o, lse, lens, seed)


def _flash_bwd_rule(causal, sm_scale, dropout_p, block_q, block_k,
                    interpret, res, g):
    dq, dk, dv = _bwd_call(res, g, causal, sm_scale, dropout_p, block_q,
                           block_k, interpret)
    lens, seed = res[5], res[6]
    zlens = (np.zeros(lens.shape, jax.dtypes.float0)
             if lens is not None else None)
    zseed = (np.zeros(seed.shape, jax.dtypes.float0)
             if seed is not None else None)
    return dq, dk, dv, zlens, zseed


_flash_bhsd.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q, k, v, causal=False, sm_scale=None, kv_lens=None,
                    dropout_p=0.0, dropout_seed=0,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    interpret=False):
    """[B, S, H, D] differentiable flash attention.

    kv_lens: optional [B] int32 — key positions >= kv_lens[b] are masked
    (padding). dropout_p/dropout_seed: in-kernel attention dropout
    (training); masks are regenerated in backward, nothing stored.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_q = _fit_block(sq, block_q, d)
    block_k = _fit_block(sk, block_k, d)
    if sq % block_q or sk % block_k or block_q % 8 or block_k % 8:
        raise ValueError(
            f"flash_attention requires seq lens tileable into 8-row blocks "
            f"of at least 128, got sq={sq} (block {block_q}), sk={sk} "
            f"(block {block_k}); pad or use F.scaled_dot_product_attention")

    def fold(x):
        return jnp.swapaxes(x, 1, 2).reshape(x.shape[0] * x.shape[2],
                                             x.shape[1], x.shape[3])

    lens = None
    if kv_lens is not None:
        lens = jnp.repeat(jnp.asarray(kv_lens, jnp.int32), h)
    seed = None
    if dropout_p:
        seed = jnp.asarray([dropout_seed], jnp.int32).reshape((1,))
    o = _flash_bhsd(fold(q), fold(k), fold(v), lens, seed, causal,
                    sm_scale, float(dropout_p), block_q, block_k, interpret)
    return jnp.swapaxes(o.reshape(b, h, sq, d), 1, 2)


_DECODE_Q_ROWS = 8  # Mosaic minimum sublane tile for f32


def flash_decode(q, k_cache, v_cache, kv_lens, sm_scale=None,
                 block_k=DEFAULT_BLOCK_K, interpret=False):
    """Single-step decode attention against a padded KV cache.

    q [B, 1, H, D]; k_cache/v_cache [B, S, H, D] (S static, padded);
    kv_lens [B] int32 — entries at positions >= kv_lens[b] are padding.
    Returns [B, 1, H, D]. ref: the reference's flash decode / paged
    attention path for generation; here the fwd kernel runs with the query
    padded to the 8-sublane minimum tile, masked by kv_lens.
    """
    b, sq, h, d = q.shape
    assert sq == 1, "flash_decode is the single-query path"
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    qp = jnp.concatenate(
        [q, jnp.zeros((b, _DECODE_Q_ROWS - 1) + q.shape[2:], q.dtype)],
        axis=1)

    def fold(x):
        return jnp.swapaxes(x, 1, 2).reshape(x.shape[0] * x.shape[2],
                                             x.shape[1], x.shape[3])

    sk = k_cache.shape[1]
    block_k = min(block_k, sk)
    if sk % block_k:
        raise ValueError(
            f"flash_decode requires the cache length to be divisible by "
            f"block_k, got S={sk} (block {block_k}); pad the cache")
    lens = jnp.repeat(jnp.asarray(kv_lens, jnp.int32), h)
    o, _ = _fwd_call(fold(qp), fold(k_cache), fold(v_cache), lens, None,
                     False, sm_scale, 0.0, _DECODE_Q_ROWS, block_k,
                     interpret)
    o = jnp.swapaxes(o.reshape(b, h, _DECODE_Q_ROWS, d), 1, 2)
    return o[:, :1]
