"""Pallas TPU kernels for the hot ops.

ref parity: the reference's hand-written CUDA kernels
(paddle/phi/kernels/gpu/flash_attn_kernel.cu, fused softmax/layernorm).
Here each kernel is written against the MXU/VPU with VMEM blocking and is
validated in interpret mode on CPU (tests/test_pallas_*).
"""
from .conv_bn_act import (conv1x1_batch_stats,  # noqa: F401
                          fused_conv1x1_bn_act)
from .flash_attention import flash_attention, flash_decode  # noqa: F401
