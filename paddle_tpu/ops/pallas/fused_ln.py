"""Fused residual-add + LayerNorm Pallas TPU kernel (fwd + bwd).

Why: step anatomy on the 345M GPT (BENCHLOG r4) put the MFU gap in
elementwise HBM passes — the pre-LN block's `s = x + drop(h);
ln_2(s)` chain costs an extra full read of s when the add and the
norm compile to separate HBM round trips. This kernel computes

    s = x + res        (returned: the next residual branch needs it)
    y = (s - mean)/sqrt(var + eps) * gamma + beta

in ONE pass over the rows (2 reads + 2 writes instead of 3 reads +
2 writes), saving per-row mean/rstd for an equally fused backward.
ref parity: paddle/phi/kernels/fusion/fused_layernorm_residual_
dropout_bias (the reference fuses the same chain in CUDA); dropout
stays outside this kernel (it is pointwise and XLA fuses it into the
producing matmul — the win here is the add->reduce boundary XLA keeps
as a kernel break).

Grid: rows are tiled [block_rows, H] per step; the weight grads are
accumulated across the sequential TPU grid into fp32 [1, H] outputs.
Validated in interpret mode on CPU (tests/test_fused_ln.py);
bf16/fp32 both supported, softmax-free so tolerance is tight.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["fused_add_layer_norm", "fused_add_layer_norm_y"]

_STAT_LANES = 128  # row stats stored [N, 128] to satisfy TPU tiling


def _fwd_kernel(x_ref, r_ref, g_ref, b_ref, y_ref, s_ref, mu_ref,
                rs_ref, *, eps):
    s = x_ref[:].astype(jnp.float32) + r_ref[:].astype(jnp.float32)
    mu = jnp.mean(s, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(s - mu), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = (s - mu) * rstd
    y = xhat * g_ref[:].astype(jnp.float32) + b_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    s_ref[:] = s.astype(s_ref.dtype)
    mu_ref[:] = jnp.broadcast_to(mu, mu_ref.shape)
    rs_ref[:] = jnp.broadcast_to(rstd, rs_ref.shape)


def _bwd_kernel(dy_ref, ds_ref, s_ref, mu_ref, rs_ref, g_ref,
                dx_ref, dg_ref, db_ref):
    i = pl.program_id(0)
    dy = dy_ref[:].astype(jnp.float32)
    ds = ds_ref[:].astype(jnp.float32)
    s = s_ref[:].astype(jnp.float32)
    mu = mu_ref[:, :1]
    rstd = rs_ref[:, :1]
    g = g_ref[:].astype(jnp.float32)
    xhat = (s - mu) * rstd
    dxhat = dy * g
    m1 = jnp.mean(dxhat, axis=-1, keepdims=True)
    m2 = jnp.mean(dxhat * xhat, axis=-1, keepdims=True)
    dx = rstd * (dxhat - m1 - xhat * m2) + ds
    dx_ref[:] = dx.astype(dx_ref.dtype)
    dg_part = jnp.sum(dy * xhat, axis=0, keepdims=True)
    db_part = jnp.sum(dy, axis=0, keepdims=True)

    @pl.when(i == 0)
    def _():
        dg_ref[:] = dg_part
        db_ref[:] = db_part

    @pl.when(i > 0)
    def _():
        dg_ref[:] += dg_part
        db_ref[:] += db_part


def _fwd_kernel_y(x_ref, r_ref, g_ref, b_ref, y_ref, mu_ref, rs_ref, *,
                  eps):
    """y-only forward (post-LN blocks discard the sum): one write
    fewer per call; backward recomputes s from (x, res)."""
    s = x_ref[:].astype(jnp.float32) + r_ref[:].astype(jnp.float32)
    mu = jnp.mean(s, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(s - mu), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = (s - mu) * rstd * g_ref[:].astype(jnp.float32) \
        + b_ref[:].astype(jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    mu_ref[:] = jnp.broadcast_to(mu, mu_ref.shape)
    rs_ref[:] = jnp.broadcast_to(rstd, rs_ref.shape)


def _bwd_kernel_y(dy_ref, x_ref, r_ref, mu_ref, rs_ref, g_ref,
                  dx_ref, dg_ref, db_ref):
    i = pl.program_id(0)
    dy = dy_ref[:].astype(jnp.float32)
    s = x_ref[:].astype(jnp.float32) + r_ref[:].astype(jnp.float32)
    mu = mu_ref[:, :1]
    rstd = rs_ref[:, :1]
    g = g_ref[:].astype(jnp.float32)
    xhat = (s - mu) * rstd
    dxhat = dy * g
    m1 = jnp.mean(dxhat, axis=-1, keepdims=True)
    m2 = jnp.mean(dxhat * xhat, axis=-1, keepdims=True)
    dx = rstd * (dxhat - m1 - xhat * m2)
    dx_ref[:] = dx.astype(dx_ref.dtype)
    dg_part = jnp.sum(dy * xhat, axis=0, keepdims=True)
    db_part = jnp.sum(dy, axis=0, keepdims=True)

    @pl.when(i == 0)
    def _():
        dg_ref[:] = dg_part
        db_ref[:] = db_part

    @pl.when(i > 0)
    def _():
        dg_ref[:] += dg_part
        db_ref[:] += db_part


def _pick_block_rows(n, h):
    # ~4 fp32 row tiles must sit in VMEM (~16 MB); keep tiles <= ~2 MB
    # each and rows a multiple of 8 (fp32 sublane)
    cap = max(8, min(256, (2 << 20) // max(1, 4 * h) // 8 * 8))
    while n % cap:
        cap //= 2
        if cap < 8:
            return 0
    return cap


def _fwd_call(x2, r2, gamma, beta, eps, block_rows, interpret):
    n, h = x2.shape
    grid = (n // block_rows,)
    row = lambda i: (i, 0)
    vec = lambda i: (0, 0)
    kern = functools.partial(_fwd_kernel, eps=eps)
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, h), row),
            pl.BlockSpec((block_rows, h), row),
            pl.BlockSpec((1, h), vec),
            pl.BlockSpec((1, h), vec),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, h), row),
            pl.BlockSpec((block_rows, h), row),
            pl.BlockSpec((block_rows, _STAT_LANES), row),
            pl.BlockSpec((block_rows, _STAT_LANES), row),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h), x2.dtype),
            jax.ShapeDtypeStruct((n, h), x2.dtype),
            jax.ShapeDtypeStruct((n, _STAT_LANES), jnp.float32),
            jax.ShapeDtypeStruct((n, _STAT_LANES), jnp.float32),
        ],
        interpret=interpret,
    )(x2, r2, gamma[None, :], beta[None, :])


def _bwd_call(dy2, ds2, s2, mu, rstd, gamma, block_rows, interpret):
    n, h = dy2.shape
    grid = (n // block_rows,)
    row = lambda i: (i, 0)
    vec = lambda i: (0, 0)
    return pl.pallas_call(
        _bwd_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, h), row),
            pl.BlockSpec((block_rows, h), row),
            pl.BlockSpec((block_rows, h), row),
            pl.BlockSpec((block_rows, _STAT_LANES), row),
            pl.BlockSpec((block_rows, _STAT_LANES), row),
            pl.BlockSpec((1, h), vec),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, h), row),
            pl.BlockSpec((1, h), vec),
            pl.BlockSpec((1, h), vec),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h), dy2.dtype),
            jax.ShapeDtypeStruct((1, h), jnp.float32),
            jax.ShapeDtypeStruct((1, h), jnp.float32),
        ],
        interpret=interpret,
    )(dy2, ds2, s2, mu, rstd, gamma[None, :])


def _reference(x, res, gamma, beta, eps):
    s = x.astype(jnp.float32) + res.astype(jnp.float32)
    mu = jnp.mean(s, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(s - mu), axis=-1, keepdims=True)
    y = (s - mu) * jax.lax.rsqrt(var + eps) * gamma.astype(jnp.float32) \
        + beta.astype(jnp.float32)
    return y.astype(x.dtype), s.astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def fused_add_layer_norm(x, res, gamma, beta, eps=1e-5, block_rows=0,
                         interpret=False):
    """(y, s): y = LayerNorm(x + res) * gamma + beta, s = x + res.

    x, res: [..., H]; gamma/beta: [H]. Both outputs differentiable
    (s feeds the next residual branch). Falls back to the jnp
    reference (same math, XLA-fused) when the row count doesn't tile.
    """
    y, s, _, _ = _fused_fwd_impl(x, res, gamma, beta, eps, block_rows,
                                 interpret)
    return y, s


def _fused_fwd_impl(x, res, gamma, beta, eps, block_rows, interpret):
    h = x.shape[-1]
    lead = x.shape[:-1]
    n = 1
    for d in lead:
        n *= d
    br = block_rows or _pick_block_rows(n, h)
    if not br or n % br:
        y, s = _reference(x, res, gamma, beta, eps)
        return y, s, None, None
    x2 = x.reshape(n, h)
    r2 = res.reshape(n, h)
    y2, s2, mu, rstd = _fwd_call(x2, r2, gamma, beta, eps, br, interpret)
    return (y2.reshape(*lead, h), s2.reshape(*lead, h),
            mu, rstd)


def _fused_fwd(x, res, gamma, beta, eps, block_rows, interpret):
    y, s, mu, rstd = _fused_fwd_impl(x, res, gamma, beta, eps,
                                     block_rows, interpret)
    return (y, s), (s, mu, rstd, gamma, beta)


def _fused_bwd(eps, block_rows, interpret, saved, cts):
    s, mu, rstd, gamma, beta = saved
    dy, ds = cts
    h = s.shape[-1]
    lead = s.shape[:-1]
    n = 1
    for d in lead:
        n *= d
    if mu is None:  # forward took the jnp fallback — mirror it
        def ref_fn(x_, r_, g_, b_):
            return _reference(x_, r_, g_, b_, eps)
        zeros = jnp.zeros_like(s)
        _, vjp = jax.vjp(ref_fn, s, zeros, gamma, beta)
        dx, _, dg, db = vjp((dy, ds))
        return dx, dx, dg, db
    br = block_rows or _pick_block_rows(n, h)
    dx2, dg, db = _bwd_call(dy.reshape(n, h), ds.reshape(n, h),
                            s.reshape(n, h), mu, rstd, gamma, br,
                            interpret)
    dx = dx2.reshape(*lead, h)
    return dx, dx, dg[0].astype(gamma.dtype), db[0].astype(beta.dtype)


fused_add_layer_norm.defvjp(_fused_fwd, _fused_bwd)


def _fwd_call_y(x2, r2, gamma, beta, eps, block_rows, interpret):
    n, h = x2.shape
    row = lambda i: (i, 0)
    vec = lambda i: (0, 0)
    return pl.pallas_call(
        functools.partial(_fwd_kernel_y, eps=eps),
        grid=(n // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, h), row),
            pl.BlockSpec((block_rows, h), row),
            pl.BlockSpec((1, h), vec),
            pl.BlockSpec((1, h), vec),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, h), row),
            pl.BlockSpec((block_rows, _STAT_LANES), row),
            pl.BlockSpec((block_rows, _STAT_LANES), row),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h), x2.dtype),
            jax.ShapeDtypeStruct((n, _STAT_LANES), jnp.float32),
            jax.ShapeDtypeStruct((n, _STAT_LANES), jnp.float32),
        ],
        interpret=interpret,
    )(x2, r2, gamma[None, :], beta[None, :])


def _bwd_call_y(dy2, x2, r2, mu, rstd, gamma, block_rows, interpret):
    n, h = dy2.shape
    row = lambda i: (i, 0)
    vec = lambda i: (0, 0)
    return pl.pallas_call(
        _bwd_kernel_y,
        grid=(n // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, h), row),
            pl.BlockSpec((block_rows, h), row),
            pl.BlockSpec((block_rows, h), row),
            pl.BlockSpec((block_rows, _STAT_LANES), row),
            pl.BlockSpec((block_rows, _STAT_LANES), row),
            pl.BlockSpec((1, h), vec),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, h), row),
            pl.BlockSpec((1, h), vec),
            pl.BlockSpec((1, h), vec),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, h), dy2.dtype),
            jax.ShapeDtypeStruct((1, h), jnp.float32),
            jax.ShapeDtypeStruct((1, h), jnp.float32),
        ],
        interpret=interpret,
    )(dy2, x2, r2, mu, rstd, gamma[None, :])


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def fused_add_layer_norm_y(x, res, gamma, beta, eps=1e-5, block_rows=0,
                           interpret=False):
    """y = LayerNorm(x + res) * gamma + beta, WITHOUT materializing the
    sum (post-LN blocks discard it): one HBM write fewer per call than
    fused_add_layer_norm, and backward re-adds x+res in-kernel."""
    y, _, _ = _fused_fwd_impl_y(x, res, gamma, beta, eps, block_rows,
                                interpret)
    return y


def _fused_fwd_impl_y(x, res, gamma, beta, eps, block_rows, interpret):
    h = x.shape[-1]
    n = 1
    for d in x.shape[:-1]:
        n *= d
    br = block_rows or _pick_block_rows(n, h)
    if not br or n % br:
        y, _ = _reference(x, res, gamma, beta, eps)
        return y, None, None
    y2, mu, rstd = _fwd_call_y(x.reshape(n, h), res.reshape(n, h),
                               gamma, beta, eps, br, interpret)
    return y2.reshape(x.shape), mu, rstd


def _fused_fwd_y(x, res, gamma, beta, eps, block_rows, interpret):
    y, mu, rstd = _fused_fwd_impl_y(x, res, gamma, beta, eps,
                                    block_rows, interpret)
    return y, (x, res, mu, rstd, gamma, beta)


def _fused_bwd_y(eps, block_rows, interpret, saved, dy):
    x, res, mu, rstd, gamma, beta = saved
    h = x.shape[-1]
    n = 1
    for d in x.shape[:-1]:
        n *= d
    if mu is None:  # forward took the jnp fallback — mirror it
        def ref_y(x_, r_, g_, b_):
            return _reference(x_, r_, g_, b_, eps)[0]
        _, vjp = jax.vjp(ref_y, x, res, gamma, beta)
        return vjp(dy)
    br = block_rows or _pick_block_rows(n, h)
    dx2, dg, db = _bwd_call_y(dy.reshape(n, h), x.reshape(n, h),
                              res.reshape(n, h), mu, rstd, gamma, br,
                              interpret)
    dx = dx2.reshape(x.shape)
    return dx, dx, dg[0].astype(gamma.dtype), db[0].astype(beta.dtype)


fused_add_layer_norm_y.defvjp(_fused_fwd_y, _fused_bwd_y)
