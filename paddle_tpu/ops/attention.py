"""Flash attention for TPU.

ref parity: paddle.nn.functional.flash_attention (CUDA flash-attn v2 in the
reference). Here: a Pallas TPU kernel (ops/pallas/flash_attention.py) tiled
for the MXU, with an XLA-fusable jnp fallback. The public entry keeps the
reference's [batch, seq, heads, head_dim] layout.

In-kernel coverage (matching the reference's flash_attn feature set):
causal, per-sequence KV padding lengths (kv_lens), attention dropout
(mask regenerated in backward). Arbitrary dense attn_mask tensors still
fall back to the jnp path — the reference routes those off flash too.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

_PALLAS_MIN_SEQ = 128
_PALLAS_HEAD_DIMS = (64, 128, 256)


def _platform():
    try:
        return jax.devices()[0].platform
    except Exception:
        return "cpu"


def flash_attention_available(q_shape, k_shape, attn_mask, dropout_p) -> bool:
    """Pallas kernel handles: TPU, no explicit dense mask (padding lengths
    and dropout ARE supported in-kernel), seq multiple of block, supported
    head dims."""
    if attn_mask is not None:
        return False
    if _platform() != "tpu":
        return False
    if len(q_shape) != 4:
        return False
    b, sq, h, d = q_shape
    sk = k_shape[1]
    return (d in _PALLAS_HEAD_DIMS and sq % _PALLAS_MIN_SEQ == 0
            and sk % _PALLAS_MIN_SEQ == 0)


def flash_attention(q, k, v, causal=False, sm_scale=None, kv_lens=None,
                    dropout_p=0.0, dropout_seed=0):
    """[B, S, H, D] flash attention. Uses the Pallas kernel on TPU, jnp
    reference otherwise."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if flash_attention_available(q.shape, k.shape, None, dropout_p):
        from .pallas.flash_attention import flash_attention as pallas_flash
        # On a real TPU the kernel compiles natively; if the availability
        # gate was forced on elsewhere (CPU tests), run in interpret mode so
        # the identical kernel/ad path is exercised.
        return pallas_flash(q, k, v, causal=causal, sm_scale=sm_scale,
                            kv_lens=kv_lens, dropout_p=dropout_p,
                            dropout_seed=dropout_seed,
                            interpret=_platform() != "tpu")
    return reference_attention(q, k, v, causal=causal, sm_scale=sm_scale,
                               kv_lens=kv_lens, dropout_p=dropout_p,
                               dropout_seed=dropout_seed)


def flash_decode(q, k_cache, v_cache, kv_lens, sm_scale=None):
    """Single-query decode against a padded KV cache ([B, 1, H, D] x
    [B, S, H, D] + kv_lens [B]). Pallas on TPU (opt-in), jnp elsewhere.

    The Pallas decode kernel is gated behind PADDLE_TPU_FLASH_DECODE=1:
    its first Mosaic compile inside a scanned decode program hung the
    shared TPU terminal in round 2 (BENCHLOG "decode-path incident") and
    it is not yet hardware-proven (tools/decode_probe.py bisects it in
    killable subprocesses). Decode attention is HBM-bandwidth-bound, so
    the jnp path is a safe default; flip the env once the probe passes."""
    import os
    d = q.shape[-1]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    sk = k_cache.shape[1]
    if (os.environ.get("PADDLE_TPU_FLASH_DECODE") == "1"
            and _platform() == "tpu" and d in _PALLAS_HEAD_DIMS
            and sk % _PALLAS_MIN_SEQ == 0):
        from .pallas.flash_attention import flash_decode as pallas_decode
        return pallas_decode(q, k_cache, v_cache, kv_lens,
                             sm_scale=sm_scale)
    return reference_attention(q, k_cache, v_cache, sm_scale=sm_scale,
                               kv_lens=kv_lens)


def paged_flash_available(head_dim, page_size, use_flash=None):
    """Gate for the paged GQA decode kernel (serving engine /
    nlp/paged_cache.py). Mirrors flash_decode's caution: the Pallas
    decode path stays OFF by default on hardware until
    PADDLE_TPU_FLASH_DECODE=1 (round-2 wedge, BENCHLOG), but an
    explicit use_flash=True forces it anywhere the SHAPE supports
    (interpret mode off-TPU — the CPU ladder/tests exercise the
    identical kernel); a forced-but-unsupported shape falls back to
    the jnp reference with a stderr warning (callers that report
    results must echo the effective gate, e.g. bench --serve's
    flash_kernel field).

    use_flash: True -> force on; False -> off; None -> auto (TPU +
    env gate + supported shape)."""
    shape_ok = head_dim in _PALLAS_HEAD_DIMS and page_size % 8 == 0
    if use_flash is False:
        return False
    if use_flash is True:
        if not shape_ok:
            import sys
            print(f"paged_flash_available: use_flash=True refused — "
                  f"head_dim={head_dim} not in {_PALLAS_HEAD_DIMS} or "
                  f"page_size={page_size} % 8 != 0; running the jnp "
                  "reference path", file=sys.stderr, flush=True)
        return shape_ok
    import os
    return (shape_ok and _platform() == "tpu"
            and os.environ.get("PADDLE_TPU_FLASH_DECODE") == "1")


def paged_flash_decode(q, k_pages, v_pages, page_table, lens,
                       k_scale=None, v_scale=None, sm_scale=None):
    """Paged GQA decode attention — Pallas kernel entry used by
    paged_cache.paged_update_and_attend when the layer cache is built
    with use_flash=True (the caller owns the gate via
    paged_flash_available). Runs the kernel natively on TPU, in
    interpret mode elsewhere so CPU tests/ladder rungs execute the
    identical kernel."""
    from .pallas.flash_decode import paged_flash_decode as kernel
    return kernel(q, k_pages, v_pages, page_table, lens,
                  k_scale=k_scale, v_scale=v_scale, sm_scale=sm_scale,
                  interpret=_platform() != "tpu")


def reference_attention(q, k, v, causal=False, sm_scale=None, kv_lens=None,
                        dropout_p=0.0, dropout_seed=0):
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    qh, kh, vh = (jnp.swapaxes(a, 1, 2) for a in (q, k, v))
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * sm_scale
    sq, sk = logits.shape[-2], logits.shape[-1]
    if causal:
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        logits = jnp.where(mask, logits, -jnp.inf)
    if kv_lens is not None:
        lm = jnp.arange(sk)[None, None, None, :] < \
            jnp.asarray(kv_lens)[:, None, None, None]
        logits = jnp.where(lm, logits, -jnp.inf)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    # fully-masked rows produce NaN softmax -> zero them (kernel outputs 0)
    probs = jnp.where(jnp.isnan(probs), 0.0, probs).astype(q.dtype)
    if dropout_p:
        key = jax.random.PRNGKey(dropout_seed)
        keep = jax.random.bernoulli(key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2)
