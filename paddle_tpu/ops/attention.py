"""Flash attention for TPU.

ref parity: paddle.nn.functional.flash_attention (CUDA flash-attn v2 in the
reference). Here: a Pallas TPU kernel (ops/pallas/flash_attention.py) tiled
for the MXU, with an XLA-fusable jnp fallback. The public entry keeps the
reference's [batch, seq, heads, head_dim] layout.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

_PALLAS_MIN_SEQ = 128
_PALLAS_HEAD_DIMS = (64, 128, 256)


def _platform():
    try:
        return jax.devices()[0].platform
    except Exception:
        return "cpu"


def flash_attention_available(q_shape, k_shape, attn_mask, dropout_p) -> bool:
    """Pallas kernel handles: TPU, no explicit mask, no dropout, seq multiple
    of block, supported head dims."""
    if attn_mask is not None or dropout_p:
        return False
    if _platform() != "tpu":
        return False
    if len(q_shape) != 4:
        return False
    b, sq, h, d = q_shape
    sk = k_shape[1]
    return (d in _PALLAS_HEAD_DIMS and sq % _PALLAS_MIN_SEQ == 0
            and sk % _PALLAS_MIN_SEQ == 0)


def flash_attention(q, k, v, causal=False, sm_scale=None):
    """[B, S, H, D] flash attention. Uses the Pallas kernel on TPU, jnp
    reference otherwise."""
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if flash_attention_available(q.shape, k.shape, None, 0.0):
        from .pallas.flash_attention import flash_attention as pallas_flash
        # On a real TPU the kernel compiles natively; if the availability
        # gate was forced on elsewhere (CPU tests), run in interpret mode so
        # the identical kernel/ad path is exercised.
        return pallas_flash(q, k, v, causal=causal, sm_scale=sm_scale,
                            interpret=_platform() != "tpu")
    return reference_attention(q, k, v, causal=causal, sm_scale=sm_scale)


def reference_attention(q, k, v, causal=False, sm_scale=None):
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    qh, kh, vh = (jnp.swapaxes(a, 1, 2) for a in (q, k, v))
    logits = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * sm_scale
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2)
