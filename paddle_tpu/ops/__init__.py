"""Custom TPU kernels (Pallas) + availability gates.

The reference ships hand-written CUDA kernels for its hot ops
(paddle/phi/kernels/gpu/flash_attn_*); here the equivalents are Pallas TPU
kernels with jnp fallbacks so every op also runs on CPU (interpret mode) for
tests.
"""
from __future__ import annotations

import jax

from .attention import (  # noqa: F401
    flash_attention, flash_attention_available, flash_decode)
from .fused import fused_rms_norm, fused_softmax_cross_entropy  # noqa: F401
