"""paddle.Model high-level API (ref: python/paddle/hapi/model.py)."""
from __future__ import annotations

import os
import warnings

import numpy as np

from ..metric import Metric
from ..nn.layer import Layer
from ..resilience import faults, preemption
from ..serialization import load as _load
from ..serialization import save as _save
from ..tensor import Tensor
from .callbacks import CallbackList, ProgBarLogger, ModelCheckpoint, config_callbacks
from .engine import Engine


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


class Model:
    """ref: paddle.Model(network, inputs=None, labels=None)."""

    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs_spec = inputs
        self._labels_spec = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._engine: Engine | None = None
        self.stop_training = False
        self._amp_dtype = None
        self._mesh = None

    # ------------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, guard=None):
        """guard: optional resilience.TrainGuard — compiles the NaN/
        inf-guarded train step (skip + snapshot/rollback semantics,
        docs/robustness.md) instead of the plain one."""
        self._optimizer = optimizer
        self._loss = loss
        ms = _to_list(metrics)
        for m in ms:
            assert isinstance(m, Metric), "metrics must be paddle_tpu.metric.Metric"
        self._metrics = ms
        if amp_configs:
            if isinstance(amp_configs, str):
                level = amp_configs
                self._amp_dtype = "bfloat16" if level in ("O1", "O2") else None
            elif isinstance(amp_configs, dict):
                level = amp_configs.get("level", "O1")
                dtype = amp_configs.get("dtype", "bfloat16")
                self._amp_dtype = dtype if level != "O0" else None
        from ..framework import convert_dtype
        amp_np = convert_dtype(self._amp_dtype) if self._amp_dtype else None
        self._engine = Engine(self.network, loss=self._loss,
                              optimizer=self._optimizer,
                              metrics=self._metrics, amp_dtype=amp_np,
                              mesh=self._mesh, guard=guard)

    def _ensure_engine(self):
        if self._engine is None:
            self._engine = Engine(self.network, loss=self._loss,
                                  optimizer=self._optimizer)
        return self._engine

    # ------------------------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        eng = self._ensure_engine()
        loss_v, outs = eng.train_batch(_to_list(inputs), _to_list(labels))
        metrics_out = self._update_metrics(outs, labels)
        # advance lr scheduler per-step like the reference's hapi loop
        # — except on a guard-SKIPPED step, where no update was applied
        # and the schedule position must track opt_step
        if eng.guard is None or eng.guard.last_outcome == "ok":
            self._lr_step_after_update()
            if eng.guard is not None:
                eng.guard.note_lr_stepped(eng)
        loss = float(np.asarray(loss_v))
        return ([loss], metrics_out) if metrics_out else [loss]

    def _train_batch_accum(self, inputs, labels, apply):
        """Gradient-accumulation microbatch (fit's accumulate_grad_batches
        path — ref: gradient_merge / accumulate_steps). The LR scheduler
        steps only on real optimizer updates."""
        eng = self._ensure_engine()
        loss_v, outs, applied = eng.train_batch_accum(
            _to_list(inputs), _to_list(labels), apply_update=apply)
        if applied:
            self._lr_step_after_update()
        metrics_out = self._update_metrics(outs, labels)
        loss = float(np.asarray(loss_v))
        return ([loss], metrics_out) if metrics_out else [loss]

    def _lr_step_after_update(self):
        from ..optimizer.lr import LRScheduler, ReduceOnPlateau
        if isinstance(self._optimizer._lr, LRScheduler) and \
                not isinstance(self._optimizer._lr, ReduceOnPlateau):
            self._optimizer._lr.step()

    def eval_batch(self, inputs, labels=None):
        eng = self._ensure_engine()
        loss_v, outs = eng.eval_batch(_to_list(inputs), _to_list(labels))
        metrics_out = self._update_metrics(outs, labels)
        loss = float(np.asarray(loss_v)) if loss_v is not None else None
        return ([loss], metrics_out) if metrics_out else [loss]

    def predict_batch(self, inputs):
        eng = self._ensure_engine()
        outs = eng.predict_batch(_to_list(inputs))
        import jax
        return jax.tree_util.tree_map(lambda a: np.asarray(a), outs)

    def _update_metrics(self, outs, labels):
        if not self._metrics:
            return None
        outs_l = outs if isinstance(outs, (list, tuple)) else [outs]
        labels_l = _to_list(labels)
        res = []
        for m in self._metrics:
            stats = m.compute(Tensor(outs_l[0]) if not isinstance(outs_l[0], Tensor)
                              else outs_l[0], *labels_l)
            r = m.update(*_to_list(stats))
            res.append(r)
        return res

    # ------------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        from ..io import DataLoader, Dataset
        assert train_data is not None
        eng = self._ensure_engine()
        if isinstance(train_data, Dataset):
            mesh = getattr(eng, "mesh", None)
            last = len(train_data) % batch_size
            if (not drop_last and mesh is not None
                    and "dp" in mesh.axis_names
                    and last and last % mesh.shape["dp"]):
                # a ragged final batch can't split over dp and the Engine
                # refuses to silently train unsharded — same policy as the
                # reference's DistributedBatchSampler, which pads/drops
                if len(train_data) < batch_size:
                    raise ValueError(
                        f"fit on a dp mesh: dataset length "
                        f"{len(train_data)} < batch_size {batch_size} and "
                        f"not divisible by dp={mesh.shape['dp']} — "
                        "dropping the ragged batch would train zero steps. "
                        "Lower batch_size or pad the dataset.")
                warnings.warn(
                    f"fit on a dp mesh: dataset length {len(train_data)} "
                    f"is not divisible by batch_size {batch_size}; "
                    "dropping the last ragged batch (drop_last=True)")
                drop_last = True
            train_loader = DataLoader(train_data, batch_size=batch_size,
                                      shuffle=shuffle, drop_last=drop_last,
                                      num_workers=num_workers)
        else:
            train_loader = train_data
        if isinstance(train_loader, DataLoader):
            train_loader._obs_role = "train"
        eval_loader = None
        if eval_data is not None:
            if isinstance(eval_data, Dataset):
                eval_loader = DataLoader(eval_data, batch_size=batch_size,
                                         num_workers=num_workers)
            else:
                eval_loader = eval_data

        steps = None
        try:
            steps = len(train_loader)
        except TypeError:
            pass
        cbks = config_callbacks(callbacks, model=self, epochs=epochs,
                                steps=steps, log_freq=log_freq,
                                save_freq=save_freq, save_dir=save_dir,
                                verbose=verbose,
                                metrics=self._metrics_name())
        cbks.on_begin("train")
        try:
            for epoch in range(epochs):
                cbks.on_epoch_begin(epoch)
                for m in self._metrics:
                    m.reset()
                logs = {}
                for step, batch in enumerate(train_loader):
                    if num_iters is not None and step >= num_iters:
                        break
                    cbks.on_batch_begin("train", step, logs)
                    ins, labs = self._split_batch(batch)
                    if accumulate_grad_batches > 1:
                        out = self._train_batch_accum(
                            ins, labs,
                            apply=(step + 1) % accumulate_grad_batches
                            == 0)
                    else:
                        out = self.train_batch(ins, labs)
                    logs = self._make_logs(out)
                    if eng.guard is not None:
                        # skip/rollback/found-inf counters ride the
                        # batch logs (ProgBar prints them, VisualDL
                        # persists)
                        logs.update(eng.guard.log_scalars())
                    logs["batch_size"] = len(np.asarray(ins[0]._value)) \
                        if isinstance(ins[0], Tensor) else batch_size
                    # resilience seams, host step boundary: the sigterm
                    # injector delivers the signal BEFORE on_batch_end
                    # so a PreemptionCheckpoint callback observes the
                    # flag at this same boundary and checkpoints; the
                    # post-callback check then ends fit cleanly either
                    # way
                    faults.maybe_sigterm(eng._step)
                    cbks.on_batch_end("train", step, logs)
                    if preemption.requested():
                        self.stop_training = True
                    if self.stop_training:
                        break
                if accumulate_grad_batches > 1:
                    # tail microbatches (epoch end / early stop /
                    # num_iters): apply the partial window instead of
                    # dropping it or leaking it into the next epoch
                    if eng.flush_accum():
                        self._lr_step_after_update()
                cbks.on_epoch_end(epoch, logs)
                if preemption.requested():
                    # the SIGTERM grace window is for the checkpoint
                    # (the PreemptionCheckpoint callback already wrote
                    # it), not for an eval pass over the whole eval set
                    break
                if eval_loader is not None and (epoch % eval_freq == 0
                                                or epoch == epochs - 1):
                    eval_logs = self.evaluate(eval_loader, verbose=0,
                                              callbacks=None,
                                              _internal=True)
                    logs.update({f"eval_{k}": v
                                 for k, v in eval_logs.items()})
                    cbks.on_eval_end(eval_logs)
                if self.stop_training:
                    break
        except Exception as e:
            # an unhandled exception in fit is a flight-recorder
            # trigger (docs/observability.md): the last N step records
            # + registry snapshot survive the crash
            self._flight_dump("fit_exception", step=eng._step,
                              error=f"{type(e).__name__}: {e}")
            raise
        cbks.on_end("train", logs)
        self._sync_weights_back()
        if preemption.requested():
            # preemption is a flight trigger too — the dump is the
            # post-mortem complement of the checkpoint the
            # PreemptionCheckpoint callback wrote
            self._flight_dump("preemption", step=eng._step)
            # the flag has been SERVICED: this fit stopped for it and
            # every checkpoint callback (incl. on_train_end) has run.
            # Left set, the process-global flag would kill any later
            # fit in this process after one batch. Supervisors should
            # read PreemptionCheckpoint.preempted, not the raw flag.
            preemption.clear()
        return self

    @staticmethod
    def _flight_dump(reason, **extra):
        try:
            from ..observability import flightrec
            flightrec.dump(reason, extra=extra or None)
        except Exception:  # noqa: BLE001 — a broken disk must not mask
            pass           # the failure being recorded

    def serve_metrics(self, port=0, host="127.0.0.1"):
        """Attach a live HTTP metrics exporter to this training run:
        /metrics is the process-global registry (everything
        TelemetryCallback and the DataLoader publish), /healthz a
        liveness doc carrying the engine step + guard stats, /report
        the recompile + compiled-cost reports. Returns the exporter
        (read .port when port=0, .close() to stop — its thread is a
        daemon, so SIGTERM'd runs exit without it). A second call
        replaces the first."""
        from ..observability.exporter import MetricsExporter
        eng = self._ensure_engine()

        def health():
            doc = {"phase": "train", "step": eng._step,
                   "opt_step": eng._opt_step}
            if eng.guard is not None:
                doc["guard"] = eng.guard.stats()
            return doc

        old = getattr(self, "_exporter", None)
        if old is not None:
            old.close()
        self._exporter = MetricsExporter(port=port, host=host,
                                         health_fn=health)
        return self._exporter

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None,
                 _internal=False):
        from ..io import DataLoader, Dataset
        if isinstance(eval_data, Dataset):
            loader = DataLoader(eval_data, batch_size=batch_size,
                                num_workers=num_workers)
        else:
            loader = eval_data
        if isinstance(loader, DataLoader):
            loader._obs_role = "eval"
        for m in self._metrics:
            m.reset()
        losses = []
        for step, batch in enumerate(loader):
            ins, labs = self._split_batch(batch)
            out = self.eval_batch(ins, labs)
            loss = out[0] if isinstance(out, tuple) else out
            if loss and loss[0] is not None:
                losses.append(loss[0])
        logs = {}
        if losses:
            logs["loss"] = [float(np.mean(losses))]
        for m in self._metrics:
            res = m.accumulate()
            names = m.name() if isinstance(m.name(), list) else [m.name()]
            vals = res if isinstance(res, list) else [res]
            for n, v in zip(names, vals):
                logs[n] = v
        if not _internal:
            self._sync_weights_back()
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        from ..io import DataLoader, Dataset
        if isinstance(test_data, Dataset):
            loader = DataLoader(test_data, batch_size=batch_size,
                                num_workers=num_workers)
        else:
            loader = test_data
        if isinstance(loader, DataLoader):
            loader._obs_role = "predict"
        outputs = []
        for batch in loader:
            ins, _ = self._split_batch(batch, predict=True)
            outs = self.predict_batch(ins)
            outputs.append(outs)
        if not outputs:
            return []
        first = outputs[0]
        n_out = len(first) if isinstance(first, (list, tuple)) else 1
        if n_out == 1:
            flat = [o if not isinstance(o, (list, tuple)) else o[0]
                    for o in outputs]
            return [np.concatenate(flat, 0)] if stack_outputs else [flat]
        cols = list(zip(*outputs))
        if stack_outputs:
            return [np.concatenate(c, 0) for c in cols]
        return [list(c) for c in cols]

    def _split_batch(self, batch, predict=False):
        if isinstance(batch, (list, tuple)):
            batch = list(batch)
            if predict:
                return _to_list(batch[0]), []
            n_in = len(self._inputs_spec) if self._inputs_spec else \
                max(len(batch) - 1, 1)
            ins = batch[:n_in]
            labs = batch[n_in:]
            return ins, labs
        return [batch], []

    def _make_logs(self, out):
        logs = {}
        if isinstance(out, tuple):
            losses, metrics = out
            logs["loss"] = losses
            names = self._metrics_name()[1:]
            for n, v in zip(names, metrics):
                logs[n] = v[0] if isinstance(v, list) and len(v) == 1 else v
        else:
            logs["loss"] = out
        return logs

    def _metrics_name(self):
        names = ["loss"]
        for m in self._metrics:
            n = m.name()
            names.extend(n if isinstance(n, list) else [n])
        return names

    def _sync_weights_back(self):
        if self._engine is not None:
            self._engine.sync_to_layer()

    # ------------------------------------------------------------------
    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def state_dict(self, *args, **kwargs):
        self._sync_weights_back()
        return self.network.state_dict(*args, **kwargs)

    def save(self, path, training=True):
        """path + '.pdparams' (weights) and '.pdopt' (optimizer) like the
        reference; training=False exports inference StableHLO via jit.save."""
        self._sync_weights_back()
        if not training:
            from .. import jit as pjit
            spec = self._inputs_spec
            pjit.save(self.network, path, input_spec=spec)
            return
        _save(self.network.state_dict(), path + ".pdparams")
        if self._optimizer is not None and self._engine is not None:
            opt = {"engine_step": self._engine._step,
                   "opt_step": self._engine._opt_step}
            import jax
            if self._engine._opt_state is not None:
                leaves, _ = jax.tree_util.tree_flatten(self._engine._opt_state)
                opt["leaves"] = [Tensor(l) for l in leaves]
            from ..optimizer.lr import LRScheduler
            if isinstance(self._optimizer._lr, LRScheduler):
                opt["LR_Scheduler"] = self._optimizer._lr.state_dict()
            _save(opt, path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        state = _load(path + ".pdparams") if not path.endswith(".pdparams") \
            else _load(path)
        missing, unexpected = self.network.set_state_dict(state)
        if (missing or unexpected) and not skip_mismatch:
            if missing:
                warnings.warn(f"missing keys: {missing}")
            if unexpected:
                warnings.warn(f"unexpected keys: {unexpected}")
        eng = self._ensure_engine()
        eng.sync_from_layer()
        eng.reset_accum_window()
        opt_path = path + ".pdopt"
        if not reset_optimizer and os.path.exists(opt_path) and \
                self._optimizer is not None:
            blob = _load(opt_path)
            eng._step = blob.get("engine_step", 0)
            eng._opt_step = blob.get("opt_step", eng._step)
            if "leaves" in blob and eng._opt_state is None and \
                    self._optimizer is not None:
                # trainable-only, matching _ensure_opt_state — including
                # frozen params here would grow the treedef and break the
                # leaf-count match below
                trainable = {n: eng._params[n]
                             for n, p in self.network.named_parameters()
                             if p.trainable and n in eng._params}
                eng._opt_state = self._optimizer.init_state(trainable)
            if "leaves" in blob and eng._opt_state is not None:
                import jax
                leaves, treedef = jax.tree_util.tree_flatten(eng._opt_state)
                new = [t._value for t in blob["leaves"]]
                eng._opt_state = jax.tree_util.tree_unflatten(treedef, new)
            from ..optimizer.lr import LRScheduler
            if "LR_Scheduler" in blob and isinstance(self._optimizer._lr,
                                                     LRScheduler):
                self._optimizer._lr.set_state_dict(blob["LR_Scheduler"])
        return self

    def summary(self, input_size=None, dtype=None):
        from .summary import summary as _summary
        if input_size is None and self._inputs_spec:
            input_size = [tuple(s.shape) for s in self._inputs_spec]
        return _summary(self.network, input_size, dtypes=dtype)
