"""hapi callbacks (ref: python/paddle/hapi/callbacks.py)."""
from __future__ import annotations

import numbers
import os
import time

import numpy as np

from ..observability.telemetry import TelemetryCallback  # noqa: F401

__all__ = ["WandbCallback", "Callback", "CallbackList", "ProgBarLogger", "ModelCheckpoint",
           "LRScheduler", "EarlyStopping", "VisualDL", "ReduceLROnPlateau",
           "PreemptionCheckpoint", "TelemetryCallback", "config_callbacks"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks=None):
        self.callbacks = list(callbacks or [])

    def append(self, cb):
        self.callbacks.append(cb)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def on_begin(self, mode, logs=None):
        for c in self.callbacks:
            getattr(c, f"on_{mode}_begin")(logs)

    def on_end(self, mode, logs=None):
        for c in self.callbacks:
            getattr(c, f"on_{mode}_end")(logs)

    def on_epoch_begin(self, epoch, logs=None):
        for c in self.callbacks:
            c.on_epoch_begin(epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        for c in self.callbacks:
            c.on_epoch_end(epoch, logs)

    def on_batch_begin(self, mode, step, logs=None):
        for c in self.callbacks:
            getattr(c, f"on_{mode}_batch_begin")(step, logs)

    def on_batch_end(self, mode, step, logs=None):
        for c in self.callbacks:
            getattr(c, f"on_{mode}_batch_end")(step, logs)

    def on_eval_end(self, logs=None):
        for c in self.callbacks:
            c.on_eval_end(logs)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")
        self.steps = self.params.get("steps")

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self._start = time.time()

    def _fmt(self, logs):
        parts = []
        for k, v in (logs or {}).items():
            if k == "batch_size":
                continue
            if isinstance(v, list):
                v = v[0] if v else None
            if isinstance(v, numbers.Number):
                parts.append(f"{k}: {v:.4f}")
        return " - ".join(parts)

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            print(f"Epoch {self.epoch + 1}/{self.epochs} step {step} "
                  f"- {self._fmt(logs)}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dur = time.time() - self._start
            print(f"Epoch {epoch + 1}/{self.epochs} done ({dur:.1f}s) "
                  f"- {self._fmt(logs)}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            os.makedirs(self.save_dir, exist_ok=True)
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler (by_step or by_epoch)."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        assert by_step ^ by_epoch
        self.by_step = by_step

    def on_epoch_end(self, epoch, logs=None):
        from ..optimizer.lr import LRScheduler as Sched
        if not self.by_step and isinstance(self.model._optimizer._lr, Sched):
            self.model._optimizer._lr.step()
    # by_step handled inside Model.train_batch


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "auto":
            mode = "min" if "loss" in monitor or "err" in monitor else "max"
        self.mode = mode
        self.wait = 0
        self.best = None

    def _better(self, cur):
        if self.best is None:
            return True
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def on_eval_end(self, logs=None):
        logs = logs or {}
        v = logs.get(self.monitor)
        if v is None:
            return
        if isinstance(v, list):
            v = v[0]
        if self._better(v):
            self.best = v
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class VisualDL(Callback):
    """Scalar logger; writes JSONL events (VisualDL-parity tracer)."""

    def __init__(self, log_dir="./log"):
        super().__init__()
        self.log_dir = log_dir
        self._f = None
        self._step = 0

    def on_train_begin(self, logs=None):
        os.makedirs(self.log_dir, exist_ok=True)
        self._f = open(os.path.join(self.log_dir, "events.jsonl"), "a")

    def _write(self, rec):
        import json
        if self._f is not None:
            self._f.write(json.dumps(rec) + "\n")

    def on_train_batch_end(self, step, logs=None):
        self._step += 1
        rec = {"step": self._step, "wall": time.time()}
        for k, v in (logs or {}).items():
            if isinstance(v, list) and v:
                v = v[0]
            if isinstance(v, numbers.Number):
                rec[k] = float(v)
        self._write(rec)

    def on_train_end(self, logs=None):
        if self._f:
            self._f.close()


class ReduceLROnPlateau(Callback):
    def __init__(self, monitor="loss", factor=0.1, patience=10, verbose=1,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0):
        super().__init__()
        self.monitor = monitor
        self.factor = factor
        self.patience = patience
        if mode == "auto":
            mode = "min" if "loss" in monitor or "err" in monitor else "max"
        self.mode = mode
        self.min_delta = min_delta
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.wait = 0
        self.best = None
        self.cool = 0

    def on_eval_end(self, logs=None):
        logs = logs or {}
        v = logs.get(self.monitor)
        if v is None:
            return
        if isinstance(v, list):
            v = v[0]
        if self.cool > 0:
            self.cool -= 1
            return
        better = (self.best is None or
                  (self.mode == "min" and v < self.best - self.min_delta) or
                  (self.mode == "max" and v > self.best + self.min_delta))
        if better:
            self.best = v
            self.wait = 0
        else:
            self.wait += 1
            if self.wait > self.patience:
                opt = self.model._optimizer
                from ..optimizer.lr import LRScheduler as Sched
                if not isinstance(opt._lr, Sched):
                    opt._lr = max(float(opt._lr) * self.factor, self.min_lr)
                self.cool = self.cooldown
                self.wait = 0


class PreemptionCheckpoint(Callback):
    """Preemption-safe checkpointing (resilience subsystem).

    Installs SIGTERM/SIGINT handlers on train begin; when a signal
    lands, the NEXT batch boundary writes a full training-state
    checkpoint (params + optimizer moments + update counters + LR
    schedule + scaler) through a CheckpointManager — whose COMPLETE-
    marker finalize makes the write crash-safe — then stops fit
    cleanly. Resume with `resilience.preemption.restore_training_state
    (model, manager)` before the next fit: loss-exact continuation.

    every_n_steps > 0 also writes periodic checkpoints at that engine-
    step cadence, so an un-graceful kill (SIGKILL, node loss) costs at
    most that window.
    """

    def __init__(self, manager, every_n_steps=0, install_handlers=True,
                 metric_key=None):
        super().__init__()
        self.manager = manager
        self.every_n_steps = int(every_n_steps)
        self.install_handlers = install_handlers
        self.metric_key = metric_key
        self.preempted = False
        self.saved_step = None

    def _metric(self, logs):
        v = (logs or {}).get(self.metric_key) if self.metric_key else None
        if isinstance(v, (list, tuple)):
            v = v[0] if v else None
        return float(v) if isinstance(v, numbers.Number) else None

    def _save(self, logs):
        from ..resilience.preemption import save_training_state
        self.saved_step = save_training_state(
            self.model, self.manager, metric=self._metric(logs))
        return self.saved_step

    def on_train_begin(self, logs=None):
        # a reused callback object (resumed fit in the same process)
        # must be able to checkpoint a SECOND preemption
        self.preempted = False
        self.saved_step = None
        if self.install_handlers:
            from ..resilience import preemption
            preemption.install()

    def on_train_batch_end(self, step, logs=None):
        from ..resilience import preemption
        eng = self.model._engine
        if (self.every_n_steps and eng is not None
                and eng._step % self.every_n_steps == 0):
            self._save(logs)
        if preemption.requested() and not self.preempted:
            self.preempted = True
            self._save(logs)
            self.manager.wait()  # the checkpoint MUST be on disk and
            #                      finalized before fit returns — the
            #                      grace window may be nearly spent
            self.model.stop_training = True

    def on_train_end(self, logs=None):
        # a signal that landed after the last batch boundary (eval,
        # epoch end) still gets its checkpoint
        from ..resilience import preemption
        if preemption.requested() and not self.preempted:
            self.preempted = True
            self._save(logs)
        self.manager.wait()


def config_callbacks(callbacks=None, model=None, epochs=None, steps=None,
                     log_freq=2, verbose=2, save_freq=1, save_dir=None,
                     metrics=None, mode="train"):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks.append(ProgBarLogger(log_freq, verbose=verbose))
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks.append(LRScheduler())
    if not any(isinstance(c, ModelCheckpoint) for c in cbks) and save_dir:
        cbks.append(ModelCheckpoint(save_freq, save_dir))
    lst = CallbackList(cbks)
    lst.set_model(model)
    lst.set_params({"epochs": epochs, "steps": steps, "verbose": verbose,
                    "metrics": metrics or []})
    return lst


class WandbCallback(Callback):
    """ref: paddle.callbacks.WandbCallback — logs to Weights & Biases when
    the `wandb` package is installed; otherwise falls back to the JSONL
    tracer (same schema as VisualDL) so the metrics are never lost."""

    def __init__(self, project=None, name=None, dir=None, **kwargs):
        super().__init__()
        self._wandb = None
        self._fallback = None
        try:
            import wandb
            self._wandb = wandb
            self._init_kwargs = dict(project=project, name=name, dir=dir,
                                     **kwargs)
        except ImportError:
            self._fallback = VisualDL(log_dir=dir or "./wandb_fallback")

    @staticmethod
    def _scalars(logs):
        out = {}
        for k, v in (logs or {}).items():
            if isinstance(v, (list, tuple)) and v:
                v = v[0]
            if isinstance(v, (int, float)):
                out[k] = float(v)
        return out

    def on_train_begin(self, logs=None):
        if self._wandb is not None:
            self._run = self._wandb.init(**self._init_kwargs)
        elif self._fallback is not None:
            self._fallback.on_train_begin(logs)

    def on_train_batch_end(self, step, logs=None):
        if self._fallback is not None:
            self._fallback.on_train_batch_end(step, logs)

    def on_epoch_end(self, epoch, logs=None):
        scalars = self._scalars(logs)
        if self._wandb is not None:
            self._run.log({f"train/{k}": v for k, v in scalars.items()})
        elif self._fallback is not None:
            # VisualDL records per batch; emit an explicit epoch record so
            # epoch-level metrics land in the JSONL too
            self._fallback._write({"event": "epoch", "epoch": epoch,
                                   **scalars})

    def on_train_end(self, logs=None):
        if self._wandb is not None:
            self._run.finish()
        elif self._fallback is not None:
            self._fallback.on_train_end(logs)
