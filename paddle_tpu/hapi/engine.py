"""Engine: compiles (model, loss, optimizer) into ONE jitted train step.

ref: the reference's Model.fit dispatches per-op through the dygraph tracer
(or builds a static Program under @to_static). TPU-native: the entire
step — forward, loss, backward, grad clip, optimizer update, running-stat
updates — is a single pure function of (params, buffers, opt_state, lr,
rng, batch), compiled once by XLA with buffer donation so parameter update
is in-place in HBM. Data parallelism: pass a Mesh and the batch is sharded
over 'dp' while params follow their annotated shardings (GSPMD inserts the
grad psum — the moral equivalent of fleet's allreduce hooks).
"""
from __future__ import annotations

import weakref
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..nn.layer import Layer, functional_call
from ..observability.trace import RecompileTracer
from ..optimizer.lr import LRScheduler
from ..tensor import Tensor


def _unwrap(x):
    return jax.tree_util.tree_map(
        lambda t: t._value if isinstance(t, Tensor) else (
            jnp.asarray(t) if isinstance(t, np.ndarray) else t), x,
        is_leaf=lambda t: isinstance(t, Tensor))


def _global_grad_norm(grads):
    """Global L2 norm over every gradient leaf, fp32. Computed INSIDE
    the compiled step (the reductions fuse into the backward pass's
    epilogue — no extra dispatch); surfaced as Engine.last_grad_norm
    for the telemetry layer, which syncs it lazily."""
    leaves = [g for g in jax.tree_util.tree_leaves(grads)
              if hasattr(g, "dtype")]
    if not leaves:
        return jnp.float32(0.0)
    return jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                        for g in leaves))


class Engine:
    def __init__(self, network: Layer, loss=None, optimizer=None,
                 metrics=None, amp_dtype=None, mesh=None,
                 donate_params=True, guard=None):
        self.network = network
        self.loss = loss
        self.optimizer = optimizer
        if optimizer is not None:
            import weakref
            optimizer._engine_ref = weakref.ref(self)
        self.metrics = metrics or []
        self.amp_dtype = amp_dtype
        self.mesh = mesh
        self.donate = donate_params
        # resilience.TrainGuard: when set, train_batch compiles the
        # guarded step variant (fused all-finite check, masked update,
        # optional in-step GradScaler state) — see _build_guarded_fn.
        # A property: assigning engine.guard (attach OR detach) drops
        # the compiled step, whose signature depends on guard presence
        self._guard = guard
        self._scaler_state = None
        self._params, self._buffers = network.raw_state()
        self._opt_state = None
        self._step = 0
        self._train_fn = None
        self._multi_fns = {}
        self._eval_fn = None
        self._pred_fn = None
        self._rng_key = jax.random.PRNGKey(0)
        # recompile accounting (docs/observability.md): every jitted
        # entry point below is wrapped by this tracer, so "the train
        # step retraced mid-run" is a queryable run fact, not a
        # mystery slowdown. The device-resident grad norm of the last
        # fused step rides here for telemetry (no sync until read).
        from ..observability.metrics import get_registry
        self.tracer = RecompileTracer(name="engine",
                                      registry=get_registry())
        # retire the tracer when this Engine is collected: repeated
        # Engine construction (sweeps, notebooks, pytest) must not grow
        # the process-wide live-tracer list; close() keeps the site
        # aggregates visible to report_all() via the bounded
        # closed-report ring
        weakref.finalize(self, self.tracer.close)
        # grad-norm telemetry is OPT-IN: the reduction is fused into
        # the step but is still a real all-gradients fp32 reduce XLA
        # cannot dead-code-eliminate (it is a program output) — a bare
        # Engine run stays measurement-neutral vs pre-telemetry
        # baselines. TelemetryCallback enables it at train begin,
        # before the step first builds.
        self.collect_grad_norm = False
        self.last_grad_norm = None
        self._train_fn_collects_gnorm = False
        # gradient accumulation (two extra jitted programs, built lazily)
        self._grad_fn = None
        self._apply_fn = None
        self._acc_grads = None
        self._micro_count = 0
        # optimizer updates, NOT microbatches: Adam's bias correction
        # must see the number of update() calls
        self._opt_step = 0

    # ------------------------------------------------------------------
    def sync_from_layer(self):
        self._params, self._buffers = self.network.raw_state()

    def sync_to_layer(self):
        self.network.load_raw_state(self._params, self._buffers)

    def _shard_batch(self, arrs, allow_ragged=False):
        if self.mesh is None or "dp" not in self.mesh.axis_names:
            return arrs
        from jax.sharding import NamedSharding, PartitionSpec
        sh = NamedSharding(self.mesh, PartitionSpec("dp"))
        ndp = self.mesh.shape["dp"]

        def place(a):
            if not (hasattr(a, "ndim") and a.ndim >= 1):
                return a
            if a.shape[0] % ndp == 0:
                return jax.device_put(a, sh)
            if allow_ragged:
                # eval's last DataLoader batch (no drop_last): run it
                # replicated rather than raising mid-epoch
                return a
            raise ValueError(
                f"training batch dim {a.shape[0]} is not divisible by the "
                f"dp mesh axis ({ndp}): every train step would silently "
                "lose data parallelism. Use a divisible batch_size or "
                "drop_last=True.")
        return jax.tree_util.tree_map(place, arrs)

    # ------------------------------------------------------------------
    def _trainable_keys(self):
        # frozen (trainable=False) params are closed over as constants of
        # the step — they get no grads and no optimizer update (parity with
        # the eager Optimizer.step's p.trainable filter)
        return {n for n, p in self.network.named_parameters() if p.trainable}

    def _grad_shardings(self, trainable_keys):
        """GroupSharded/ZeRO stage 2+: constraints that make XLA lower
        the dp grad-sum to reduce-scatter (None when not sharding)."""
        gs = getattr(self.optimizer, "_group_sharded", None)
        if gs is None or not gs.shard_grads:
            return None
        from jax.sharding import NamedSharding
        from ..distributed.fleet.sharding import constraint_specs
        live_arrs = {k: v for k, v in self._params.items()
                     if k in trainable_keys}
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(gs.mesh, s),
            constraint_specs(live_arrs, gs.mesh, gs.axis))

    @staticmethod
    def _make_loss_fn(network, loss_layer, amp_dt, frozen, buffers,
                      inputs, labels, rng):
        """The forward+loss closure shared by the fused train step and
        the accumulation grad step (single source of truth for the AMP
        cast and buffer-dtype-restore logic)."""
        def loss_fn(p):
            run_p = {**frozen, **p}
            run_in = inputs
            if amp_dt is not None:
                cast = jax.tree_util.tree_map(
                    lambda a: a.astype(amp_dt)
                    if jnp.issubdtype(a.dtype, jnp.floating) else a,
                    (run_p, list(inputs)))
                run_p, run_in = cast
            outs, new_buf = functional_call(
                network, run_p, buffers, *run_in, rng=rng, mutable=True)
            if amp_dt is not None:
                # keep running stats at their original dtype so the step
                # signature is stable (no recompile) and stats stay fp32
                new_buf = jax.tree_util.tree_map(
                    lambda n, o: n.astype(o.dtype)
                    if hasattr(n, "astype") else n, new_buf, buffers)
            outs_t = outs if isinstance(outs, (list, tuple)) else [outs]
            if loss_layer is not None:
                l = loss_layer(*outs_t, *labels)
            else:
                l = outs_t[0]
            l_arr = l._value if isinstance(l, Tensor) else l
            if isinstance(outs, dict) and outs.get("_loss_only_aux"):
                # model-agnostic convention: a dict output marked
                # _loss_only_aux feeds ONLY the criterion (e.g. GPT's
                # fused head+CE passes the tied weight) — returning it
                # from the compiled step would materialize those
                # tensors as extra program outputs every step
                outs = ()
            return l_arr.astype(jnp.float32), (_unwrap(outs), new_buf)
        return loss_fn

    @property
    def guard(self):
        return self._guard

    @guard.setter
    def guard(self, g):
        # the guarded and plain steps have different signatures; a
        # stale executable from the other mode would mis-bind args.
        # The scaler state belongs to the outgoing guard's scaler —
        # a new guard's scaler re-initializes from ITS init scale
        self._guard = g
        self._train_fn = None
        self._multi_fns = {}
        self._scaler_state = None

    def attach_guard(self, guard):
        """Attach (or with None, detach) a resilience.TrainGuard: the
        next train_batch builds the matching step variant."""
        self.guard = guard
        return guard

    def enable_grad_norm(self):
        """Ask the compiled train step to also output the global grad
        L2 norm (Engine.last_grad_norm, synced lazily). Takes effect
        when the step next builds: enabling before the first batch
        (TelemetryCallback does this at train begin) is free; enabling
        mid-run deliberately does NOT drop an already-compiled step —
        that rebuild would be exactly the unexpected retrace the
        tracer exists to catch."""
        self.collect_grad_norm = True

    def _build_guarded_fn(self):
        """Guarded train step (resilience.TrainGuard's compiled half).

        Same single-dispatch structure as _build_train_fn plus, fused
        into the SAME XLA program (the finite-checks are reductions
        over tensors the step already produced — no extra launch):

        - `fault_scale` scalar multiplied into the loss pre-autodiff
          (1.0 normally; the nan_grads injector passes NaN, poisoning
          loss and every grad at once);
        - an all-finite flag over loss + every gradient leaf;
        - param/buffer/optimizer updates MASKED by that flag — a bad
          step is a perfect no-op on model state (the host also skips
          the opt_step increment, so Adam bias correction and the
          GradScaler never see skipped steps);
        - optional GradScaler state threaded through: loss scaled
          pre-grad, grads unscaled pre-check, dynamic scale updated
          from the found-inf flag (functional_update).
        """
        network = self.network
        loss_layer = self.loss
        opt = self.optimizer
        clip = getattr(opt, "_grad_clip", None)
        amp_dt = self.amp_dtype
        trainable_keys = self._trainable_keys()
        grad_shardings = self._grad_shardings(trainable_keys)
        make_loss_fn = self._make_loss_fn
        collect_gnorm = self.collect_grad_norm
        self._train_fn_collects_gnorm = collect_gnorm
        scaler = self.guard.scaler if self.guard is not None else None
        use_scaler = scaler is not None
        if use_scaler:
            from ..amp import GradScaler as _GS
            s_incr, s_decr = scaler._incr_ratio, scaler._decr_ratio
            s_incr_n, s_decr_n = scaler._incr_every, scaler._decr_every

        def train_step(params, buffers, opt_state, scaler_state, lr,
                       step_i, opt_step_i, rng, fault_scale, inputs,
                       labels):
            rng = jax.random.fold_in(rng, step_i)
            frozen = {k: v for k, v in params.items()
                      if k not in trainable_keys}
            live = {k: v for k, v in params.items() if k in trainable_keys}
            loss_fn = make_loss_fn(network, loss_layer, amp_dt, frozen,
                                   buffers, inputs, labels, rng)

            def guarded_loss(p):
                l, (outs, new_buf) = loss_fn(p)
                l = l * fault_scale
                ls = l * scaler_state["scale"] if use_scaler else l
                return ls, (l, outs, new_buf)

            (_, (loss_v, outs, new_buf)), grads = jax.value_and_grad(
                guarded_loss, has_aux=True)(live)
            if use_scaler:
                inv = 1.0 / scaler_state["scale"]
                grads = jax.tree_util.tree_map(lambda g: g * inv, grads)
            if grad_shardings is not None:
                grads = jax.lax.with_sharding_constraint(
                    grads, grad_shardings)
            ok = jnp.isfinite(loss_v)
            for g in jax.tree_util.tree_leaves(grads):
                ok = ok & jnp.all(jnp.isfinite(g))
            gnorm = _global_grad_norm(grads) if collect_gnorm \
                else jnp.float32(0.0)
            if clip is not None:
                grads = clip.apply(grads)
            new_live, new_opt = opt.update(live, grads, opt_state,
                                           lr, opt_step_i)

            def mask(new, old):
                # elementwise select, NOT arithmetic: NaNs in the
                # discarded branch must not propagate
                return jax.tree_util.tree_map(
                    lambda n, o: jnp.where(ok, n, o)
                    if hasattr(n, "dtype") else n, new, old)

            new_live = mask(new_live, live)
            new_opt = mask(new_opt, opt_state)
            new_buf = mask(new_buf, buffers)
            if use_scaler:
                scaler_state = _GS.functional_update(
                    scaler_state, ~ok, incr_ratio=s_incr,
                    decr_ratio=s_decr, incr_every=s_incr_n,
                    decr_every=s_decr_n)
            return ({**frozen, **new_live}, new_buf, new_opt,
                    scaler_state, loss_v, ok, gnorm, outs)

        donate = (0, 1, 2) if self.donate else ()
        return self.tracer.jit("train_step_guarded", train_step,
                               donate_argnums=donate)

    def _build_train_fn(self):
        if self.guard is not None:
            return self._build_guarded_fn()
        network = self.network
        loss_layer = self.loss
        opt = self.optimizer
        clip = getattr(opt, "_grad_clip", None)
        amp_dt = self.amp_dtype
        trainable_keys = self._trainable_keys()
        grad_shardings = self._grad_shardings(trainable_keys)
        make_loss_fn = self._make_loss_fn
        collect_gnorm = self.collect_grad_norm
        self._train_fn_collects_gnorm = collect_gnorm

        def train_step(params, buffers, opt_state, lr, step_i, opt_step_i,
                       rng, inputs, labels):
            # per-step randomness folds from a CONSTANT base key inside the
            # compiled step — splitting on the host would cost device ops
            # (and, on a remote backend, round trips) every iteration.
            # step_i counts CALLS (unique rng per batch); opt_step_i counts
            # optimizer UPDATES (Adam bias correction) — they differ once
            # gradient accumulation has run in the same session.
            rng = jax.random.fold_in(rng, step_i)
            frozen = {k: v for k, v in params.items()
                      if k not in trainable_keys}
            live = {k: v for k, v in params.items() if k in trainable_keys}
            loss_fn = make_loss_fn(network, loss_layer, amp_dt, frozen,
                                   buffers, inputs, labels, rng)
            (loss_v, (outs, new_buf)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(live)
            if grad_shardings is not None:
                grads = jax.lax.with_sharding_constraint(
                    grads, grad_shardings)
            gnorm = _global_grad_norm(grads) if collect_gnorm \
                else jnp.float32(0.0)
            if clip is not None:
                grads = clip.apply(grads)
            new_live, new_opt = opt.update(live, grads, opt_state,
                                           lr, opt_step_i)
            return ({**frozen, **new_live}, new_buf, new_opt, loss_v,
                    gnorm, outs)

        donate = (0, 1, 2) if self.donate else ()
        return self.tracer.jit("train_step", train_step,
                               donate_argnums=donate)

    def _build_accum_fns(self):
        """Gradient accumulation as TWO compiled programs (ref: the
        reference's gradient_merge / accumulate_steps): `grad_fn` runs
        forward+backward for one microbatch and adds into a donated
        fp32 accumulator; `apply_fn` averages, clips and applies the
        optimizer once per k microbatches. Splitting keeps each program
        static — no data-dependent 'is this the k-th call' inside jit."""
        network = self.network
        loss_layer = self.loss
        opt = self.optimizer
        clip = getattr(opt, "_grad_clip", None)
        amp_dt = self.amp_dtype
        trainable_keys = self._trainable_keys()
        grad_shardings = self._grad_shardings(trainable_keys)
        make_loss_fn = self._make_loss_fn

        donate = self.donate

        def grad_step(params, buffers, acc, step_i, rng, inputs, labels):
            rng = jax.random.fold_in(rng, step_i)
            frozen = {k: v for k, v in params.items()
                      if k not in trainable_keys}
            live = {k: v for k, v in params.items() if k in trainable_keys}
            loss_fn = make_loss_fn(network, loss_layer, amp_dt, frozen,
                                   buffers, inputs, labels, rng)
            (loss_v, (outs, new_buf)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(live)
            grads32 = jax.tree_util.tree_map(
                lambda g: g.astype(jnp.float32), grads)
            if grad_shardings is not None:
                # keep the fp32 accumulator sharded too — a replicated
                # accumulator would undo ZeRO-2's memory win
                grads32 = jax.lax.with_sharding_constraint(
                    grads32, grad_shardings)
            acc_out = jax.tree_util.tree_map(
                lambda a, g: a + g, acc, grads32)
            return acc_out, new_buf, loss_v, outs

        def apply_step(params, opt_state, acc, n_micro, lr, step_i):
            frozen = {k: v for k, v in params.items()
                      if k not in trainable_keys}
            live = {k: v for k, v in params.items() if k in trainable_keys}
            grads = jax.tree_util.tree_map(
                lambda a, p: (a / n_micro).astype(p.dtype), acc, live)
            if clip is not None:
                grads = clip.apply(grads)
            new_live, new_opt = opt.update(live, grads, opt_state,
                                           lr, step_i)
            if not donate:
                # nothing to alias into without donation — returning a
                # zero tree would just be a param-size transient
                return {**frozen, **new_live}, new_opt, None
            # return the accumulator ZEROED: the donated acc buffer gets
            # an in-place output alias (no param-size dead donation — the
            # source of the 'donated buffers were not usable' warning)
            # and the next window starts from it without re-allocating
            new_acc = jax.tree_util.tree_map(jnp.zeros_like, acc)
            return {**frozen, **new_live}, new_opt, new_acc

        grad_jit = self.tracer.jit(
            "grad_step", grad_step,
            donate_argnums=(2,) if self.donate else ())
        apply_jit = self.tracer.jit(
            "apply_step", apply_step,
            donate_argnums=(0, 1, 2) if self.donate else ())
        return grad_jit, apply_jit

    def _ensure_opt_state(self):
        """Lazy optimizer-state init shared by the fused and accumulation
        paths — including the set_state_dict pending-leaves restore."""
        if self._opt_state is not None:
            return
        trainable = {n: self._params[n]
                     for n, p in self.network.named_parameters()
                     if p.trainable and n in self._params}
        self._opt_state = self.optimizer.init_state(trainable)
        pending = getattr(self.optimizer, "_pending_state_leaves", None)
        if pending is not None:
            leaves, treedef = jax.tree_util.tree_flatten(self._opt_state)
            if len(pending) == len(leaves):
                self._opt_state = jax.tree_util.tree_unflatten(
                    treedef, pending)
            self.optimizer._pending_state_leaves = None
        self._apply_zero_placement()

    def train_batch_accum(self, inputs, labels, apply_update):
        """One microbatch of gradient accumulation; pass
        apply_update=True on the last microbatch to run the optimizer on
        the averaged gradients. Returns (loss, outs, applied)."""
        if self.guard is not None:
            raise ValueError(
                "TrainGuard covers the fused train_batch path only — "
                "gradient accumulation splits the step into two "
                "programs and a half-guarded window would mask grads "
                "but not the accumulator. Detach (engine.guard = None)"
                " or use accumulate_grad_batches=1.")
        if self.network.training is False:
            self.network.train()
        self._ensure_opt_state()
        if self._grad_fn is None:
            self._grad_fn, self._apply_fn = self._build_accum_fns()
        in_arrs = self._shard_batch(_unwrap(list(inputs)))
        lab_arrs = self._shard_batch(_unwrap(list(labels)))
        self._step += 1
        if self._acc_grads is None:
            # zeros-init at window start keeps grad_step a single trace
            # (an acc=None variant would be a second compiled program).
            # Under ZeRO the zeros are created ON their grad shardings —
            # a replicated fp32 accumulator would cost full-model memory
            # per device, the exact thing stage 2 shards away
            trainable_keys = self._trainable_keys()
            shardings = self._grad_shardings(trainable_keys)
            self._acc_grads = {}
            for k, v in self._params.items():
                if k not in trainable_keys:
                    continue
                z = jnp.zeros(v.shape, jnp.float32)
                if shardings is not None and k in shardings:
                    z = jax.device_put(z, shardings[k])
                self._acc_grads[k] = z
        self._acc_grads, self._buffers, loss_v, outs = self._grad_fn(
            self._params, self._buffers, self._acc_grads,
            np.int32(self._step), self._rng_key, in_arrs, lab_arrs)
        # this path computes no global grad norm: clear the fused-step
        # value so telemetry never reports a stale one as current
        self.last_grad_norm = None
        self._micro_count += 1
        applied = False
        if apply_update:
            applied = self._apply_accum()
        return loss_v, outs, applied

    def _apply_accum(self):
        if not self._micro_count or self._acc_grads is None:
            return False
        lr = np.float32(self._lr_now())
        self._opt_step += 1
        self._params, self._opt_state, new_acc = self._apply_fn(
            self._params, self._opt_state, self._acc_grads,
            np.float32(self._micro_count), lr, np.int32(self._opt_step))
        # under donation, new_acc is the zeroed (still correctly
        # ZeRO-sharded) accumulator aliased in place — keep it so the
        # next window starts without re-allocating; without donation
        # apply_step returns None (retention would just pin an extra
        # param-size fp32 buffer)
        self._acc_grads = new_acc
        self._micro_count = 0
        if self.donate:
            self.network.load_raw_state(self._params, self._buffers)
        return True

    def flush_accum(self):
        """Apply any partially-accumulated window (epoch end, early stop,
        num_iters cutoff) so tail microbatch gradients are never dropped
        or leaked into the next fit. Returns True if an update ran.

        Also drops the retained zeroed accumulator: at a flush boundary
        (fit exit, path switch) training may be followed by eval/serving,
        where a param-size fp32 buffer held for reuse is pure overhead."""
        applied = self._apply_accum()
        self._acc_grads = None
        return applied

    def reset_accum_window(self):
        """Drop any half-accumulated gradient window WITHOUT applying it.
        Call after restoring params/opt state from a checkpoint: grads
        computed against the pre-restore parameters must not be averaged
        into the first post-restore update."""
        self._acc_grads = None
        self._micro_count = 0

    def _build_eval_fn(self):
        network = self.network
        loss_layer = self.loss

        def eval_step(params, buffers, inputs, labels):
            outs = functional_call(network, params, buffers, *inputs)
            outs_t = outs if isinstance(outs, (list, tuple)) else [outs]
            l_arr = None
            if loss_layer is not None and labels:
                l = loss_layer(*outs_t, *labels)
                l_arr = (l._value if isinstance(l, Tensor) else l).astype(jnp.float32)
            return _unwrap(outs), l_arr

        return self.tracer.jit("eval_step", eval_step)

    # ------------------------------------------------------------------
    def _lr_now(self):
        opt = self.optimizer
        if opt is None:
            return 0.0
        lr = opt._lr
        if isinstance(lr, LRScheduler):
            return float(lr())
        return float(lr)

    def train_batch(self, inputs, labels):
        """One optimizer step. inputs/labels: lists of Tensors/arrays."""
        if self.guard is not None:
            return self._train_batch_guarded(inputs, labels)
        if self.network.training is False:
            self.network.train()
        self._ensure_opt_state()
        if self._micro_count:
            # a pending accumulation window must not leak into (or be
            # invalidated by) a fused step — apply the partial window now;
            # flush_accum (not _apply_accum) so the path switch also
            # drops the retained accumulator buffer
            self.flush_accum()
        if self._train_fn is None:
            self._train_fn = self._build_train_fn()
        in_arrs = self._shard_batch(_unwrap(list(inputs)))
        lab_arrs = self._shard_batch(_unwrap(list(labels)))
        # host-side numpy scalars: they ride along with the execute call
        # instead of costing standalone device ops each step
        lr = np.float32(self._lr_now())
        self._step += 1
        self._opt_step += 1
        (self._params, self._buffers, self._opt_state, loss_v,
         gnorm, outs) = self._train_fn(
            self._params, self._buffers, self._opt_state,
            lr, np.int32(self._step),
            np.int32(self._opt_step), self._rng_key,
            in_arrs, lab_arrs)
        self.last_grad_norm = gnorm if self._train_fn_collects_gnorm \
            else None
        # donation deleted the old param/buffer jax arrays: rebind the live
        # Parameter tensors to the new ones so direct network access (eager
        # forward, state_dict, .numpy()) stays valid mid-fit
        if self.donate:
            self.network.load_raw_state(self._params, self._buffers)
        return loss_v, outs

    def _train_batch_guarded(self, inputs, labels):
        """train_batch through the TrainGuard: guarded step dispatch
        with transient-error retry, host-synced finite flag, skip/
        snapshot/rollback bookkeeping. Returns (loss, outs) like
        train_batch — on a skipped step the loss is the (non-finite)
        observed value and model state is unchanged."""
        from ..resilience import faults
        from ..resilience.retry import call_with_retries
        guard = self.guard
        if self.network.training is False:
            self.network.train()
        self._ensure_opt_state()
        if self._micro_count:
            self.flush_accum()
        if self._train_fn is None:
            self._train_fn = self._build_train_fn()
        if guard.scaler is not None and self._scaler_state is None:
            from ..amp import GradScaler
            self._scaler_state = GradScaler.functional_init(
                guard.scaler._scale)
        guard.before_first_step(self)
        in_arrs = self._shard_batch(_unwrap(list(inputs)))
        lab_arrs = self._shard_batch(_unwrap(list(labels)))
        lr = np.float32(self._lr_now())
        self._step += 1
        step = self._step
        # injection seams: NaN-poison scalar rides the stable step
        # signature (no recompile); slow/dispatch faults drill the
        # watchdog + retry paths
        fault_scale = np.float32(faults.nan_scale(step))
        faults.maybe_sleep("slow_step", step)

        def dispatch():
            # injected transients fire BEFORE the execute call, so a
            # retry re-submits un-consumed (un-donated) buffers
            faults.maybe_raise("dispatch_error", step)
            return self._train_fn(
                self._params, self._buffers, self._opt_state,
                self._scaler_state, lr, np.int32(step),
                np.int32(self._opt_step + 1), self._rng_key,
                fault_scale, in_arrs, lab_arrs)

        from ..resilience.retry import retryable_for
        (self._params, self._buffers, self._opt_state,
         self._scaler_state, loss_v, ok_flag, gnorm,
         outs) = call_with_retries(
            dispatch, retries=guard.retries,
            retryable=retryable_for(self.donate),
            base_delay=guard.retry_base_delay, stats=guard.retry_stats)
        self.last_grad_norm = gnorm if self._train_fn_collects_gnorm \
            else None
        # ONE host sync for the flag (Model.train_batch syncs the loss
        # anyway); the tentative opt_step+1 the step saw is only
        # committed on a good step, so skips never advance Adam's bias
        # correction
        ok = bool(np.asarray(ok_flag))
        if ok:
            self._opt_step += 1
        if self.donate:
            self.network.load_raw_state(self._params, self._buffers)
        guard.after_step(self, ok)
        return loss_v, outs

    def train_batch_multi(self, inputs, labels, lr_values=None):
        """Run K optimizer steps in ONE device dispatch: inputs/labels
        are lists of STACKED arrays [K, batch, ...] and the K steps run
        inside a compiled lax.scan.

        TPU-native perf lever: each dispatch to a (remote) backend costs
        ~ms of latency; a K-step scan amortizes it K-fold (bench.py
        --scan-steps uses the same construction — this is its public
        form). Semantics match K train_batch calls exactly (per-step rng
        folding, update counters), with the learning rate CONSTANT
        across the window unless lr_values [K] supplies a schedule; the
        LR scheduler object is advanced by the caller per update as
        usual. A pending gradient-accumulation window is flushed first.
        Returns (losses [K], None) — per-step model outputs are not
        materialized (that would double-compute the last forward); use
        train_batch when outputs/metrics are needed."""
        if self.guard is not None:
            raise ValueError(
                "TrainGuard and train_batch_multi are mutually "
                "exclusive: the guarded step's signature (fault scalar,"
                " scaler state, finite flag) does not fit the K-step "
                "scan closure. Use train_batch, or detach the guard "
                "(engine.guard = None).")
        if self.network.training is False:
            self.network.train()
        self._ensure_opt_state()
        if self._micro_count:
            self.flush_accum()
        if self._train_fn is None:
            self._train_fn = self._build_train_fn()
        in_arrs = self._shard_batch_stacked(_unwrap(list(inputs)))
        lab_arrs = self._shard_batch_stacked(_unwrap(list(labels)))
        lead = {a.shape[0] for a in jax.tree_util.tree_leaves(
            (in_arrs, lab_arrs)) if hasattr(a, "shape") and a.ndim >= 1}
        if len(lead) != 1:
            # validate BEFORE touching counters: a failed call must not
            # skew _step/_opt_step (rng folds + Adam bias correction)
            raise ValueError(
                f"stacked inputs/labels disagree on K: {sorted(lead)}")
        k = int(next(iter(lead)))
        if lr_values is None:
            lrs = np.full((k,), self._lr_now(), np.float32)
        else:
            lrs = np.asarray(lr_values, np.float32)
            if lrs.shape != (k,):
                raise ValueError(f"lr_values must have shape ({k},)")
        # cache key includes the train_fn identity: any site that
        # rebuilds _train_fn (resume/re-placement) invalidates these
        # closures implicitly, with no second attribute to remember
        cache_key = (k, id(self._train_fn))
        multi = self._multi_fns.get(cache_key)
        if multi is None:
            fn = self._train_fn

            def multi_step(params, buffers, opt_state, lrs, step0,
                           opt_step0, rng, ins, labs):
                def body(carry, xs):
                    p, b, s = carry
                    i, lr_i, xi, yi = xs
                    p, b, s, loss_i, _gn, _ = fn(
                        p, b, s, lr_i, step0 + i, opt_step0 + i, rng,
                        list(xi), list(yi))
                    return (p, b, s), loss_i
                (p, b, s), losses = jax.lax.scan(
                    body, (params, buffers, opt_state),
                    (jnp.arange(k, dtype=jnp.int32), lrs,
                     tuple(ins), tuple(labs)))
                # one extra forward for the last step's outputs would
                # double-compute; callers needing per-step outputs
                # should use train_batch
                return p, b, s, losses

            multi = self.tracer.jit("train_step_multi", multi_step,
                                    donate_argnums=(0, 1, 2)
                                    if self.donate else ())
            if len(self._multi_fns) > 8:
                self._multi_fns.clear()
            self._multi_fns[cache_key] = multi
        step0, opt_step0 = self._step + 1, self._opt_step + 1
        self._step += k
        self._opt_step += k
        (self._params, self._buffers, self._opt_state, losses) = multi(
            self._params, self._buffers, self._opt_state, lrs,
            np.int32(step0), np.int32(opt_step0), self._rng_key,
            in_arrs, lab_arrs)
        # the scan discards per-step grad norms: clear the fused-step
        # value so telemetry never reports a stale one as current
        self.last_grad_norm = None
        if self.donate:
            self.network.load_raw_state(self._params, self._buffers)
        return losses, None

    def _shard_batch_stacked(self, arrs):
        """dp placement for [K, batch, ...] stacks: batch is dim 1
        (tree-mapped like _shard_batch, so nested containers work)."""
        if self.mesh is None or "dp" not in self.mesh.axis_names:
            return arrs
        from jax.sharding import NamedSharding, PartitionSpec
        sh = NamedSharding(self.mesh, PartitionSpec(None, "dp"))
        ndp = self.mesh.shape["dp"]

        def place(a):
            if not (hasattr(a, "ndim") and a.ndim >= 2):
                return a
            if a.shape[1] % ndp:
                raise ValueError(
                    f"stacked batch dim {a.shape[1]} not divisible by "
                    f"the dp mesh axis ({ndp})")
            return jax.device_put(a, sh)
        return jax.tree_util.tree_map(place, arrs)

    def eval_batch(self, inputs, labels=()):
        if self.network.training:
            self.network.eval()
        if self._eval_fn is None:
            self._eval_fn = self._build_eval_fn()
        # shard the eval batch over dp exactly like train_batch — else
        # Model.evaluate/predict on a dp mesh silently runs replicated
        outs, loss_v = self._eval_fn(
            self._params, self._buffers,
            self._shard_batch(_unwrap(list(inputs)), allow_ragged=True),
            self._shard_batch(_unwrap(list(labels)), allow_ragged=True))
        return loss_v, outs

    def predict_batch(self, inputs):
        _, outs = self.eval_batch(inputs, ())
        return outs

    def _apply_zero_placement(self):
        """GroupSharded/ZeRO placement (stage 1: opt state; stage 3: +
        params). Must precede _build_train_fn so the grad sharding
        constraints are computed from the placed params."""
        gs = getattr(self.optimizer, "_group_sharded", None)
        if gs is None or self._opt_state is None:
            return
        from ..distributed.fleet.sharding import shard_tree
        self._opt_state = shard_tree(self._opt_state, gs.mesh, gs.axis)
        if gs.shard_params:
            self._params = shard_tree(self._params, gs.mesh, gs.axis)
            self.network.load_raw_state(self._params, self._buffers)

    # state ------------------------------------------------------------
    def opt_state_dict(self):
        return {"state": self._opt_state, "step": self._step,
                "opt_step": self._opt_step}

    def load_opt_state_dict(self, d):
        self._opt_state = d["state"]
        self._step = d["step"]
        # older checkpoints predate the separate update counter; the
        # fused path kept it == step
        self._opt_step = d.get("opt_step", d["step"])
        self.reset_accum_window()
        if self.guard is not None:
            # snapshots taken before the restore are now the WRONG
            # last-good state — a rollback must never resurrect them;
            # the ring reseeds from the restored state on first step
            self.guard.ring.clear()
        # resume path: re-apply ZeRO placement and rebuild the compiled
        # programs so baked-in grad constraints / frozen-param constants
        # match the (re)placed params — the accumulation programs bake
        # the same state as the fused one
        if getattr(self.optimizer, "_group_sharded", None) is not None:
            self._apply_zero_placement()
            self._train_fn = None
            self._multi_fns = {}
            self._grad_fn = None
            self._apply_fn = None
