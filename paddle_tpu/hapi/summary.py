"""paddle.summary / paddle.flops (ref: python/paddle/hapi/model_summary.py,
python/paddle/hapi/dynamic_flops.py)."""
from __future__ import annotations

import numpy as np

from ..nn.layer import Layer
from ..tensor import Tensor


def summary(net: Layer, input_size=None, dtypes=None, input=None):
    """Prints the reference-style layer table; returns
    {'total_params': n, 'trainable_params': n}."""
    rows = []
    hooks = []
    order = []

    def make_hook(name, layer):
        def hook(lyr, inputs, output):
            try:
                out_shape = list(output.shape) if isinstance(output, Tensor) \
                    else [list(o.shape) for o in output
                          if isinstance(o, Tensor)]
            except Exception:
                out_shape = "?"
            n_params = sum(int(np.prod(p.shape)) for p in
                           lyr._parameters.values() if p is not None)
            rows.append((f"{type(lyr).__name__}-{len(rows) + 1}",
                         str(out_shape), n_params))
        return hook

    for name, sub in net.named_sublayers(include_self=False):
        if not sub._sub_layers:  # leaves only
            hooks.append(sub.register_forward_post_hook(make_hook(name, sub)))

    if input is not None:
        xs = input if isinstance(input, (list, tuple)) else [input]
        net.eval()
        net(*xs)
    elif input_size is not None:
        from ..tensor_ops.creation import zeros
        sizes = input_size if isinstance(input_size, list) else [input_size]
        if sizes and isinstance(sizes[0], int):
            sizes = [tuple(sizes)]
        dts = dtypes if isinstance(dtypes, (list, tuple)) else \
            [dtypes] * len(sizes)
        xs = [zeros([1 if (s is None or (isinstance(s, int) and s < 0)) else s
                     for s in shape], dtype=dt or "float32")
              for shape, dt in zip(sizes, dts)]
        was_training = net.training
        net.eval()
        net(*xs)
        if was_training:
            net.train()
    for h in hooks:
        h.remove()

    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(int(np.prod(p.shape)) for p in net.parameters()
                    if p.trainable)
    line = "-" * 64
    print(line)
    print(f"{'Layer (type)':<28}{'Output Shape':<24}{'Param #':>12}")
    print(line)
    for nm, shp, n in rows:
        print(f"{nm:<28}{shp:<24}{n:>12,}")
    print(line)
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    print(line)
    return {"total_params": total, "trainable_params": trainable}


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Rough analytic FLOPs (matmul/conv dominate; mirrors paddle.flops
    accounting: multiply-adds counted once). Counts nn.Linear, the mpu
    Column/RowParallelLinear projections (what GPT/Llama/ERNIE blocks
    are actually built from — tests/test_flops_drift.py pins this
    mirror against XLA cost_analysis), and conv layers."""
    from ..distributed.fleet.mpu import (ColumnParallelLinear,
                                         RowParallelLinear)
    from ..nn.layers_common import Linear
    from ..nn.layers_conv import _ConvNd
    total = [0]
    hooks = []

    def linear_hook(lyr, inputs, output):
        x = inputs[0]
        batch = int(np.prod(x.shape[:-1]))
        total[0] += batch * lyr.in_features * lyr.out_features

    def conv_hook(lyr, inputs, output):
        out = output
        out_elems = int(np.prod(out.shape))
        k = int(np.prod(lyr._kernel_size)) * lyr._in_channels // lyr._groups
        total[0] += out_elems * k

    for _, sub in net.named_sublayers(include_self=True):
        if isinstance(sub, (Linear, ColumnParallelLinear,
                            RowParallelLinear)):
            hooks.append(sub.register_forward_post_hook(linear_hook))
        elif isinstance(sub, _ConvNd):
            hooks.append(sub.register_forward_post_hook(conv_hook))
    from ..tensor_ops.creation import zeros
    x = zeros(input_size)
    was_training = net.training
    net.eval()
    net(x)
    if was_training:
        net.train()
    for h in hooks:
        h.remove()
    if print_detail:
        print(f"Total FLOPs (MAC): {total[0]:,}")
    return total[0]
