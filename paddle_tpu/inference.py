"""paddle.inference gate (ref: paddle/fluid/inference — the C++
Predictor/AnalysisConfig serving stack).

The reference's inference library loads a static Program and runs it
through a C++ predictor with TensorRT/ONNX backends. The TPU-native
deployment path is StableHLO: `paddle.jit.save(layer, path)` exports a
portable, codeless artifact that `paddle.jit.load(path)` (or any
StableHLO runtime) executes — see examples/deploy_stablehlo.py for the
full train -> export -> codeless-reload -> serve flow, and
paddle_tpu.nn.quant / paddle_tpu.quantization for int8 serving.
"""
from __future__ import annotations

__all__ = ["Config", "create_predictor"]

_RECIPE = (
    "paddle.inference's C++ Predictor is not part of the TPU backend. "
    "Migration: export with paddle.jit.save(layer, path) (StableHLO + "
    "params; works without model code on reload) and serve via "
    "paddle.jit.load(path) — examples/deploy_stablehlo.py is the "
    "end-to-end recipe. For int8 serving see "
    "paddle_tpu.nn.quant.quantize_for_serving.")


class Config:
    def __init__(self, *a, **k):
        raise NotImplementedError(_RECIPE)


def create_predictor(*a, **k):
    raise NotImplementedError(_RECIPE)
