"""Eager autograd engine.

The reference implements reverse-mode autodiff with a C++ tape over PHI
kernels (ref: paddle/fluid/eager/, imperative::Tracer). TPU-native rebuild:
every differentiable op is dispatched through :func:`apply_op`, which — when
gradients are required — runs the op under ``jax.vjp`` and links the pullback
into a graph *owned by the output tensors* (entries hold inputs strongly and
outputs weakly, so the graph is freed by normal GC when outputs are dropped —
an eval loop without no_grad() cannot leak, matching the reference's
refcounted autograd graph). ``Tensor.backward()`` walks the reachable graph
in reverse topological order and accumulates cotangents into ``.grad``.

This graph exists for *API parity* with eager training loops
(``loss.backward(); opt.step()``). The performance path (``hapi.Model`` /
``Engine``) never uses it: there, the whole train step is a pure function
differentiated with ``jax.grad`` and compiled once with ``jax.jit``.
"""
from __future__ import annotations

import contextlib
import threading
import weakref
from typing import Callable

import jax
import jax.numpy as jnp

_state = threading.local()

try:  # jax >= 0.4.x keeps this in _src; public alias was removed in 0.9
    from jax._src.core import trace_state_clean as _trace_state_clean
except ImportError:  # pragma: no cover - future jax relocation
    _trace_state_clean = None


def in_jax_trace(arrs=()) -> bool:
    """True when executing under an active jax trace (jit/grad/vmap/...).

    Inside a trace the eager tape must NOT be built: the outer transform
    already owns differentiation, and a nested ``jax.vjp`` both bloats the
    jaxpr and breaks ``custom_vjp`` ops (Pallas kernels hit
    ``_pallas_call_jvp_rule`` asserts when a vjp is opened inside another
    vjp inside ``jax.grad``). Detection is two-tier: the global trace-state
    flag, plus a Tracer scan of the inputs as a fallback.
    """
    if _trace_state_clean is not None:
        try:
            return not _trace_state_clean()
        except Exception:  # pragma: no cover
            pass
    return any(isinstance(a, jax.core.Tracer) for a in arrs)


def is_grad_enabled() -> bool:
    return getattr(_state, "grad_enabled", True)


def set_grad_enabled(mode: bool):
    _state.grad_enabled = bool(mode)


@contextlib.contextmanager
def no_grad():
    """ref: paddle.no_grad (decorator/context)."""
    prev = is_grad_enabled()
    set_grad_enabled(False)
    try:
        yield
    finally:
        set_grad_enabled(prev)


@contextlib.contextmanager
def enable_grad():
    prev = is_grad_enabled()
    set_grad_enabled(True)
    try:
        yield
    finally:
        set_grad_enabled(prev)


class GradNode:
    """One recorded op: pullback + its tensor inputs (strong) and outputs
    (weak). fwd_fn (the op over its diff inputs, non-diff args closed
    over) enables functional REPLAY of the subgraph — what
    grad(create_graph=True) differentiates, since re-deriving the
    gradients from the inputs is the only way the residual term of the
    second derivative survives (a vjp-of-the-stored-vjp would treat the
    residuals as constants and silently drop it)."""
    __slots__ = ("inputs", "out_refs", "vjp_fn", "fwd_fn", "n_outs",
                 "__weakref__")

    def __init__(self, inputs, outputs, vjp_fn, fwd_fn=None):
        self.inputs = inputs                       # list[Tensor]
        self.out_refs = [weakref.ref(o) for o in outputs]
        self.n_outs = len(outputs)
        self.vjp_fn = vjp_fn
        self.fwd_fn = fwd_fn


def _is_tensor(x) -> bool:
    from .tensor import Tensor
    return isinstance(x, Tensor)


def _float_like(arr) -> bool:
    return jnp.issubdtype(jnp.asarray(arr).dtype, jnp.inexact)


def apply_op(fn: Callable, *args, differentiable: bool = True, **kwargs):
    """Dispatch `fn` (a jnp-level function) over Tensor/array args.

    Tensors are unwrapped to jax arrays; if grad mode is on, any input has
    stop_gradient=False, and the op is differentiable, the call is run under
    jax.vjp and linked into the autograd graph. Returns Tensors mirroring
    fn's output structure.
    """
    from .tensor import Tensor

    flat, treedef = jax.tree_util.tree_flatten(
        (args, kwargs), is_leaf=_is_tensor)
    t_idx = [i for i, x in enumerate(flat) if _is_tensor(x)]
    tensors = [flat[i] for i in t_idx]

    def run(arrs):
        buf = list(flat)
        for i, a in zip(t_idx, arrs):
            buf[i] = a
        a2, k2 = jax.tree_util.tree_unflatten(treedef, buf)
        return fn(*a2, **k2)

    needs_grad = (
        differentiable
        and is_grad_enabled()
        and any(not t.stop_gradient for t in tensors)
    )

    arrs = [t._value for t in tensors]
    if in_jax_trace(arrs):
        # Functional path (Engine/jit/grad/vmap): the outer transform owns
        # differentiation — dispatch directly, no tape. Grads flow through
        # the outer trace; building a nested vjp here is pure overhead and
        # crashes custom_vjp kernels (Pallas flash attention).
        out = run(arrs)
        return jax.tree_util.tree_map(
            lambda a: Tensor(a, stop_gradient=not needs_grad), out)
    if not needs_grad:
        out = run(arrs)
        return jax.tree_util.tree_map(
            lambda a: Tensor(a, stop_gradient=True), out)

    diff_pos = [i for i, t in enumerate(tensors)
                if not t.stop_gradient and _float_like(t._value)]
    if not diff_pos:
        out = run(arrs)
        return jax.tree_util.tree_map(
            lambda a: Tensor(a, stop_gradient=True), out)

    def run_diff(*darrs):
        buf = list(arrs)
        for i, a in zip(diff_pos, darrs):
            buf[i] = a
        return run(buf)

    out_arrs, vjp_fn = jax.vjp(run_diff, *(arrs[i] for i in diff_pos))

    # replay closure for create_graph: closes over raw ARRAYS and the
    # treedef only — not the Tensor wrappers `run` pins via `flat` — so
    # taping an op does not extend wrapper lifetimes on the default path
    base_flat = [None if j in t_idx else x for j, x in enumerate(flat)]

    def fwd_replay(*darrs):
        buf = list(base_flat)
        for j, a in zip(t_idx, arrs):
            buf[j] = a
        for i, a in zip(diff_pos, darrs):
            buf[t_idx[i]] = a
        a2, k2 = jax.tree_util.tree_unflatten(treedef, buf)
        return fn(*a2, **k2)
    out_tensors = jax.tree_util.tree_map(
        lambda a: Tensor(a, stop_gradient=False), out_arrs)
    flat_outs = [t for t in jax.tree_util.tree_leaves(
        out_tensors, is_leaf=_is_tensor) if _is_tensor(t)]
    node = GradNode(inputs=[tensors[i] for i in diff_pos],
                    outputs=flat_outs, vjp_fn=vjp_fn, fwd_fn=fwd_replay)
    for t in flat_outs:
        t._grad_node = node
    return out_tensors


def _toposort(roots):
    """Nodes reachable from roots' grad nodes, consumers-before-producers
    (Kahn's algorithm on consumer->producer edges, so every node is
    processed only after ALL its consumers contributed cotangents —
    correct for diamond graphs like loss = a + f(a))."""
    nodes = {}
    stack = []
    for r in roots:
        node = getattr(r, "_grad_node", None)
        if node is not None and id(node) not in nodes:
            nodes[id(node)] = node
            stack.append(node)
    while stack:
        node = stack.pop()
        for t in node.inputs:
            child = getattr(t, "_grad_node", None)
            if child is not None and id(child) not in nodes:
                nodes[id(child)] = child
                stack.append(child)
    indeg = {nid: 0 for nid in nodes}
    for node in nodes.values():
        for t in node.inputs:
            child = getattr(t, "_grad_node", None)
            if child is not None and id(child) in nodes:
                indeg[id(child)] += 1
    order = []
    ready = [n for nid, n in nodes.items() if indeg[nid] == 0]
    while ready:
        node = ready.pop()
        order.append(node)
        for t in node.inputs:
            child = getattr(t, "_grad_node", None)
            if child is not None and id(child) in nodes:
                indeg[id(child)] -= 1
                if indeg[id(child)] == 0:
                    ready.append(child)
    return order


def _apply_grad_hooks(t, c):
    """Run a tensor's registered grad hooks on cotangent array `c`; a
    non-None Tensor/array return replaces it."""
    from .tensor import Tensor
    for hook in list(t._grad_hooks.values()):
        r = hook(Tensor(c, stop_gradient=True))
        if r is not None:
            c = r._value if _is_tensor(r) else jnp.asarray(r)
    return c


def backward(tensors, grad_tensors=None, retain_graph: bool = False):
    """ref: paddle.autograd.backward / Tensor.backward."""
    from .tensor import Tensor

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]

    cot = {}
    for t, g in zip(tensors, grad_tensors):
        if g is None:
            g_arr = jnp.ones_like(t._value)
        else:
            g_arr = g._value if _is_tensor(g) else jnp.asarray(g)
        cot[id(t)] = cot.get(id(t), 0) + g_arr

    order = _toposort(tensors)
    hooked_leaves = {}
    hooks_done = set()
    for node in order:
        out_cots = []
        has_any = False
        for ref in node.out_refs:
            o = ref()
            # grad hooks fire on the ACCUMULATED gradient of a tensor: for
            # produced tensors that moment is here (topo order guarantees
            # every consumer already contributed to cot[id(o)])
            if (o is not None and o._grad_hooks and id(o) in cot
                    and id(o) not in hooks_done):
                hooks_done.add(id(o))
                cot[id(o)] = _apply_grad_hooks(o, cot[id(o)])
                if not o.stop_gradient and o._retain_grads:
                    prev = o._grad_value
                    o._grad_value = (cot[id(o)] if prev is None
                                     else prev + cot[id(o)])
            c = cot.get(id(o)) if o is not None else None
            if c is None:
                shape_src = o._value if o is not None else None
                c = jnp.zeros_like(shape_src) if shape_src is not None else None
                if c is None:
                    # output tensor was GC'd and nothing flowed into it
                    out_cots = None
                    break
            else:
                has_any = True
            out_cots.append(c)
        if not has_any or out_cots is None:
            continue
        seed = out_cots[0] if node.n_outs == 1 else tuple(out_cots)
        in_cots = node.vjp_fn(seed)
        for t, c in zip(node.inputs, in_cots):
            cot[id(t)] = cot.get(id(t), 0) + c
            is_leaf = getattr(t, "_grad_node", None) is None
            if t._grad_hooks:
                # defer the .grad write until the accumulated total is
                # final and the hooks have fired (producer time for
                # intermediates, post-loop for leaves)
                if is_leaf:
                    hooked_leaves[id(t)] = t
                continue
            if not t.stop_gradient and (is_leaf or t._retain_grads):
                prev = t._grad_value
                t._grad_value = c if prev is None else prev + c

    for t in hooked_leaves.values():
        total = _apply_grad_hooks(t, cot[id(t)])
        if not t.stop_gradient:
            prev = t._grad_value
            t._grad_value = total if prev is None else prev + total

    if not retain_graph:
        # sever links so the graph (and its vjp residuals) frees now
        for node in order:
            for ref in node.out_refs:
                o = ref()
                if o is not None:
                    o._grad_node = None
            node.vjp_fn = None
            node.fwd_fn = None
            node.inputs = []


def _grad_create_graph(outputs, inputs, grad_outputs, allow_unused):
    """grad(create_graph=True): functionally REPLAY the recorded
    subgraph from the inputs (and every requires-grad leaf, so a later
    backward through the returned grads reaches the parameters — the
    WGAN-GP pattern), take jax.vjp of the replay, and record the whole
    thing as ONE tape op. Differentiating the result re-runs jax's
    second-order machinery over the true function of the inputs, so the
    residual term of d2y/dx2 is exact (unlike differentiating the stored
    pullback, which would treat residuals as constants).

    Gradient hooks do not fire on this path (it never walks the tape
    node-by-node); use backward()/grad(create_graph=False) for hooks."""
    from .tensor import Tensor

    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    elif isinstance(grad_outputs, Tensor):
        grad_outputs = [grad_outputs]
    seeds = tuple(
        jnp.ones_like(o._value) if g is None
        else (g._value if _is_tensor(g) else jnp.asarray(g))
        for o, g in zip(outputs, grad_outputs))

    order = _toposort(outputs)
    if any(n.fwd_fn is None for n in order):
        raise RuntimeError(
            "create_graph=True needs the recorded forward fns; part of "
            "this graph was built by an op that did not store one")
    used_ids = {id(t) for node in order for t in node.inputs}
    for o in outputs:              # an output passed as input is "used"
        used_ids.add(id(o))
    unused = [t for t in inputs if id(t) not in used_ids]
    if unused and not allow_unused:
        raise ValueError(
            "some inputs are not reachable from outputs; pass "
            "allow_unused=True to get None gradients for them")
    # duplicates in `inputs` would fight over the id-keyed replay env;
    # differentiate once per unique tensor and fan the result back out
    uniq, uniq_ids = [], set()
    for t in inputs:
        if id(t) not in uniq_ids:
            uniq_ids.add(id(t))
            uniq.append(t)
    in_ids = {id(t) for t in uniq}
    leaves = []                    # requires-grad leaves beyond `inputs`
    seen = set(in_ids)
    for node in order:
        for t in node.inputs:
            if (getattr(t, "_grad_node", None) is None
                    and not t.stop_gradient and id(t) not in seen):
                seen.add(id(t))
                leaves.append(t)
    n_in = len(uniq)

    def gradfn(*all_arrs):
        in_arrs, leaf_arrs = all_arrs[:n_in], all_arrs[n_in:]

        def replay(*xs):
            env = {id(t): a for t, a in zip(uniq, xs)}
            env.update({id(t): a for t, a in zip(leaves, leaf_arrs)})
            for node in reversed(order):    # producers first
                vals = [env.get(id(t), t._value) for t in node.inputs]
                outs = jax.tree_util.tree_leaves(node.fwd_fn(*vals))
                for ref, o in zip(node.out_refs, outs):
                    ot = ref()
                    # never overwrite a SEEDED value: for a non-leaf
                    # input the producer also replays, and clobbering
                    # the tracer would sever the vjp dependence
                    if ot is not None and id(ot) not in in_ids:
                        env[id(ot)] = o
            return tuple(env.get(id(o), o._value) for o in outputs)

        _, vjp = jax.vjp(replay, *in_arrs)
        res = vjp(seeds)
        # a bare array for the single-input case: the tape seeds a
        # 1-output node with the raw cotangent, not a 1-tuple
        return res[0] if len(res) == 1 else res

    # create_graph means BUILD the graph — even under no_grad (the
    # reference semantics); without taping, the later backward through
    # the returned grads would be a silent no-op
    with enable_grad():
        grads = apply_op(gradfn, *uniq, *leaves)
    grads = list(grads) if isinstance(grads, (tuple, list)) else [grads]
    by_id = {id(t): g for t, g in zip(uniq, grads)}
    return [None if id(t) not in used_ids else by_id[id(t)]
            for t in inputs]


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, allow_unused=False):
    """ref: paddle.grad — gradients of outputs w.r.t. inputs via the eager
    graph. create_graph=True returns gradients that are themselves on
    the tape (functional replay — see _grad_create_graph), enabling
    double/triple grad and gradient penalties.
    """
    from .tensor import Tensor

    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    if create_graph:
        return _grad_create_graph(outputs, inputs, grad_outputs,
                                  allow_unused)
    keep = {id(t): t._grad_value for t in inputs}
    retain = [t._retain_grads for t in inputs]
    for t in inputs:
        t._grad_value = None
        t._retain_grads = True
    backward(outputs, grad_outputs,
             retain_graph=bool(retain_graph))
    res = []
    for t, r in zip(inputs, retain):
        g = t._grad_value
        if g is None and not allow_unused:
            raise ValueError(
                "paddle_tpu.grad: an input is not reachable from outputs; "
                "pass allow_unused=True to get None for it instead")
        res.append(Tensor(g, stop_gradient=True) if g is not None else None)
        t._grad_value = keep[id(t)]
        t._retain_grads = r
    return res


# ---------------------------------------------------------------------------
# PyLayer: user-defined forward/backward (ref: paddle.autograd.PyLayer,
# python/paddle/autograd/py_layer.py)
# ---------------------------------------------------------------------------
class PyLayerContext:
    """ref: paddle.autograd.PyLayerContext — carries state from forward to
    backward (`save_for_backward` / `saved_tensor`, plus arbitrary
    attributes)."""

    def __init__(self):
        self._saved = ()
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = tuple(tensors)

    def saved_tensor(self):
        return self._saved


class PyLayerMeta(type):
    pass


class PyLayer(metaclass=PyLayerMeta):
    """ref: paddle.autograd.PyLayer — custom op with a user-defined
    backward.

    TPU-native dual dispatch:
    - eagerly, ``apply`` runs ``forward`` under no_grad and links one
      GradNode whose pullback calls ``backward`` (exact reference
      semantics: ops inside forward are NOT taped);
    - inside a jax trace (Engine/jit/grad), ``apply`` wraps the pair as a
      ``jax.custom_vjp`` so the compiled step uses the custom rule — the
      same mechanism the Pallas flash-attention kernel uses. Saved
      tensors ride the custom_vjp residuals, so nothing leaks across
      traces.
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grad_outputs):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        from .tensor import Tensor

        flat, treedef = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=_is_tensor)
        t_idx = [i for i, x in enumerate(flat) if _is_tensor(x)]
        tensors = [flat[i] for i in t_idx]
        arrs = [t._value for t in tensors]

        def rebuild(darrs, stop_gradient=True):
            buf = list(flat)
            for i, a in zip(t_idx, darrs):
                buf[i] = Tensor(a, stop_gradient=stop_gradient)
            a2, k2 = jax.tree_util.tree_unflatten(treedef, buf)
            return a2, k2

        if in_jax_trace(arrs):
            return cls._apply_traced(rebuild, arrs)

        ctx = PyLayerContext()
        a2, k2 = rebuild(arrs)
        with no_grad():
            out = cls.forward(ctx, *a2, **k2)

        needs_grad = (is_grad_enabled()
                      and any(not t.stop_gradient for t in tensors))
        if not needs_grad:
            return out

        # pass-through outputs cannot self-cycle the toposort: forward only
        # ever sees the REBUILT input wrappers (rebuild() above), never the
        # caller's tensors, so a returned input is already a distinct
        # object from the node's recorded inputs
        out_flat = [t for t in jax.tree_util.tree_leaves(
            out, is_leaf=_is_tensor) if _is_tensor(t)]

        for t in out_flat:
            t.stop_gradient = False
        diff_pos = [i for i, t in enumerate(tensors)
                    if not t.stop_gradient and _float_like(t._value)]
        n_outs = len(out_flat)

        def vjp_fn(seed):
            seeds = (seed,) if n_outs == 1 else tuple(seed)
            seed_ts = [Tensor(s, stop_gradient=True) for s in seeds]
            with no_grad():
                grads = cls.backward(ctx, *seed_ts)
            if not isinstance(grads, (tuple, list)):
                grads = (grads,)
            if len(grads) != len(tensors):
                raise ValueError(
                    f"{cls.__name__}.backward returned {len(grads)} grads "
                    f"for {len(tensors)} tensor inputs")
            out = []
            for i in diff_pos:
                g = grads[i]
                if g is None:
                    out.append(jnp.zeros_like(tensors[i]._value))
                else:
                    out.append(g._value if _is_tensor(g) else jnp.asarray(g))
            return tuple(out)

        node = GradNode(inputs=[tensors[i] for i in diff_pos],
                        outputs=out_flat, vjp_fn=vjp_fn)
        for t in out_flat:
            t._grad_node = node
        return out

    @classmethod
    def _apply_traced(cls, rebuild, arrs):
        from .tensor import Tensor

        n_in = len(arrs)
        ctx_cell = {}

        def prim(*darrs):
            ctx = PyLayerContext()
            ctx_cell["ctx"] = ctx
            a2, k2 = rebuild(darrs)
            out = cls.forward(ctx, *a2, **k2)
            return jax.tree_util.tree_map(
                lambda t: t._value if _is_tensor(t) else t, out,
                is_leaf=_is_tensor)

        f = jax.custom_vjp(prim)

        def fwd(*darrs):
            out = prim(*darrs)
            ctx = ctx_cell["ctx"]
            saved = tuple(t._value if _is_tensor(t) else t
                          for t in ctx._saved)
            return out, saved

        def bwd(saved, ct):
            ctx = ctx_cell["ctx"]
            ctx._saved = tuple(Tensor(s) if isinstance(s, jax.Array)
                               or hasattr(s, "dtype") else s for s in saved)
            cts = jax.tree_util.tree_leaves(ct)
            seed_ts = [Tensor(c, stop_gradient=True) for c in cts]
            grads = cls.backward(ctx, *seed_ts)
            if not isinstance(grads, (tuple, list)):
                grads = (grads,)
            if len(grads) != n_in:
                raise ValueError(
                    f"{cls.__name__}.backward returned {len(grads)} grads "
                    f"for {n_in} tensor inputs")
            out = []
            for i in range(n_in):
                g = grads[i]
                if g is None:
                    out.append(jnp.zeros_like(arrs[i]))
                else:
                    out.append((g._value if _is_tensor(g)
                                else jnp.asarray(g)).astype(arrs[i].dtype))
            return tuple(out)

        f.defvjp(fwd, bwd)
        out = f(*arrs)
        return jax.tree_util.tree_map(lambda a: Tensor(a, stop_gradient=False),
                                      out)


# ---------------------------------------------------------------------------
# functional autograd API (ref: python/paddle/autograd/functional.py +
# paddle.incubate.autograd, exposed as paddle_tpu.incubate.autograd too):
# jacobian / hessian / jvp / vjp built on jax's transforms — exact,
# composable, jit-compatible. Tensor<->array pytree plumbing reuses
# functional_transforms._unwrap/_wrap.
# ---------------------------------------------------------------------------
def _check_fn_flags(create_graph, where):
    if create_graph:
        raise NotImplementedError(
            f"{where}: create_graph=True is not supported on this API — "
            "compose jax transforms via paddle_tpu.functional_grad / "
            "paddle_tpu.value_and_grad for higher-order pipelines")


def _wrap_fn(func):
    """Lift a Tensor-level callable to a jnp-level one."""
    from .functional_transforms import _unwrap
    from .tensor import Tensor

    def jf(*arrs):
        ts = [Tensor(a, stop_gradient=False) for a in arrs]
        return _unwrap(func(*ts))
    return jf


def _input_arrays(xs):
    from .functional_transforms import _unwrap
    multi = isinstance(xs, (list, tuple))
    arrs = _unwrap(list(xs) if multi else [xs])
    return multi, arrs


def jacobian(func, xs, create_graph=False, allow_unused=False):
    """ref: paddle.autograd.jacobian — J[i, j] = d out_i / d x_j."""
    from .functional_transforms import _wrap
    _check_fn_flags(create_graph, "jacobian")
    multi, arrs = _input_arrays(xs)
    jf = _wrap_fn(func)
    jac = jax.jacrev(lambda *a: jf(*a), argnums=tuple(range(len(arrs))))(
        *arrs)
    out = _wrap(jac)
    if not multi:
        return out[0] if isinstance(out, tuple) else out
    return out


def hessian(func, xs, create_graph=False, allow_unused=False):
    """ref: paddle.autograd.hessian — for a SCALAR-output func."""
    from .functional_transforms import _wrap
    _check_fn_flags(create_graph, "hessian")
    multi, arrs = _input_arrays(xs)
    jf = _wrap_fn(func)

    def scalar(*a):
        return jnp.reshape(jf(*a), ())
    hes = jax.hessian(scalar, argnums=tuple(range(len(arrs))))(*arrs)
    out = _wrap(hes)
    if not multi:
        return out[0][0] if isinstance(out, tuple) else out
    return out


def jvp(func, xs, v=None, create_graph=False, allow_unused=False):
    """ref: paddle.incubate.autograd.jvp -> (outputs, jvp_result)."""
    from .functional_transforms import _unwrap, _wrap
    _check_fn_flags(create_graph, "jvp")
    multi, arrs = _input_arrays(xs)
    if v is None:
        tangents = tuple(jnp.ones_like(a) for a in arrs)
    else:
        tangents = tuple(_unwrap(list(v) if isinstance(v, (list, tuple))
                                 else [v]))
    jf = _wrap_fn(func)
    out, tangent_out = jax.jvp(lambda *a: jf(*a), tuple(arrs), tangents)
    return _wrap(out), _wrap(tangent_out)


def vjp(func, xs, v=None, create_graph=False, allow_unused=False):
    """ref: paddle.incubate.autograd.vjp -> (outputs, vjp_result)."""
    from .functional_transforms import _unwrap, _wrap
    _check_fn_flags(create_graph, "vjp")
    multi, arrs = _input_arrays(xs)
    jf = _wrap_fn(func)
    out, pullback = jax.vjp(lambda *a: jf(*a), *arrs)
    if v is None:
        cot = jax.tree_util.tree_map(jnp.ones_like, out)
    else:
        # cotangent must mirror the OUTPUT pytree structure exactly
        cot_arrays = _unwrap(v)
        out_flat, out_tree = jax.tree_util.tree_flatten(out)
        cot_flat = jax.tree_util.tree_leaves(cot_arrays)
        if len(cot_flat) != len(out_flat):
            raise ValueError(
                f"vjp: cotangent has {len(cot_flat)} leaves but the "
                f"output has {len(out_flat)}")
        cot = jax.tree_util.tree_unflatten(out_tree, cot_flat)
    grads = pullback(cot)
    outs_t = _wrap(out)
    grads_w = [_wrap(g) for g in grads]
    if not multi:
        return outs_t, grads_w[0]
    return outs_t, grads_w
