"""paddle.static namespace (ref: python/paddle/static/).

The reference's static graph mode (Program/Executor/feed-fetch) is
subsumed by XLA here: `paddle.jit.to_static` traces once and compiles —
that IS the static graph. This module keeps the `paddle.static` names
import-compatible: `InputSpec` is the real one, introspection maps to the
HLO dump, and Program/Executor construction raises with the exact
migration recipe instead of an AttributeError.
"""
from __future__ import annotations

from .jit import InputSpec  # noqa: F401  (the real thing)

__all__ = ["InputSpec", "Program", "Executor", "default_main_program",
           "default_startup_program", "program_guard", "data", "save",
           "load", "name_scope"]

_MSG = (
    "paddle.static graph mode is replaced by XLA compilation: decorate "
    "your function/Layer with paddle_tpu.jit.to_static(fn, "
    "input_spec=[InputSpec(...)]) — it traces once and compiles, which is "
    "the static graph. Use paddle_tpu.jit.save/load for deployment "
    "artifacts and paddle_tpu.jit.get_hlo for program introspection."
)


class Program:
    def __init__(self, *a, **k):
        raise NotImplementedError(_MSG)


class Executor:
    def __init__(self, *a, **k):
        raise NotImplementedError(_MSG)


def default_main_program():
    raise NotImplementedError(_MSG)


def default_startup_program():
    raise NotImplementedError(_MSG)


def program_guard(*a, **k):
    raise NotImplementedError(_MSG)


def data(name, shape, dtype="float32", lod_level=0):
    """ref: paddle.static.data — returns an InputSpec (the jit-era
    equivalent of a feed placeholder)."""
    return InputSpec(shape=shape, dtype=dtype, name=name)


def name_scope(prefix=None):
    import contextlib

    @contextlib.contextmanager
    def cm():
        yield
    return cm()


def save(layer, path, *a, **k):
    from . import jit
    return jit.save(layer, path, *a, **k)


def load(path, *a, **k):
    from . import jit
    return jit.load(path, *a, **k)
