"""Live metrics endpoint — scrape a RUNNING engine, not its files.

A stdlib ``http.server`` on a daemon thread serving three endpoints:

- ``/metrics``  — Prometheus text exposition of a MetricsRegistry
  (what a prometheus/grafana scraper or ``curl`` reads mid-run);
- ``/healthz``  — JSON health snapshot (ServingEngine.health() when
  attached there; a minimal liveness doc otherwise) — the thing a
  load balancer probes;
- ``/report``   — JSON recompile report + compiled-cost report
  (trace.report_all + introspect.cost_report): the "what did XLA
  build and did anything retrace" question, answered live.

Every read happens under the registry's own lock (to_prometheus /
snapshot take it), so a scrape landing mid-serve-dispatch sees a
consistent registry — never a torn histogram whose ``_count``
disagrees with its ``+Inf`` bucket.

Attachment is one call: ``ServingEngine.serve_metrics(port=...)`` or
``Model.serve_metrics(port=...)`` (port 0 picks a free one —
``exporter.port`` tells you which). ``close()`` is idempotent and
releases the port immediately (``allow_reuse_address`` covers the
TIME_WAIT rebind); the serving thread is a daemon, so SIGTERM'd
processes exit without joining it.

Stdlib-only by contract (standalone-loadable via bench._obs_mod);
the /report handler imports sibling modules lazily and degrades to
an empty section when they are unavailable.
"""
from __future__ import annotations

import json
import math
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

__all__ = ["MetricsExporter", "serve_metrics"]


def _finite(obj):
    """Non-finite floats -> None (RFC-valid JSON). Duplicated across
    the stdlib-only observability modules on purpose: each stays
    standalone-loadable (bench._obs_mod) with no intra-package imports
    at module scope."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: _finite(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_finite(v) for v in obj]
    return obj


class MetricsExporter:
    """HTTP exporter for one registry (+ optional health/report fns).

    registry: MetricsRegistry to expose (None -> the process-global
        one, resolved lazily so a standalone load can still pass one).
    health_fn: zero-arg callable returning a JSON-able dict
        (ServingEngine.health); None serves a minimal liveness doc.
    report_fn: zero-arg callable returning extra /report sections
        merged over the defaults.
    traces_fn: one-arg callable serving ``/traces`` (arg None = the
        index of known traces) and ``/traces/<key>`` (arg = the key —
        a trace id or fleet rid; return None for unknown keys -> 404).
        None disables the endpoint (FleetRouter.serve_metrics wires
        its trace_report here).
    requests_fn: one-arg callable serving ``/requests`` (arg None =
        the recent-resolved index: rid, tenant, status, ttft/e2e,
        archive locator — the /traces index's request-plane sibling)
        and ``/requests/<rid>`` (one row; None -> 404). None disables
        the endpoint.
    history_fn: one-arg callable serving ``/history`` — receives the
        parsed query params ({} for a bare GET = the series index;
        keys like series/res/window/q/op select a range/rate/quantile
        read; return None for unknown series -> 404). None disables
        the endpoint (FleetRouter.serve_metrics wires its
        HistoryStore here).
    tenants_fn: zero-arg callable serving ``/tenants`` (the
        TenantAccountant report: top-K heavy hitters + exact totals).
        None disables the endpoint.
    profile_fn: one-arg callable serving ``/profile?window=S`` — the
        continuous profiler's report (folded stacks + per-phase
        digest) over the last S seconds (None = since start); return
        None when no profiler is armed -> 404. None disables the
        endpoint (ServingEngine/FleetRouter wire their
        ContinuousProfiler here, the /traces attach-point pattern).
    memory_fn: one-arg callable serving ``/memory?window=S`` — the
        memory ledger's typed segment tree + headroom forecast (the
        window arg is accepted for route symmetry; a ledger is a
        level, not a ring). Return None -> 404; engines instead
        answer a stub JSON ({"armed": false, ...}) when no ledger is
        armed, so the route itself is always probeable. None disables
        the endpoint.
    host/port: bind address; port 0 = ephemeral (read .port after).

    Every route observes its own wall time into the per-route
    ``exporter_scrape_seconds`` histogram: a slow ``/metrics`` render
    stretches the history plane's scrape cadence and skews rate()
    windows, so scrape latency is itself a first-class series. The
    ``/metrics`` route measures a throwaway render FIRST, observes it,
    then serves a fresh render — so the served exposition already
    contains the observation and stays byte-identical to a subsequent
    in-process ``to_prometheus()`` (the telemetry_smoke parity
    contract).
    """

    def __init__(self, registry=None, port=0, host="127.0.0.1",
                 health_fn=None, report_fn=None, traces_fn=None,
                 history_fn=None, tenants_fn=None, requests_fn=None,
                 profile_fn=None, memory_fn=None):
        if registry is None:
            from .metrics import get_registry
            registry = get_registry()
        self.registry = registry
        self.health_fn = health_fn
        self.report_fn = report_fn
        self.traces_fn = traces_fn
        self.history_fn = history_fn
        self.tenants_fn = tenants_fn
        self.requests_fn = requests_fn
        self.profile_fn = profile_fn
        self.memory_fn = memory_fn
        self._scrape_hists = {}
        self._started = time.time()
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            # scrapes every few seconds would spam stderr
            def log_message(self, *a):  # noqa: D102
                pass

            def _send(self, code, body, ctype):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _send_json(self, doc, code=200):
                try:
                    body = json.dumps(doc, allow_nan=False)
                except ValueError:
                    # a NaN loss in a health/report doc must still
                    # answer as valid JSON (the storm runs this layer
                    # exists to observe)
                    body = json.dumps(_finite(doc), allow_nan=False)
                self._send(code, body, "application/json")

            def do_GET(self):  # noqa: N802 — http.server API
                parts = self.path.split("?", 1)
                path = parts[0].rstrip("/") or "/"
                seg = "/" + path.split("/")[1] if path != "/" else "/"
                t0 = time.perf_counter()
                try:
                    if path == "/metrics":
                        # double render: measure + observe FIRST, then
                        # serve a fresh exposition that already holds
                        # the observation (byte-parity contract above)
                        exporter.registry.to_prometheus()
                        exporter._observe_scrape(
                            "/metrics", time.perf_counter() - t0)
                        self._send(200, exporter.registry.to_prometheus(),
                                   "text/plain; version=0.0.4; "
                                   "charset=utf-8")
                    elif path == "/healthz":
                        self._send_json(exporter._health())
                    elif path == "/report":
                        self._send_json(exporter._report())
                    elif exporter.traces_fn is not None and (
                            path == "/traces"
                            or path.startswith("/traces/")):
                        key = (path[len("/traces/"):]
                               if path.startswith("/traces/")
                               else "") or None
                        doc = exporter.traces_fn(key)
                        if doc is None:
                            self._send_json(
                                {"error": f"unknown trace {key!r}"},
                                code=404)
                        else:
                            self._send_json(doc)
                    elif exporter.requests_fn is not None and (
                            path == "/requests"
                            or path.startswith("/requests/")):
                        key = (path[len("/requests/"):]
                               if path.startswith("/requests/")
                               else "") or None
                        doc = exporter.requests_fn(key)
                        if doc is None:
                            self._send_json(
                                {"error": f"unknown request {key!r}"},
                                code=404)
                        else:
                            self._send_json(doc)
                    elif exporter.history_fn is not None \
                            and path == "/history":
                        from urllib.parse import parse_qs
                        params = {k: v[-1] for k, v in parse_qs(
                            parts[1] if len(parts) > 1 else ""
                            ).items()}
                        doc = exporter.history_fn(params)
                        if doc is None:
                            self._send_json(
                                {"error": "unknown history query "
                                          f"{params!r}"}, code=404)
                        else:
                            self._send_json(doc)
                    elif exporter.tenants_fn is not None \
                            and path == "/tenants":
                        self._send_json(exporter.tenants_fn())
                    elif exporter.profile_fn is not None \
                            and path == "/profile":
                        from urllib.parse import parse_qs
                        params = {k: v[-1] for k, v in parse_qs(
                            parts[1] if len(parts) > 1 else ""
                            ).items()}
                        window = None
                        if params.get("window"):
                            try:
                                window = float(params["window"])
                            except ValueError:
                                window = None
                        doc = exporter.profile_fn(window)
                        if doc is None:
                            self._send_json(
                                {"error": "no profiler armed "
                                          "(PADDLE_TPU_PROFILE=1)"},
                                code=404)
                        else:
                            self._send_json(doc)
                    elif exporter.memory_fn is not None \
                            and path == "/memory":
                        from urllib.parse import parse_qs
                        params = {k: v[-1] for k, v in parse_qs(
                            parts[1] if len(parts) > 1 else ""
                            ).items()}
                        window = None
                        if params.get("window"):
                            try:
                                window = float(params["window"])
                            except ValueError:
                                window = None
                        doc = exporter.memory_fn(window)
                        if doc is None:
                            self._send_json(
                                {"error": "no ledger armed "
                                          "(PADDLE_TPU_MEM_LEDGER=1)"},
                                code=404)
                        else:
                            self._send_json(doc)
                    else:
                        endpoints = ["/metrics", "/healthz", "/report"]
                        if exporter.traces_fn is not None:
                            endpoints.append("/traces")
                        if exporter.requests_fn is not None:
                            endpoints.append("/requests")
                        if exporter.history_fn is not None:
                            endpoints.append("/history")
                        if exporter.tenants_fn is not None:
                            endpoints.append("/tenants")
                        if exporter.profile_fn is not None:
                            endpoints.append("/profile")
                        if exporter.memory_fn is not None:
                            endpoints.append("/memory")
                        self._send_json(
                            {"error": f"unknown path {path!r}",
                             "endpoints": endpoints}, code=404)
                except Exception as e:  # noqa: BLE001 — a handler bug must
                    # answer 500, not silently drop the connection
                    try:
                        self._send_json({"error": f"{type(e).__name__}: "
                                                  f"{e}"}, code=500)
                    except OSError:
                        pass
                finally:
                    if seg != "/metrics":
                        exporter._observe_scrape(
                            seg, time.perf_counter() - t0)

        Handler.protocol_version = "HTTP/1.1"
        # a close()d exporter's port rebinds immediately (no TIME_WAIT
        # stall between bench rungs/tests): http.server's HTTPServer
        # already sets allow_reuse_address
        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.host, self.port = self._server.server_address[:2]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            kwargs={"poll_interval": 0.1},
            daemon=True, name=f"paddle-tpu-metrics-{self.port}")
        self._thread.start()
        self._closed = False

    @property
    def url(self):
        return f"http://{self.host}:{self.port}"

    def _observe_scrape(self, route, dur_s):
        """Per-route scrape-latency self-metric. Never raises — a
        telemetry bug must not turn a scrape into a 500."""
        try:
            h = self._scrape_hists.get(route)
            if h is None:
                h = self._scrape_hists[route] = self.registry.histogram(
                    "exporter_scrape_seconds",
                    help="wall seconds serving one exporter route "
                         "(slow renders stretch scrape cadence and "
                         "skew rate() windows)",
                    labels={"route": route})
            h.observe(dur_s)
        except Exception:   # noqa: BLE001
            pass

    def _health(self):
        doc = {"status": "ok", "ts": round(time.time(), 6),
               "uptime_s": round(time.time() - self._started, 3)}
        if self.health_fn is not None:
            doc.update(self.health_fn())
        return doc

    def _report(self):
        doc = {"ts": round(time.time(), 6)}
        try:
            from .trace import report_all
            doc["recompile_report"] = report_all()
        except ImportError:
            doc["recompile_report"] = None
        try:
            from .introspect import cost_report
            doc["cost_report"] = cost_report()
        except ImportError:
            doc["cost_report"] = None
        if self.report_fn is not None:
            doc.update(self.report_fn())
        return doc

    def close(self):
        """Stop serving and release the port. Idempotent — engines
        call this from close() AND finalizers."""
        if self._closed:
            return
        self._closed = True
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=2.0)

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter-shutdown safety
            pass


def serve_metrics(port=0, registry=None, host="127.0.0.1",
                  health_fn=None, report_fn=None, traces_fn=None,
                  history_fn=None, tenants_fn=None, requests_fn=None,
                  profile_fn=None):
    """Start a MetricsExporter (the one-call attach the docs show);
    returns it — read ``.port`` / ``.url``, call ``.close()``."""
    return MetricsExporter(registry=registry, port=port, host=host,
                           health_fn=health_fn, report_fn=report_fn,
                           traces_fn=traces_fn, history_fn=history_fn,
                           tenants_fn=tenants_fn,
                           requests_fn=requests_fn,
                           profile_fn=profile_fn)
