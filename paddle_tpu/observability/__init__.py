"""Unified telemetry subsystem (metrics registry, recompile tracer,
structured run telemetry, compiled-cost introspection, live exporter,
spans, distributed tracing, SLO burn-rate accounting, crash flight
recorder) — docs/observability.md.

Layering: ``metrics``, ``telemetry``, ``exporter``, ``spans``,
``contprof``, ``dtrace``, ``slo``, ``flightrec``, ``history``,
``tenancy``, ``trafficrec`` and ``sentinel`` are pure stdlib
(importable from the jax-free bench orchestrator and worker
processes); ``trace`` and ``introspect`` import jax lazily inside
the wrapping calls.
"""
from . import (contprof, dtrace, exporter, flightrec,  # noqa: F401
               history, introspect, metrics, sentinel, slo, spans,
               telemetry, tenancy, trace, trafficrec)
from .contprof import ContinuousProfiler  # noqa: F401
from .dtrace import TraceStore, get_store  # noqa: F401
from .exporter import MetricsExporter, serve_metrics  # noqa: F401
from .flightrec import FlightRecorder  # noqa: F401
from .history import HistoryStore  # noqa: F401
from .sentinel import AnomalySentinel  # noqa: F401
from .tenancy import SpaceSavingSketch, TenantAccountant  # noqa: F401
from .trafficrec import TrafficRecorder, load_archive  # noqa: F401
from .introspect import (cost_report, measured_mfu,  # noqa: F401
                         resolve_peak_flops)
from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,  # noqa: F401
                      default_time_buckets, get_registry)
from .slo import SLObjective, SLOTracker  # noqa: F401
from .spans import SpanRecorder, export_chrome  # noqa: F401
from .telemetry import TelemetryCallback, TelemetryLogger  # noqa: F401
from .trace import RecompileTracer, get_tracer, report_all  # noqa: F401

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "default_time_buckets", "get_registry",
           "TelemetryCallback", "TelemetryLogger", "RecompileTracer",
           "get_tracer", "report_all", "MetricsExporter",
           "serve_metrics", "SpanRecorder", "export_chrome",
           "TraceStore", "get_store", "SLObjective", "SLOTracker",
           "FlightRecorder", "cost_report", "measured_mfu",
           "resolve_peak_flops", "HistoryStore", "AnomalySentinel",
           "SpaceSavingSketch", "TenantAccountant",
           "TrafficRecorder", "load_archive",
           "ContinuousProfiler",
           "metrics", "telemetry", "trace",
           "introspect", "exporter", "spans", "contprof", "dtrace",
           "slo", "flightrec", "history", "sentinel", "tenancy",
           "trafficrec"]
