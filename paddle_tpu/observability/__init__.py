"""Unified telemetry subsystem (metrics registry, recompile tracer,
structured run telemetry) — docs/observability.md.

Layering: ``metrics`` and ``telemetry`` are pure stdlib (importable
from the jax-free bench orchestrator and worker processes); ``trace``
imports jax lazily inside the wrapping calls.
"""
from . import metrics, telemetry, trace  # noqa: F401
from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,  # noqa: F401
                      default_time_buckets, get_registry)
from .telemetry import TelemetryCallback, TelemetryLogger  # noqa: F401
from .trace import RecompileTracer, get_tracer, report_all  # noqa: F401

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "default_time_buckets", "get_registry",
           "TelemetryCallback", "TelemetryLogger", "RecompileTracer",
           "get_tracer", "report_all", "metrics", "telemetry", "trace"]
