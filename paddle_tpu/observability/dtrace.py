"""Distributed request tracing — one causal span tree per fleet request.

Round 10's ``SpanRecorder`` answers "what did THIS engine's host loop
do"; a fleet request crosses router -> transport -> replica -> engine
(and, under failover or hedging, SEVERAL replicas), so the question
"where did this request's 800 ms go" needs spans that share a trace
identity across those hops. This module is that layer:

- a **trace context** — ``{"trace_id", "span_id", "proc", "hops"}`` —
  minted by ``FleetRouter.submit`` and propagated through the
  ``ReplicaClient`` transport verbs into ``InprocReplica`` /
  ``ServingEngine``. ``span_id`` is the parent for anything the
  receiving hop records; ``proc`` names the lane (router / replica
  name); ``hops`` is a propagation budget (``hop()``) so a
  pathological failover loop cannot grow a tree without bound;
- a **TraceStore**: bounded ring of whole span trees. Eviction is by
  TRACE, never by span — an exported tree can never contain an orphan
  child whose parent was evicted out from under it (the round-10 ring
  could); a tree that overflows ``max_spans_per_trace`` stops
  accepting spans and is marked ``truncated`` instead of losing
  interior nodes;
- **latency attribution**: ``attribution(trace_id)`` decomposes the
  root span into its direct-child hops (placement wait, transport,
  per-replica legs with their nested queue/prefill/decode), reports
  the interval-union coverage of the end-to-end wall time, and flags
  ``within_tolerance`` when the uncovered remainder is under
  ``tolerance`` (default 5%) — legs annotated ``hedge_loser`` stay in
  the tree but out of the serial sum, since they overlap the winner
  by construction;
- a **cross-process Perfetto merge**: ``to_chrome``/``export_chrome``
  emit one ``{"traceEvents": [...]}`` timeline with a process group
  per ``proc`` (router lane + one lane per replica) and a thread per
  request, on the same epoch<->perf_counter base as ``spans.py`` so
  fleet traces align with the round-10 engine/train/profiler
  timelines. ``clock_offsets={proc: seconds}`` reconciles per-process
  clock skew (the router estimates offsets from heartbeat
  timestamps; in-process replicas share the clock, so offsets are
  ~0 — the seam exists for the subprocess deployment).

All timestamps are ``time.perf_counter()`` seconds (``now()``).
Every mutating call is a no-op while ``introspect.introspecting()``
is set — tracing can never perturb the AOT replay or read as work in
a zero-recompile assertion — and tolerates ``ctx=None`` (an untraced
request records nothing). Stdlib-only; sibling imports are lazy.
"""
from __future__ import annotations

import itertools
import json
import math
import os
import threading
import time
from collections import OrderedDict

__all__ = ["TraceStore", "get_store", "hop", "now"]

_id_counter = itertools.count(1)


def now():
    """The trace clock (perf_counter seconds)."""
    return time.perf_counter()


def _suppressed():
    try:
        from .introspect import introspecting
    except ImportError:  # standalone file-load (bench._obs_mod)
        return False
    return introspecting()


def _finite(obj):
    """Non-finite floats -> None (RFC-valid JSON). Duplicated across
    the stdlib-only observability modules on purpose — each stays
    standalone-loadable."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: _finite(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_finite(v) for v in obj]
    return obj


def _to_epoch_us(perf_t):
    """Epoch microseconds on the SAME base spans.py uses, so a fleet
    timeline and an engine/train timeline land aligned in one
    Perfetto view."""
    try:
        from .spans import _to_epoch_us as base
        return base(perf_t)
    except ImportError:
        return (_EPOCH_BASE + (perf_t - _PERF_BASE)) * 1e6


_EPOCH_BASE = time.time()
_PERF_BASE = time.perf_counter()


def hop(ctx):
    """Cross one process/transport boundary: returns a propagatable
    copy with the hop budget decremented, or None when the budget is
    exhausted (the receiver then records nothing — the tree stays
    bounded even if requests bounce forever)."""
    if ctx is None or int(ctx.get("hops", 0)) <= 0:
        return None
    return dict(ctx, hops=int(ctx["hops"]) - 1)


class TraceStore:
    """Bounded store of causally-linked span trees.

    max_traces: whole-tree ring bound (oldest TRACE evicts first).
    max_spans_per_trace: per-tree span cap; overflowing trees are
        marked ``truncated`` and drop NEW spans — interior nodes are
        never removed, so parents outlive their children by
        construction.
    sample: keep-fraction in [0, 1] for whole trees (default 1.0 =
        trace everything; the process-global store reads
        ``PADDLE_TPU_TRACE_SAMPLE``). Sampling is head-based and
        DETERMINISTIC — a fractional accumulator keeps exactly
        ``sample`` of new_trace calls, evenly spaced, no RNG — and
        by WHOLE TREE: a sampled-out request records nothing anywhere
        (``new_trace`` returns None, every hop no-ops), so whole-tree
        tracing stays bounded at high QPS. Dropped traces are counted
        in ``sampled_out`` AND as ``fleet_traces_sampled_out_total``
        in the process-global registry — dropped is visible, never
        silent.
    """

    def __init__(self, max_traces=256, max_spans_per_trace=512,
                 sample=1.0):
        self.max_traces = int(max_traces)
        self.max_spans_per_trace = int(max_spans_per_trace)
        self.sample = min(max(float(sample), 0.0), 1.0)
        self._sample_acc = 0.0
        self.sampled_out = 0
        self._sampled_counter = None
        self._traces = OrderedDict()   # trace_id -> tree record
        self._lock = threading.Lock()

    # -- recording ---------------------------------------------------------

    def new_trace(self, name="request", proc="router", t0=None,
                  rid=None, hops=8, args=None, force=False):
        """Open a new trace with its root span; returns the root
        context (None under introspection). Evicts the oldest WHOLE
        trace beyond max_traces. ``force=True`` bypasses the
        head-sampling gate (never the introspection suppression) —
        the traffic-capture plane keeps every ARCHIVED request's span
        tree so an archive entry always carries its attribution,
        whatever PADDLE_TPU_TRACE_SAMPLE says about the rest."""
        if _suppressed():
            return None
        if not force and self.sample < 1.0 and not self._sample_keep():
            return None
        trace_id = f"t{os.getpid():x}-{next(_id_counter)}"
        span = {"id": next(_id_counter), "parent": None,
                "name": name, "proc": proc,
                "t0": now() if t0 is None else float(t0), "t1": None,
                "outcome": None, "args": dict(args or {})}
        with self._lock:
            self._traces[trace_id] = {
                "spans": OrderedDict([(span["id"], span)]),
                "rid": rid, "truncated": False}
            while len(self._traces) > self.max_traces:
                self._traces.popitem(last=False)  # whole tree, never
                #                                   an interior node
        return {"trace_id": trace_id, "span_id": span["id"],
                "proc": proc, "hops": int(hops), "t0": span["t0"]}

    def _sample_keep(self):
        """Deterministic fractional-accumulator sampling decision.
        Dropping increments the internal count and the
        ``fleet_traces_sampled_out_total`` counter (lazily resolved
        from the process-global registry; absent in standalone loads
        — the internal count still tells the story there)."""
        with self._lock:
            self._sample_acc += self.sample
            if self._sample_acc >= 1.0:
                self._sample_acc -= 1.0
                return True
            self.sampled_out += 1
        if self._sampled_counter is None:
            try:
                from .metrics import get_registry
                self._sampled_counter = get_registry().counter(
                    "fleet_traces_sampled_out_total",
                    help="whole request trace trees dropped by the "
                         "PADDLE_TPU_TRACE_SAMPLE head-sampling knob")
            except ImportError:
                self._sampled_counter = False   # standalone load
        if self._sampled_counter:
            self._sampled_counter.inc()
        return False

    def _append(self, trace_id, span):
        # every caller already holds self._lock (start_span/end_span/
        # annotate take it before delegating) — re-taking a plain Lock
        # here would self-deadlock
        # tpulint: disable-next-line=CON01
        rec = self._traces.get(trace_id)
        if rec is None:
            return False  # trace already evicted: drop, never orphan
        if len(rec["spans"]) >= self.max_spans_per_trace:
            rec["truncated"] = True
            return False
        rec["spans"][span["id"]] = span
        return True

    def start_span(self, ctx, name, proc=None, t0=None, args=None):
        """Open a child span under ``ctx``; returns the CHILD context
        (same trace, new span_id) or None (no ctx / suppressed /
        evicted / truncated). Pass the child ctx back to end_span."""
        if ctx is None or _suppressed():
            return None
        span = {"id": next(_id_counter), "parent": int(ctx["span_id"]),
                "name": name, "proc": proc or ctx.get("proc", "?"),
                "t0": now() if t0 is None else float(t0), "t1": None,
                "outcome": None, "args": dict(args or {})}
        with self._lock:
            if not self._append(ctx["trace_id"], span):
                return None
        return {"trace_id": ctx["trace_id"], "span_id": span["id"],
                "proc": span["proc"], "hops": int(ctx.get("hops", 0)),
                "t0": span["t0"]}

    def end_span(self, ctx, t1=None, outcome=None, args=None):
        """Close the span ``ctx`` points at (idempotent: the first
        close wins — a hedge loser's late result cannot rewrite the
        outcome the router recorded at cancel time)."""
        if ctx is None or _suppressed():
            return
        with self._lock:
            rec = self._traces.get(ctx["trace_id"])
            span = None if rec is None \
                else rec["spans"].get(int(ctx["span_id"]))
            if span is None or span["t1"] is not None:
                return
            span["t1"] = now() if t1 is None else float(t1)
            if outcome is not None:
                span["outcome"] = str(outcome)
            if args:
                span["args"].update(args)

    def add_span(self, ctx, name, t0, t1=None, proc=None, args=None,
                 outcome=None):
        """One complete child span of ``ctx`` ([t0, t1] perf_counter
        seconds, t1 None = now). Returns the span id or None."""
        if ctx is None or _suppressed():
            return None
        span = {"id": next(_id_counter), "parent": int(ctx["span_id"]),
                "name": name, "proc": proc or ctx.get("proc", "?"),
                "t0": float(t0),
                "t1": now() if t1 is None else float(t1),
                "outcome": None if outcome is None else str(outcome),
                "args": dict(args or {})}
        with self._lock:
            if not self._append(ctx["trace_id"], span):
                return None
        return span["id"]

    def annotate(self, ctx, **args):
        """Merge args into the span ``ctx`` points at (e.g. the
        prefix-dedup boundary on a continuation leg)."""
        if ctx is None or _suppressed():
            return
        with self._lock:
            rec = self._traces.get(ctx["trace_id"])
            span = None if rec is None \
                else rec["spans"].get(int(ctx["span_id"]))
            if span is not None:
                span["args"].update(args)

    # -- reading -----------------------------------------------------------

    def trace_ids(self):
        with self._lock:
            return list(self._traces)

    def find(self, rid):
        """Latest trace_id opened for fleet request ``rid`` (None when
        unknown or evicted)."""
        with self._lock:
            found = None
            for tid, rec in self._traces.items():
                if rec["rid"] == rid:
                    found = tid
            return found

    def summaries(self):
        """Per-trace index rows in ONE pass under the lock — no tree
        build, no span copies, no attribution. This is what a
        periodically-scraped /traces index must use: the full
        attribution machinery over every stored trace would contend
        with the serving control loop on this store's lock."""
        out = []
        with self._lock:
            for tid, rec in self._traces.items():
                spans = rec["spans"]
                root = next(iter(spans.values()), None)
                if root is None:
                    continue
                t1 = root["t1"]
                if t1 is None:  # still open: bound at latest child
                    t1 = max((s["t1"] for s in spans.values()
                              if s["t1"] is not None), default=None)
                out.append({
                    "trace_id": tid, "rid": rec["rid"],
                    "outcome": root["outcome"],
                    "e2e_s": None if t1 is None
                    else round(max(t1 - root["t0"], 0.0), 6),
                    "spans": len(spans),
                    "truncated": rec["truncated"]})
        return out

    def _snapshot(self, trace_id):
        with self._lock:
            rec = self._traces.get(trace_id)
            if rec is None:
                return None
            return {"rid": rec["rid"], "truncated": rec["truncated"],
                    "spans": [dict(s, args=dict(s["args"]))
                              for s in rec["spans"].values()]}

    def tree(self, trace_id):
        """Nested span tree: each node is the span dict plus
        ``children`` (insertion order). None for unknown traces."""
        rec = self._snapshot(trace_id)
        if rec is None:
            return None
        nodes = {s["id"]: dict(s, children=[]) for s in rec["spans"]}
        root = None
        for s in rec["spans"]:
            node = nodes[s["id"]]
            parent = nodes.get(s["parent"])
            if parent is not None:
                parent["children"].append(node)
            elif root is None:
                root = node
        if root is None:
            return None
        return {"trace_id": trace_id, "rid": rec["rid"],
                "truncated": rec["truncated"], "root": root}

    def spans(self, trace_id):
        rec = self._snapshot(trace_id)
        return [] if rec is None else rec["spans"]

    # -- attribution -------------------------------------------------------

    def attribution(self, trace_id, tolerance=0.05):
        """Hop-by-hop latency decomposition of one trace.

        The root span's direct children are the hops (placement wait,
        transport, replica legs). ``hops_sum_s`` adds the SERIAL hops
        — a hop annotated ``hedge_loser`` in its args is excluded
        because it overlaps the winning leg by construction (a
        client-CANCELLED only leg is real serial work and stays in);
        ``covered_s`` is the interval-union coverage of ALL hops
        against the root, so overlapping legs are counted once;
        ``within_tolerance`` holds when the uncovered remainder is
        under ``tolerance * e2e``. Each hop carries its own child
        breakdown (queue/prefill/decode inside a replica leg) plus
        ``self_s``, the hop time its children do not explain."""
        t = self.tree(trace_id)
        if t is None:
            return None
        root = t["root"]
        t_end = root["t1"]
        if t_end is None:  # still open: bound at the latest child
            t_end = max([root["t0"]]
                        + [s["t1"] for s in self.spans(trace_id)
                           if s["t1"] is not None])
        e2e = max(t_end - root["t0"], 0.0)

        def dur(n, default_end=t_end):
            end = n["t1"] if n["t1"] is not None else default_end
            return max(end - n["t0"], 0.0)

        hops, intervals, serial = [], [], 0.0
        for child in root["children"]:
            d = dur(child)
            kids = [{"name": k["name"], "proc": k["proc"],
                     "dur_s": round(dur(k), 6),
                     "outcome": k["outcome"], "args": k["args"]}
                    for k in child["children"]]
            row = {"span_id": child["id"], "name": child["name"],
                   "proc": child["proc"], "outcome": child["outcome"],
                   "t0_rel_s": round(child["t0"] - root["t0"], 6),
                   "dur_s": round(d, 6), "args": child["args"],
                   "children": kids,
                   "self_s": round(max(d - sum(k["dur_s"]
                                               for k in kids), 0.0), 6)}
            hops.append(row)
            lo = max(child["t0"], root["t0"])
            hi = min(child["t1"] if child["t1"] is not None else t_end,
                     t_end)
            if hi > lo:
                intervals.append((lo, hi))
            if not child["args"].get("hedge_loser"):
                serial += d
        # interval-union sweep: overlapping hops (hedge legs) count
        # their shared wall time once
        covered, cur_lo, cur_hi = 0.0, None, None
        for lo, hi in sorted(intervals):
            if cur_hi is None or lo > cur_hi:
                if cur_hi is not None:
                    covered += cur_hi - cur_lo
                cur_lo, cur_hi = lo, hi
            else:
                cur_hi = max(cur_hi, hi)
        if cur_hi is not None:
            covered += cur_hi - cur_lo
        unattributed = max(e2e - covered, 0.0)
        return {"trace_id": trace_id, "rid": t["rid"],
                "outcome": root["outcome"],
                "e2e_s": round(e2e, 6), "hops": hops,
                "hops_sum_s": round(serial, 6),
                "covered_s": round(covered, 6),
                "unattributed_s": round(unattributed, 6),
                "tolerance": float(tolerance),
                "within_tolerance": bool(
                    e2e == 0.0 or unattributed <= tolerance * e2e),
                "truncated": t["truncated"]}

    # -- Perfetto export ---------------------------------------------------

    def to_chrome(self, trace_ids=None, clock_offsets=None):
        """Chrome trace events for the given traces (default: all).
        One process group per ``proc`` — router first, replicas after —
        one thread per request inside it, so concurrent requests on a
        replica never render as a mis-nested stack. ``clock_offsets``
        maps proc -> seconds SUBTRACTED from that proc's timestamps
        (per-process skew reconciled from heartbeats)."""
        offsets = dict(clock_offsets or {})
        ids = self.trace_ids() if trace_ids is None else list(trace_ids)
        rows = []     # (proc, lane, span)
        procs, lanes = [], {}
        for tid in ids:
            rec = self._snapshot(tid)
            if rec is None:
                continue
            lane = f"req{rec['rid']}" if rec["rid"] is not None else tid
            t_end = max([s["t1"] for s in rec["spans"]
                         if s["t1"] is not None] or [None],
                        key=lambda v: -1 if v is None else v)
            for s in rec["spans"]:
                if s["t1"] is None and t_end is None:
                    continue  # nothing closed yet: skip open spans
                rows.append((s["proc"], lane, s, t_end))
                if s["proc"] not in procs:
                    procs.append(s["proc"])
                lanes.setdefault((s["proc"], lane),
                                 len([k for k in lanes
                                      if k[0] == s["proc"]]))
        procs.sort(key=lambda p: (p != "router", p))
        pid_of = {p: i + 1 for i, p in enumerate(procs)}
        events = []
        for p in procs:
            events.append({"name": "process_name", "ph": "M",
                           "pid": pid_of[p], "tid": 0,
                           "args": {"name": p}})
        for (p, lane), tid_i in sorted(lanes.items(),
                                       key=lambda kv: kv[1]):
            events.append({"name": "thread_name", "ph": "M",
                           "pid": pid_of[p], "tid": tid_i,
                           "args": {"name": lane}})
        for p, lane, s, t_end in rows:
            off = float(offsets.get(p, 0.0))
            t1 = s["t1"] if s["t1"] is not None else t_end
            if t1 is None:
                continue
            args = dict(s["args"])
            if s["outcome"] is not None:
                args["outcome"] = s["outcome"]
            events.append({
                "name": s["name"], "cat": "fleet", "ph": "X",
                "ts": _to_epoch_us(s["t0"] - off),
                "dur": max((t1 - s["t0"]) * 1e6, 0.0),
                "pid": pid_of[p], "tid": lanes[(p, lane)],
                "args": args})
        events.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0)))
        return events

    def export_chrome(self, path, trace_ids=None, clock_offsets=None,
                      extra_recorders=()):
        """Write one merged Perfetto timeline (plus any round-10
        SpanRecorders — same epoch base) to ``path``. Atomic; always
        RFC-valid JSON."""
        events = self.to_chrome(trace_ids, clock_offsets)
        base_pid = max([e["pid"] for e in events], default=0)
        for i, rec in enumerate(extra_recorders):
            events.extend(rec.to_chrome(pid=base_pid + i + 1))
        doc = {"traceEvents": events, "displayTimeUnit": "ms"}
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            try:
                json.dump(doc, f, allow_nan=False)
            except ValueError:
                f.seek(0)
                f.truncate()
                json.dump(_finite(doc), f, allow_nan=False)
        os.replace(tmp, path)
        return path

    def clear(self):
        with self._lock:
            self._traces.clear()


_default = None
_default_lock = threading.Lock()


def get_store():
    """The process-global trace store (router mints into it, engines
    record into it; capacity via PADDLE_TPU_TRACE_CAP, default 256
    traces; head-sampling fraction via PADDLE_TPU_TRACE_SAMPLE,
    default 1.0 = keep everything — lower it so whole-tree tracing
    stays bounded at high QPS; drops count in
    ``fleet_traces_sampled_out_total``)."""
    global _default
    with _default_lock:
        if _default is None:
            try:
                cap = int(os.environ.get("PADDLE_TPU_TRACE_CAP", 256))
            except ValueError:
                cap = 256
            try:
                sample = float(os.environ.get(
                    "PADDLE_TPU_TRACE_SAMPLE", 1.0))
            except ValueError:
                sample = 1.0
            _default = TraceStore(max_traces=cap, sample=sample)
        return _default
