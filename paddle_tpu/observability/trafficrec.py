"""Traffic capture — recorded fleet workloads as replayable archives.

Every measurement the fleet produces today dies with the run: the
history plane remembers *aggregates*, the trace store remembers a
bounded ring of span trees, but nobody remembers the WORKLOAD — which
requests arrived when, with what prompts, tenants, priorities and
deadlines, and what the fleet answered. That record is the missing
input for every what-if question the ROADMAP's autotune/autoscale
items need: "would yesterday's traffic have met its SLO with a lower
hedge threshold" is only answerable by re-driving yesterday's traffic
(the Gemma-on-Cloud-TPU serving paper's trace-replayed TTFT/e2e
decomposition, PAPERS.md; TpuGraphs shows captured workload corpora
are what make knob search a learnable problem).

This module is the capture half (``tools/fleet_replay.py`` is the
replay half): a ``TrafficRecorder`` the FleetRouter writes through —

- one ``arrival`` record per ADMITTED request (rid, arrival offset on
  the shared epoch<->perf_counter base, tenant, priority, remaining
  deadline budget, prompt tokens, decode budget, eos) at submit;
- one ``resolve`` record per resolved request (status, output tokens,
  TTFT/e2e, failover/hedge flags, and the round-12 per-hop latency
  attribution compacted to ``[{name, proc, dur_s, outcome}, ...]``);
- ``meta`` records carrying fleet facts replay needs to reproduce
  tokens exactly (per-replica sampling params off the health plane).

Disk format = the write-ahead journal's, reused deliberately: bounded
rotating ``cap-NNNNNN.jsonl`` segments of ``<len:8hex> <crc:8hex>
<compact-json>`` lines, finalized with ``io/atomic`` ``.complete``
sidecars on rotation, torn-tail-tolerant replay (a bad line is
dropped and counted, never raised on). Rotation keeps at most
``max_segments`` segments — capture is a ring over the recent past,
not an unbounded log.

Capture discipline:

- **sampling** is head-based and deterministic (the TraceStore's
  fractional-accumulator, no RNG) via ``sample`` /
  ``PADDLE_TPU_CAPTURE_SAMPLE``; a sampled-out request is counted
  (``fleet_capture_sampled_out_total``), never silently absent;
- **trace coherence**: the router force-keeps the span tree of every
  captured request (``TraceStore.new_trace(force=True)``), so an
  archived request always carries its attribution; divergences (a
  captured request that still resolved without one) count in
  ``fleet_capture_trace_missing_total``;
- **suppressed under introspecting()** — capture can never perturb an
  AOT replay or read as work in a zero-recompile assertion;
- **best-effort**: a disk failure drops the record and counts
  ``fleet_capture_errors_total`` — losing a capture line must never
  take the serving path down (the journal owns durability-critical
  state; this plane owns measurement).

Cost is metered in the owner's registry (``fleet_capture_*``,
catalogue in docs/observability.md). Stdlib-only by contract
(standalone-loadable via bench._obs_mod; io/atomic resolved lazily
with the same file-load fallback flightrec/history use).
"""
from __future__ import annotations

import json
import math
import os
import re
import threading
import time
import zlib

__all__ = ["TrafficRecorder", "load_archive"]

_FORMAT = 1
_SEG_RE = re.compile(r"^cap-(\d{6})\.jsonl$")

_atomic_mod = None


def _atomic():
    """io/atomic.py, lazily — package import when available, straight
    file-load otherwise (standalone mode has no package context; the
    helper is stdlib-only by contract). Same pattern as history.py."""
    global _atomic_mod
    if _atomic_mod is None:
        try:
            from ..io import atomic as mod
        except ImportError:
            import importlib.util as ilu
            path = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                os.pardir, "io", "atomic.py")
            spec = ilu.spec_from_file_location(
                "_bench_obs_io_atomic", path)
            mod = ilu.module_from_spec(spec)
            spec.loader.exec_module(mod)
        _atomic_mod = mod
    return _atomic_mod


def _suppressed():
    try:
        from .introspect import introspecting
    except ImportError:  # standalone file-load (bench._obs_mod)
        return False
    return introspecting()


def _finite(obj):
    """Non-finite floats -> None (RFC-valid JSON). Duplicated across
    the stdlib-only observability modules on purpose — each stays
    standalone-loadable."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: _finite(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_finite(v) for v in obj]
    return obj


def _frame(rec):
    """One length-prefixed, CRC-checksummed line (the journal's wire
    format, duplicated like history.py so this module stays
    standalone-loadable)."""
    try:
        payload = json.dumps(rec, separators=(",", ":"),
                             allow_nan=False)
    except ValueError:
        payload = json.dumps(_finite(rec), separators=(",", ":"),
                             allow_nan=False)
    raw = payload.encode("utf-8")
    crc = zlib.crc32(raw) & 0xFFFFFFFF
    return b"%08x %08x " % (len(raw), crc) + raw + b"\n"


def _parse_line(line):
    """Record dict for one frame line, or None when torn/corrupt."""
    if len(line) < 19 or line[8:9] != b" " or line[17:18] != b" ":
        return None
    try:
        n = int(line[:8], 16)
        crc = int(line[9:17], 16)
    except ValueError:
        return None
    raw = line[18:]
    if len(raw) != n or (zlib.crc32(raw) & 0xFFFFFFFF) != crc:
        return None
    try:
        rec = json.loads(raw.decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        return None
    return rec if isinstance(rec, dict) else None


def _segments(directory):
    """[(num, path)] ascending for every cap segment in `directory`."""
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    for name in names:
        m = _SEG_RE.match(name)
        if m:
            out.append((int(m.group(1)),
                        os.path.join(directory, name)))
    out.sort()
    return out


class TrafficRecorder:
    """Bounded rotating request-capture archive writer.

    directory: created if missing; segments rotate inside it.
    registry: MetricsRegistry the ``fleet_capture_*`` series land in
        (None = unmetered — the internal counts still tell the story).
    sample: keep-fraction in [0, 1] for whole requests (arrival AND
        resolve travel together); default reads
        ``PADDLE_TPU_CAPTURE_SAMPLE`` (1.0 = capture everything).
        Deterministic fractional-accumulator head sampling, no RNG.
    segment_max_bytes: rotation threshold for the active segment.
    max_segments: ring bound — rotation deletes the oldest segments
        beyond this, so capture can never fill a disk.
    """

    def __init__(self, directory, *, registry=None, sample=None,
                 segment_max_bytes=1 << 20, max_segments=8):
        self.dir = os.path.abspath(str(directory))
        os.makedirs(self.dir, exist_ok=True)
        if sample is None:
            try:
                sample = float(os.environ.get(
                    "PADDLE_TPU_CAPTURE_SAMPLE", 1.0))
            except ValueError:
                sample = 1.0
        self.sample = min(max(float(sample), 0.0), 1.0)
        self.segment_max_bytes = int(segment_max_bytes)
        self.max_segments = max(int(max_segments), 1)
        self._sample_acc = 0.0
        self._lock = threading.Lock()
        self._meta = {}          # fleet facts (sampling params, ...)
        self._meta_dirty = False
        self._closed = False
        self._m = {}
        if registry is not None:
            for name, help_ in (
                    ("requests", "requests captured into the traffic "
                                 "archive (arrival records)"),
                    ("records", "archive records written (arrival + "
                                "resolve + meta)"),
                    ("bytes", "archive bytes written"),
                    ("errors", "capture writes dropped on an I/O "
                               "failure (capture is best-effort)"),
                    ("rotations", "archive segment rotations"),
                    ("sampled_out", "requests dropped by the capture "
                                    "sampling knob"),
                    ("trace_missing", "captured requests that resolved "
                                      "without a span tree / "
                                      "attribution (capture<->trace "
                                      "sampling divergence)")):
                self._m[name] = registry.counter(
                    f"fleet_capture_{name}_total", help=help_)
        self.sampled_out = 0
        self.errors = 0
        # epoch<->perf_counter base: arrival offsets are recorded on
        # BOTH clocks so replay schedules on a monotonic base while
        # the archive stays joinable with history/trace timelines
        self._epoch0 = time.time()
        self._perf0 = time.perf_counter()
        segs = _segments(self.dir)
        num = (segs[-1][0] + 1) if segs else 1
        self._active = self._seg_path(num)
        self._f = open(self._active, "ab")
        self._size = 0
        self._write_rec({"kind": "header", "format": _FORMAT,
                         "segment": num,
                         "epoch0": round(self._epoch0, 6)})
        self._prune(keep=self._active)

    # -- metrics ----------------------------------------------------------

    def _inc(self, name, n=1):
        c = self._m.get(name)
        if c is not None and n:
            c.inc(n)

    # -- sampling ---------------------------------------------------------

    def admit(self):
        """Deterministic capture decision for one request (call once
        per submit). Sampled-out requests count, never vanish."""
        if self._closed or _suppressed():
            return False
        if self.sample >= 1.0:
            return True
        with self._lock:
            self._sample_acc += self.sample
            if self._sample_acc >= 1.0:
                self._sample_acc -= 1.0
                return True
            self.sampled_out += 1
        self._inc("sampled_out")
        return False

    # -- recording --------------------------------------------------------

    def note_meta(self, **fields):
        """Merge fleet facts (e.g. per-replica sampling params) into
        the archive meta; written as a ``meta`` record on the next
        capture write and at the head of every later segment."""
        with self._lock:
            before = dict(self._meta)
            self._meta.update(fields)
            if self._meta != before:
                self._meta_dirty = True

    def record_arrival(self, rid, prompt, max_new, *, eos=None,
                       priority=0, tenant=None, deadline_ms=None,
                       t_epoch=None, t_pc=None):
        """Capture one admitted request. Returns ``{"segment",
        "offset"}`` (the /requests index's archive locator) or None
        (suppressed / closed / write failed)."""
        if self._closed or _suppressed():
            return None
        te = time.time() if t_epoch is None else float(t_epoch)
        tp = time.perf_counter() if t_pc is None else float(t_pc)
        rec = {"kind": "arrival", "rid": int(rid),
               "t_epoch": round(te, 6),
               "arrival_s": round(tp - self._perf0, 6),
               "tenant": tenant, "priority": int(priority),
               "deadline_ms": deadline_ms,
               "prompt": [int(t) for t in prompt],
               "max_new": int(max_new), "eos": eos}
        ref = self._append(rec)
        if ref is not None:
            self._inc("requests")
        return ref

    def note_trace_missing(self):
        """Count one capture<->trace sampling divergence (a captured
        request that resolved without a span tree / attribution) —
        part of the recorder's public surface so router wiring never
        reaches into private metric helpers."""
        self._inc("trace_missing")

    def record_resolve(self, rid, status, tokens, *, tenant=None,
                       replica=None, failovers=0, hedged=False,
                       e2e_s=None, ttft_s=None, hops=None,
                       trace_id=None):
        """Capture one resolved request's outcome + compact per-hop
        attribution rows. Returns the archive ref or None."""
        if self._closed or _suppressed():
            return None
        rec = {"kind": "resolve", "rid": int(rid),
               "status": str(status),
               "tokens": [int(t) for t in tokens],
               "tenant": tenant, "replica": replica,
               "failovers": int(failovers), "hedged": bool(hedged),
               "e2e_s": None if e2e_s is None else round(e2e_s, 6),
               "ttft_s": None if ttft_s is None else round(ttft_s, 6),
               "hops": hops, "trace_id": trace_id}
        return self._append(rec)

    def _write_rec(self, rec, fsync=False):
        """Frame + write one record to the active segment (caller
        holds no lock or the lock — pure file append). Raises OSError
        upward; _append owns the best-effort policy."""
        frame = _frame(dict(rec, ts=round(time.time(), 6)))
        self._f.write(frame)
        self._f.flush()
        if fsync:
            os.fsync(self._f.fileno())
        off = self._size
        self._size += len(frame)
        self._inc("records")
        self._inc("bytes", len(frame))
        return off

    def _append(self, rec):
        with self._lock:
            if self._closed:
                return None
            # best-effort contract: ANY write failure (OSError from
            # the disk, ValueError from a file handle a failed
            # rotation left closed) drops the record and counts — it
            # must never propagate into FleetRouter.submit
            try:
                if self._meta_dirty:
                    self._write_rec({"kind": "meta",
                                     "meta": dict(self._meta)})
                    # cleared only AFTER the write landed: a transient
                    # failure retries the meta on the next append
                    # instead of silently dropping the sampling params
                    self._meta_dirty = False
                seg = os.path.basename(self._active)
                off = self._write_rec(rec)
                if self._size >= self.segment_max_bytes:
                    self._rotate()
                return {"segment": seg, "offset": off}
            except (OSError, ValueError):
                self.errors += 1
                self._inc("errors")
                return None

    # -- rotation (ring of segments) --------------------------------------

    def _seg_path(self, num):
        return os.path.join(self.dir, f"cap-{num:06d}.jsonl")

    def _rotate(self):
        """Finalize the active segment (.complete sidecar — the
        io/atomic marker discipline) and open the next; drop the
        oldest segments beyond max_segments. Caller holds the lock."""
        atomic = _atomic()
        try:
            self._f.flush()
            os.fsync(self._f.fileno())
            self._f.close()
        except OSError:
            pass
        try:
            atomic.write_marker(atomic.marker_path(self._active),
                                {"bytes": self._size,
                                 "time": time.time()})
        except OSError:
            self.errors += 1
            self._inc("errors")
        segs = _segments(self.dir)
        num = (segs[-1][0] if segs else 0) + 1
        self._active = self._seg_path(num)
        try:
            self._f = open(self._active, "ab")
        except OSError:
            # the archive directory is gone/unwritable: capture is
            # dead. Close (errors counted) rather than leave a closed
            # handle every later append would crash on — the serving
            # path outlives its measurement plane, never vice versa
            self.errors += 1
            self._inc("errors")
            self._closed = True
            return
        self._size = 0
        self._write_rec({"kind": "header", "format": _FORMAT,
                         "segment": num,
                         "epoch0": round(self._epoch0, 6)})
        if self._meta:
            self._meta_dirty = True
        self._inc("rotations")
        self._prune(keep=self._active)

    def _prune(self, keep):
        atomic = _atomic()
        segs = _segments(self.dir)
        while len(segs) > self.max_segments:
            _num, victim = segs.pop(0)
            if victim == keep:
                break
            for path in (victim, atomic.marker_path(victim)):
                try:
                    os.remove(path)
                except OSError:
                    pass

    def close(self):
        """Flush + finalize the active segment (marker) — a closed
        archive replays with zero torn-tail drops. Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            atomic = _atomic()
            try:
                self._f.flush()
                os.fsync(self._f.fileno())
                self._f.close()
            except OSError:
                pass
            try:
                atomic.write_marker(atomic.marker_path(self._active),
                                    {"bytes": self._size,
                                     "time": time.time()})
            except OSError:
                self.errors += 1
                self._inc("errors")


# -- reading ---------------------------------------------------------------


def load_archive(directory):
    """Parse a capture archive into replayable request entries.

    Returns ``(entries, meta, stats)``:

    - ``entries``: one dict per captured request, arrival order —
      ``{rid, t_epoch, arrival_s (offset from the FIRST captured
      arrival), tenant, priority, deadline_ms, prompt, max_new, eos,
      status, tokens, ttft_s, e2e_s, hops, failovers, hedged,
      replica}`` — resolve fields are None for requests whose resolve
      record was lost to the ring/tail (counted in
      ``stats["unresolved"]``);
    - ``meta``: the merged ``meta`` records (newest wins);
    - ``stats``: ``{"segments", "records", "torn_drops",
      "unresolved"}``.

    Torn/corrupt lines are dropped and counted, never raised on —
    an archive truncated at any byte offset loads its prefix."""
    stats = {"segments": 0, "records": 0, "torn_drops": 0,
             "unresolved": 0}
    arrivals, resolves, meta = {}, {}, {}
    order = []
    for _num, path in _segments(directory):
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            continue
        stats["segments"] += 1
        for line in data.split(b"\n"):
            if not line:
                continue
            rec = _parse_line(line)
            if rec is None:
                stats["torn_drops"] += 1
                continue
            stats["records"] += 1
            kind = rec.get("kind")
            if kind == "arrival" and rec.get("rid") is not None:
                rid = int(rec["rid"])
                if rid not in arrivals:
                    order.append(rid)
                arrivals[rid] = rec
            elif kind == "resolve" and rec.get("rid") is not None:
                resolves[int(rec["rid"])] = rec
            elif kind == "meta":
                meta.update(rec.get("meta") or {})
    entries = []
    base = None
    for rid in order:
        a = arrivals[rid]
        if base is None:
            base = float(a.get("arrival_s") or 0.0)
        r = resolves.get(rid) or {}
        if not r:
            stats["unresolved"] += 1
        entries.append({
            "rid": rid, "t_epoch": a.get("t_epoch"),
            "arrival_s": round(
                max(float(a.get("arrival_s") or 0.0) - base, 0.0), 6),
            "tenant": a.get("tenant"),
            "priority": int(a.get("priority") or 0),
            "deadline_ms": a.get("deadline_ms"),
            "prompt": [int(t) for t in a.get("prompt") or []],
            "max_new": int(a.get("max_new") or 0),
            "eos": a.get("eos"),
            "status": r.get("status"),
            "tokens": None if not r
            else [int(t) for t in r.get("tokens") or []],
            "ttft_s": r.get("ttft_s"), "e2e_s": r.get("e2e_s"),
            "hops": r.get("hops"),
            "failovers": int(r.get("failovers") or 0),
            "hedged": bool(r.get("hedged")),
            "replica": r.get("replica")})
    return entries, meta, stats
