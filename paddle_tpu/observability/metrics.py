"""Typed metrics registry — the single place run facts accumulate.

Pure stdlib (no jax, no numpy): the registry must be importable from
the jax-free bench orchestrator, DataLoader worker processes and
validation tools alike. Three metric types, Prometheus-shaped:

- Counter: monotonically increasing total (requests served, steps
  skipped). ``inc(n)`` only; resets happen at the registry level.
- Gauge: last-written value (free KV pages, current loss).
- Histogram: fixed log-spaced buckets (a 1-2-5 ladder across decades),
  cumulative-bucket Prometheus export, count-weighted ``observe`` so a
  K-token decode dispatch records K per-token latencies in O(1), and
  bucket-interpolated ``quantile`` for p50/p99 rollups.

Snapshots are plain dicts and MERGEABLE: ``registry.merge(snapshot)``
folds another process/rung's snapshot in (counters and histogram
buckets add, gauges last-write-wins), which is how bench.py combines
per-rung serving registries into the campaign-level metrics.json.

Label support is deliberately minimal: a metric series is identified
by (name, sorted labels); ``registry.counter(name, labels={...})``
returns the series. Exports: ``to_prometheus()`` text and
``to_json()`` / ``dump(path)`` for the run report.

Hot-path cost: one ``observe`` is a bisect + four scalar updates under
the GIL — safe to call at host step boundaries; never call it from
inside a jitted function.
"""
from __future__ import annotations

import bisect
import json
import math
import os
import threading
import time

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "get_registry", "default_time_buckets"]


def default_time_buckets(lo_exp=-5, hi_exp=2):
    """Fixed log-spaced bucket bounds: a 1-2-5 ladder covering
    10**lo_exp .. 10**hi_exp seconds (default 10us .. 100s)."""
    return tuple(float(f"{m}e{e:+03d}")
                 for e in range(lo_exp, hi_exp + 1) for m in (1, 2, 5))


def _fmt(v):
    """Compact exact float formatting shared by exports (golden-string
    stable: repr of a float parsed from its own literal round-trips)."""
    if v == float("inf"):
        return "+Inf"
    return repr(float(v))


def _finite(obj):
    """Map non-finite floats to None for the JSON exports: bare
    NaN/Infinity tokens are not RFC JSON and break jq/JS consumers.
    (Duplicated in telemetry.py — these modules stay standalone-
    loadable, no intra-package imports at module scope.)"""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: _finite(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_finite(v) for v in obj]
    return obj


def _esc_label(v):
    """Prometheus exposition-format label escaping (backslash, quote,
    newline). Applied at series-key build time, so the key doubles as
    the exposition form AND crafted values cannot collide two
    distinct series into one key."""
    return (str(v).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _series_key(name, labels):
    if not labels:
        return name
    inner = ",".join(f'{k}="{_esc_label(labels[k])}"'
                     for k in sorted(labels))
    return f"{name}{{{inner}}}"


class _Metric:
    kind = "abstract"

    def __init__(self, name, help="", labels=None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self.key = _series_key(name, self.labels)


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help="", labels=None):
        super().__init__(name, help, labels)
        self.value = 0

    def inc(self, n=1):
        self.value += n

    def snapshot(self):
        return {"name": self.name, "labels": self.labels,
                "type": self.kind, "value": self.value}

    def merge(self, snap):
        self.value += snap["value"]

    def reset(self):
        self.value = 0


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name, help="", labels=None):
        super().__init__(name, help, labels)
        self.value = 0.0

    def set(self, v):
        self.value = v

    def inc(self, n=1):
        self.value += n

    def dec(self, n=1):
        self.value -= n

    def snapshot(self):
        return {"name": self.name, "labels": self.labels,
                "type": self.kind, "value": self.value}

    def merge(self, snap):
        self.value = snap["value"]  # last write wins

    def reset(self):
        self.value = 0.0


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help="", labels=None, buckets=None):
        super().__init__(name, help, labels)
        self.bounds = tuple(sorted(buckets)) if buckets \
            else default_time_buckets()
        # counts[i] = observations in (bounds[i-1], bounds[i]];
        # counts[-1] = overflow (> bounds[-1])
        self.counts = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.count = 0
        self.min = None
        self.max = None

    def observe(self, v, count=1):
        """Record `count` observations of value v (count-weighted: a
        batched dispatch of K tokens records K identical per-token
        latencies in one call)."""
        v = float(v)
        i = bisect.bisect_left(self.bounds, v)
        self.counts[i] += count
        self.sum += v * count
        self.count += count
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)

    def quantile(self, q):
        """Bucket-interpolated quantile estimate in [min, max]; None
        when empty."""
        if not self.count:
            return None
        target = q * self.count
        cum = 0
        for i, c in enumerate(self.counts):
            if not c:
                continue
            lo = self.bounds[i - 1] if i > 0 else self.min
            hi = self.bounds[i] if i < len(self.bounds) else self.max
            lo = max(lo, self.min)
            hi = min(hi, self.max)
            if cum + c >= target:
                frac = (target - cum) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            cum += c
        return self.max

    def mean(self):
        return self.sum / self.count if self.count else None

    def snapshot(self):
        return {"name": self.name, "labels": self.labels,
                "type": self.kind, "bounds": list(self.bounds),
                "counts": list(self.counts), "sum": self.sum,
                "count": self.count, "min": self.min, "max": self.max}

    def merge(self, snap):
        if tuple(snap["bounds"]) != self.bounds:
            raise ValueError(
                f"histogram {self.key}: cannot merge mismatched bucket "
                f"bounds ({len(snap['bounds'])} vs {len(self.bounds)})")
        for i, c in enumerate(snap["counts"]):
            self.counts[i] += c
        self.sum += snap["sum"]
        self.count += snap["count"]
        for attr, pick in (("min", min), ("max", max)):
            other = snap.get(attr)
            if other is not None:
                mine = getattr(self, attr)
                setattr(self, attr,
                        other if mine is None else pick(mine, other))

    def reset(self):
        self.counts = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.count = 0
        self.min = None
        self.max = None


class MetricsRegistry:
    """A set of named metric series. One process-global default
    (``get_registry()``); private instances are cheap and their
    snapshots merge into any other registry."""

    def __init__(self):
        self._metrics = {}
        # reentrant: merge() holds it across _get(); readers
        # (snapshot/scrape) hold it so a lazily-registered series
        # can't resize the dict mid-iteration under a scrape thread
        self._lock = threading.RLock()

    # -- creation/lookup ---------------------------------------------------
    def _get(self, cls, name, help, labels, **kw):
        key = _series_key(name, dict(labels or {}))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, help=help, labels=labels, **kw)
                self._metrics[key] = m
            elif not isinstance(m, cls):
                raise TypeError(f"metric {key!r} already registered as "
                                f"{m.kind}, requested {cls.kind}")
            return m

    def counter(self, name, help="", labels=None):
        return self._get(Counter, name, help, labels)

    def gauge(self, name, help="", labels=None):
        return self._get(Gauge, name, help, labels)

    def histogram(self, name, help="", labels=None, buckets=None):
        return self._get(Histogram, name, help, labels, buckets=buckets)

    def get(self, name, labels=None):
        """Existing series or None (read-side: tests, rollups). Under
        the lock like every other reader: a lazily-registered series
        resizing the dict mid-lookup on a scrape thread is the same
        race snapshot() guards against."""
        with self._lock:
            return self._metrics.get(
                _series_key(name, dict(labels or {})))

    def series(self):
        with self._lock:
            return list(self._metrics.values())

    def names(self):
        with self._lock:
            return sorted({m.name for m in self._metrics.values()})

    # -- snapshot/merge ----------------------------------------------------
    def snapshot(self):
        with self._lock:
            return {"ts": round(time.time(), 6),
                    "metrics": {m.key: m.snapshot()
                                for m in self._metrics.values()}}

    def merge(self, snap):
        """Fold a snapshot() (possibly from another registry/process)
        into this registry: counters/histograms add, gauges last-win.
        Atomic — a scrape sees all of the snapshot or none of it."""
        cls_by_kind = {"counter": Counter, "gauge": Gauge,
                       "histogram": Histogram}
        with self._lock:
            for entry in snap["metrics"].values():
                cls = cls_by_kind[entry["type"]]
                kw = {}
                if cls is Histogram:
                    kw["buckets"] = entry["bounds"]
                m = self._get(cls, entry["name"], "", entry["labels"],
                              **kw)
                m.merge(entry)

    def reset(self):
        """Zero every series IN PLACE (handles held by instrumented
        code stay valid) — bench uses this to split warmup from the
        timed window."""
        with self._lock:
            for m in self._metrics.values():
                m.reset()

    def clear(self):
        """Drop every series (test isolation)."""
        with self._lock:
            self._metrics.clear()

    # -- exports -----------------------------------------------------------
    def to_prometheus(self):
        """Prometheus text exposition format."""
        lines = []
        seen_names = set()
        for m in sorted(self.series(), key=lambda m: m.key):
            if m.name not in seen_names:
                seen_names.add(m.name)
                if m.help:
                    lines.append(f"# HELP {m.name} {m.help}")
                lines.append(f"# TYPE {m.name} {m.kind}")
            lab = m.key[len(m.name):]  # "" or {k="v",...}
            if isinstance(m, Histogram):
                cum = 0
                for bound, c in zip(m.bounds, m.counts):
                    cum += c
                    le = _series_key(
                        m.name + "_bucket",
                        {**m.labels, "le": _fmt(bound)})
                    lines.append(f"{le} {cum}")
                le = _series_key(m.name + "_bucket",
                                 {**m.labels, "le": "+Inf"})
                lines.append(f"{le} {m.count}")
                lines.append(f"{m.name}_sum{lab} {_fmt(m.sum)}")
                lines.append(f"{m.name}_count{lab} {m.count}")
            else:
                v = m.value
                lines.append(f"{m.key} {v if isinstance(v, int) else _fmt(v)}")
        return "\n".join(lines) + ("\n" if lines else "")

    def to_json(self, indent=None):
        doc = self.snapshot()
        try:
            return json.dumps(doc, indent=indent, allow_nan=False)
        except ValueError:
            return json.dumps(_finite(doc), indent=indent,
                              allow_nan=False)

    def dump(self, path, extra=None):
        """Write the snapshot (plus optional extra sections, e.g. the
        RecompileTracer report) as JSON to `path` — the metrics.json
        artifact bench/campaign stages emit. Always RFC-valid JSON: a
        NaN gauge (e.g. train_loss on a storm's last step) is nulled,
        never emitted as a bare NaN token jq/JS consumers reject."""
        doc = self.snapshot()
        if extra:
            doc.update(extra)
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            try:
                json.dump(doc, f, indent=1, allow_nan=False)
            except ValueError:
                f.seek(0)
                f.truncate()
                json.dump(_finite(doc), f, indent=1, allow_nan=False)
        os.replace(tmp, path)
        return path


_default = MetricsRegistry()


def get_registry():
    """The process-global default registry (train/serving/dataloader
    instrumentation publishes here unless handed a private one)."""
    return _default
