"""Declarative SLOs with sliding windows and multi-window burn-rate
alerts — the accounting layer between raw fleet metrics and "are we
violating what we promised users".

An ``SLObjective`` declares a target good-fraction over a rolling
horizon; every request maps to a good/bad event against it:

- ``latency`` objectives (TTFT p99, e2e p99): an observation is BAD
  when it exceeds ``threshold_s``. ``target=0.99`` is exactly the
  "p99 <= threshold" promise — at most 1% of requests may land above
  the threshold.
- ``availability`` objectives (goodput): the caller classifies each
  resolved request (shed / deadline-missed / failed count against
  served; client-initiated cancels count as neither).

``SLOTracker`` keeps a per-objective sliding deque of (ts, bad)
events and evaluates **multi-window burn rates** (the SRE-workbook
shape): for each ``{"short_s", "long_s", "burn"}`` window pair, the
burn rate is ``bad_fraction / error_budget`` (budget = 1 - target; a
burn of 1.0 spends the budget exactly at the horizon's pace), and the
window ALERTS only when BOTH the short and the long window burn
faster than ``burn`` — the short window makes alerts clear quickly
after recovery, the long window keeps a brief blip from paging.

``evaluate()`` exports the whole state as ``fleet_slo_*`` gauges into
the registry handed in (scrapeable next to the router's ``fleet_*``
series) and returns the structured report; ``alerts()`` is the
boolean rollup the router folds into its health snapshot so placement
(or an operator) can see burn state.

Stdlib-only; time base is ``time.monotonic()`` unless the caller
passes explicit ``now`` values (tests do, for determinism).
"""
from __future__ import annotations

import threading
import time
from collections import deque

__all__ = ["SLObjective", "SLOTracker", "default_windows",
           "default_fleet_slos"]


def default_windows():
    """Multi-window burn-rate ladder, scaled for a serving fleet with
    a short horizon (the classic SRE pairs are 5m/1h and 30m/6h on a
    30-day budget; these keep the same ~12x span ratio at a scale a
    test or a short canary can exercise)."""
    return ({"short_s": 60.0, "long_s": 720.0, "burn": 14.4},
            {"short_s": 300.0, "long_s": 3600.0, "burn": 6.0})


class SLObjective:
    """One promise: at least ``target`` of events are good.

    name: label on every exported series.
    kind: ``latency`` (``threshold_s`` required — an observation above
        it is bad) or ``availability`` (caller classifies).
    target: required good fraction in (0, 1); error budget = 1-target.
    threshold_s: latency cut line (latency kind only).
    """

    def __init__(self, name, kind="latency", target=0.99,
                 threshold_s=None):
        if kind not in ("latency", "availability"):
            raise ValueError(f"kind {kind!r}: latency | availability")
        if not 0.0 < float(target) < 1.0:
            raise ValueError(f"target must be in (0,1), got {target}")
        if kind == "latency" and threshold_s is None:
            raise ValueError(f"latency objective {name!r} needs "
                             "threshold_s")
        self.name = str(name)
        self.kind = kind
        self.target = float(target)
        self.threshold_s = None if threshold_s is None \
            else float(threshold_s)

    @property
    def budget(self):
        return 1.0 - self.target


def default_fleet_slos():
    """The Gemma-serving-paper decomposition as promises: time to
    first token, end-to-end latency, and goodput."""
    return (SLObjective("ttft", "latency", target=0.99,
                        threshold_s=1.0),
            SLObjective("e2e", "latency", target=0.99,
                        threshold_s=10.0),
            SLObjective("availability", "availability", target=0.999))


class SLOTracker:
    """Sliding-window good/bad accounting + burn-rate alerting for a
    set of objectives.

    objectives: iterable of SLObjective (unique names).
    windows: burn-window pairs ({"short_s","long_s","burn"}); the
        retention horizon is the longest long_s.
    registry: MetricsRegistry the ``fleet_slo_*`` gauges land in
        (None = no export; evaluate() still returns the report).
    max_events: per-objective deque bound (oldest events evict first
        even inside the horizon — a storm cannot grow memory).
    """

    def __init__(self, objectives=None, windows=None, registry=None,
                 max_events=4096):
        objectives = list(objectives if objectives is not None
                          else default_fleet_slos())
        names = [o.name for o in objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate objective names: {names}")
        self.objectives = {o.name: o for o in objectives}
        self.windows = [dict(w) for w in
                        (windows if windows is not None
                         else default_windows())]
        for w in self.windows:
            w["short_s"] = float(w["short_s"])
            w["long_s"] = float(w["long_s"])
            w["burn"] = float(w["burn"])
        self._horizon = max((w["long_s"] for w in self.windows),
                            default=0.0)
        self._events = {n: deque(maxlen=int(max_events))
                        for n in self.objectives}
        self._lock = threading.Lock()
        self._registry = registry
        self._gauges = {}

    # -- recording ---------------------------------------------------------

    def record_latency(self, name, seconds, now=None):
        """Observe one latency against a latency objective (unknown
        names are ignored so callers can record unconditionally)."""
        obj = self.objectives.get(name)
        if obj is None or obj.kind != "latency":
            return
        self._push(name, float(seconds) > obj.threshold_s, now)

    def record_event(self, name, good, now=None):
        """Observe one classified event against an availability
        objective."""
        obj = self.objectives.get(name)
        if obj is None:
            return
        self._push(name, not bool(good), now)

    def _push(self, name, bad, now):
        ts = time.monotonic() if now is None else float(now)
        with self._lock:
            dq = self._events[name]
            dq.append((ts, 1 if bad else 0))
            # prune beyond the horizon so idle periods do not pin a
            # storm's events forever
            cut = ts - self._horizon
            while dq and dq[0][0] < cut:
                dq.popleft()

    # -- evaluation --------------------------------------------------------

    def _window_stats(self, dq, lo):
        total = bad = 0
        for ts, b in reversed(dq):
            if ts < lo:
                break
            total += 1
            bad += b
        return total, bad

    def evaluate(self, now=None):
        """Per-objective report {sli, events, windows: [...], alert}
        + gauge export. ``sli`` is the good fraction over the longest
        window; a window with no events burns at 0 (no traffic spends
        no budget). Alert = ANY window pair whose short AND long burn
        both exceed its threshold."""
        ts = time.monotonic() if now is None else float(now)
        report = {}
        with self._lock:
            events = {n: list(dq) for n, dq in self._events.items()}
        for name, obj in self.objectives.items():
            dq = events[name]
            total_h, bad_h = self._window_stats(dq, ts - self._horizon)
            sli = 1.0 - (bad_h / total_h) if total_h else None
            rows, alert = [], False
            for w in self.windows:
                burns = {}
                for leg in ("short_s", "long_s"):
                    total, bad = self._window_stats(dq, ts - w[leg])
                    frac = (bad / total) if total else 0.0
                    burns[leg] = {"events": total, "bad": bad,
                                  "burn": frac / obj.budget}
                firing = (burns["short_s"]["burn"] > w["burn"]
                          and burns["long_s"]["burn"] > w["burn"])
                alert = alert or firing
                rows.append({"short_s": w["short_s"],
                             "long_s": w["long_s"],
                             "threshold": w["burn"],
                             "short": burns["short_s"],
                             "long": burns["long_s"],
                             "firing": firing})
            report[name] = {
                "kind": obj.kind, "target": obj.target,
                "threshold_s": obj.threshold_s,
                "events": total_h, "bad": bad_h, "sli": sli,
                "budget_remaining": (
                    None if sli is None
                    else 1.0 - (1.0 - sli) / obj.budget),
                "windows": rows, "alert": alert}
        self._export(report)
        return report

    def alerts(self, now=None):
        """{objective: bool} rollup (the health-snapshot form)."""
        return {n: r["alert"]
                for n, r in self.evaluate(now=now).items()}

    # -- gauge export ------------------------------------------------------

    def _gauge(self, name, help, **labels):
        key = (name, tuple(sorted(labels.items())))
        g = self._gauges.get(key)
        if g is None:
            g = self._registry.gauge(name, help=help, labels=labels)
            self._gauges[key] = g
        return g

    def _export(self, report):
        if self._registry is None:
            return
        for name, r in report.items():
            if r["sli"] is not None:
                self._gauge("fleet_slo_sli",
                            "good-event fraction over the longest "
                            "burn window", slo=name).set(r["sli"])
                self._gauge("fleet_slo_budget_remaining",
                            "error-budget fraction left over the "
                            "longest window (negative = overspent)",
                            slo=name).set(r["budget_remaining"])
            self._gauge("fleet_slo_alert",
                        "1 when any multi-window burn-rate pair is "
                        "firing", slo=name).set(1 if r["alert"] else 0)
            for w in r["windows"]:
                label = f"{w['short_s']:g}s/{w['long_s']:g}s"
                self._gauge("fleet_slo_burn_rate",
                            "short-window burn rate (bad fraction / "
                            "error budget) per window pair",
                            slo=name, window=label
                            ).set(w["short"]["burn"])
