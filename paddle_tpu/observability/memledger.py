"""Device-memory ledger — typed allocation accounting with headroom
forecasting (the "where did HBM go" plane).

A single replica now owns KV page pools, COW prefix sidecars,
spec-draft pools, int8 scale sidecars, weights, optimizer state and
AOT artifacts — yet nothing could answer "where did device memory go"
or "will admitting this request OOM the chip". This module closes the
gap: a process-wide **MemoryLedger** attributes every framework
allocation into a typed, labeled segment tree via explicit
``ledger.track(tag, buf)`` calls at the allocation seams (the engine's
page pool, the prefix index's dense sidecars, the speculative draft
pool, optimizer state, artifact restore), cross-checked against
ground truth — ``device.memory_stats()`` when the backend exposes it,
a ``jax.live_arrays()`` nbytes sum otherwise (CPU: tier-1 exercises
the same code) — with an ``unattributed_bytes`` residual so drift is
visible, never silent.

Design contracts, matching the rest of the observability plane:

- **Host-side only, zero-recompile untouched.** ``track``/``release``
  are pure dict arithmetic; the only jax touch is the periodic
  ``sweep()`` (driven from ``health()``/close, never the dispatch hot
  path) and even that is a host-side live-array walk, no device sync.
- **Dormant unless armed.** A never-armed engine creates NO ledger
  object and registers NO ``mem_*`` series (the spec-decode/profiler
  dormancy contract), so legacy goldens stay byte-identical.
- **Never silent.** The residual series carries what the seams missed;
  ``residual_alarm`` trips on growth past the baseline (the mem_smoke
  leak drill proves it fires), and audit callbacks (e.g. the prefix
  refcount audit) count failures into
  ``engine_mem_audit_failures_total``.
- **Stdlib-only, standalone-loadable** (``bench._obs_mod``): no
  intra-package imports at module scope; jax is imported lazily and
  its absence degrades to "no ground truth", never an exception.

Exports: ``MemoryLedger`` (track/release/set_level, ``would_fit``
admission hints, ``digest()`` for heartbeats, ``report()`` for the
``/memory`` endpoint, ``save()``/``load_snapshot()`` snapshot
persistence for ``tools/mem_diff.py``), ``MemoryAdmissionError`` (the
``PADDLE_TPU_MEM_ADMISSION=hard`` rejection type),
``active_ledger()``/``current_memory()`` (the flight-dump attach
point) and the env-knob readers.
"""
from __future__ import annotations

import json
import math
import os
import threading
import time

__all__ = ["MemoryLedger", "MemoryAdmissionError", "SEGMENTS",
           "nbytes_of", "default_ground_truth", "active_ledger",
           "current_memory", "load_snapshot",
           "mem_ledger_enabled_from_env", "mem_admission_from_env",
           "mem_capacity_from_env"]

#: the typed segment set — unknown tags fold into "other" (loudly:
#: the tag is kept as the label), never dropped
SEGMENTS = ("kv_pages", "prefix_sidecar", "spec_draft_pool", "weights",
            "optimizer_state", "grads", "activations_peak", "other")

ADMISSION_MODES = ("advisory", "hard")


def _finite(obj):
    """Map non-finite floats to None for the JSON exports (the
    metrics.py discipline, duplicated — this module stays
    standalone-loadable, no intra-package imports at module scope)."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: _finite(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_finite(v) for v in obj]
    return obj


def nbytes_of(obj):
    """Total ``.nbytes`` over an arbitrary nest of arrays (tuples,
    lists, dicts; jax or numpy — anything with an ``nbytes`` attr).
    Deduplicates by object identity inside ONE call, so a buffer
    referenced twice in the same nest counts once. None and
    unknown leaves count zero — the ledger under-attributes rather
    than guessing (the residual series carries the difference)."""
    seen = set()

    def walk(o):
        if o is None:
            return 0
        if isinstance(o, (list, tuple)):
            return sum(walk(x) for x in o)
        if isinstance(o, dict):
            return sum(walk(x) for x in o.values())
        nb = getattr(o, "nbytes", None)
        if nb is None:
            return 0
        oid = id(o)
        if oid in seen:
            return 0
        seen.add(oid)
        try:
            return int(nb)
        except (TypeError, ValueError):
            return 0

    return walk(obj)


def default_ground_truth():
    """(used_bytes, capacity_bytes) from the backend, or (None, None).

    Prefers the device's own ``memory_stats()`` (bytes_in_use /
    bytes_limit — real HBM accounting on TPU); falls back to a
    ``jax.live_arrays()`` nbytes sum (capacity unknown) so the CPU
    backend — and therefore tier-1 — exercises the exact same
    cross-check code path. Host-side only: enumerating live arrays is
    bookkeeping, not a device sync. No jax at all reads as "no ground
    truth", never an exception."""
    try:
        import jax
    except Exception:  # noqa: BLE001 — standalone/minimal environments
        return None, None
    try:
        stats = jax.devices()[0].memory_stats() or {}
    except Exception:  # noqa: BLE001 — backend without the API
        stats = {}
    used = stats.get("bytes_in_use")
    cap = stats.get("bytes_limit")
    if used:
        return int(used), (int(cap) if cap else None)
    try:
        return (int(sum(int(getattr(a, "nbytes", 0) or 0)
                        for a in jax.live_arrays())),
                (int(cap) if cap else None))
    except Exception:  # noqa: BLE001 — live_arrays absent/failed
        return None, (int(cap) if cap else None)


# -- env knobs --------------------------------------------------------------

def mem_ledger_enabled_from_env(default=False):
    """The ``PADDLE_TPU_MEM_LEDGER`` arm switch (default OFF:
    never-armed engines stay byte-identical to the legacy goldens,
    the spec-decode/profiler dormancy contract)."""
    raw = os.environ.get("PADDLE_TPU_MEM_LEDGER")
    if raw is None:
        return bool(default)
    return raw.lower() in ("1", "true", "on")


def mem_admission_from_env(default="advisory"):
    """``PADDLE_TPU_MEM_ADMISSION``: ``advisory`` (count-only hints)
    or ``hard`` (submit() rejects would-not-fit requests with a typed
    MemoryAdmissionError instead of OOMing mid-decode). Unknown values
    read as the default — a typo must not silently arm rejections."""
    raw = (os.environ.get("PADDLE_TPU_MEM_ADMISSION") or "").lower()
    return raw if raw in ADMISSION_MODES else default


def mem_capacity_from_env(default=None):
    """``PADDLE_TPU_MEM_CAPACITY_BYTES``: explicit device-memory
    budget for backends whose memory_stats() carries no bytes_limit
    (CPU tests, capped deployments). None = learn it from the device
    or run capacity-blind (would_fit answers None)."""
    raw = os.environ.get("PADDLE_TPU_MEM_CAPACITY_BYTES")
    if not raw:
        return default
    try:
        v = int(float(raw))
    except ValueError:
        return default
    return v if v > 0 else default


def _atomic():
    """io/atomic.py, lazily — package import when available, straight
    file-load otherwise (standalone mode has no package context)."""
    global _atomic_mod
    if _atomic_mod is None:
        try:
            from ..io import atomic as mod
        except ImportError:
            import importlib.util as ilu
            path = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                os.pardir, "io", "atomic.py")
            spec = ilu.spec_from_file_location(
                "_bench_obs_io_atomic", path)
            mod = ilu.module_from_spec(spec)
            spec.loader.exec_module(mod)
        _atomic_mod = mod
    return _atomic_mod


_atomic_mod = None


class MemoryAdmissionError(RuntimeError):
    """Typed hard-mode admission rejection: the request's KV page
    allocation would not fit the forecast headroom. Carries the
    numbers an operator/caller needs to size down or shed."""

    def __init__(self, need_bytes, headroom_bytes, capacity_bytes):
        self.need_bytes = int(need_bytes)
        self.headroom_bytes = (None if headroom_bytes is None
                               else int(headroom_bytes))
        self.capacity_bytes = (None if capacity_bytes is None
                               else int(capacity_bytes))
        super().__init__(
            f"admission would not fit: need {self.need_bytes} B, "
            f"headroom {self.headroom_bytes} B of "
            f"{self.capacity_bytes} B capacity "
            f"(PADDLE_TPU_MEM_ADMISSION=hard)")


# -- the ledger -------------------------------------------------------------

class MemoryLedger:
    """Typed, labeled device-allocation accounting for one process.

    Two attribution channels share the segment tree:

    - **tracked tokens** (``track``/``track_bytes`` -> token,
      ``release(token)``): allocations with an owner who sees both
      ends of the lifetime (the engine's page pool, the draft pool);
    - **levels** (``set_level``): segments whose byte count is
      recomputed from an authoritative source at the seam (the prefix
      index's sidecar inventory, optimizer state) — idempotent
      absolute sets, no release bookkeeping to get wrong.

    ``sweep()`` refreshes the ground-truth cross-check, the
    unattributed residual, the high watermark and the EWMA growth
    forecast; every public reader takes the internal lock, so
    exporter HTTP threads can read a live ledger safely.
    """

    def __init__(self, *, registry=None, name="engine",
                 capacity_bytes=None, ewma_alpha=0.3,
                 min_sweep_interval_s=0.5, residual_alarm_ratio=0.5,
                 residual_alarm_floor=1 << 20, ground_truth_fn=None):
        self.name = str(name)
        self.capacity_bytes = (None if capacity_bytes is None
                               else int(capacity_bytes))
        self.ewma_alpha = float(ewma_alpha)
        self.min_sweep_interval_s = float(min_sweep_interval_s)
        self.residual_alarm_ratio = float(residual_alarm_ratio)
        self.residual_alarm_floor = int(residual_alarm_floor)
        self._ground_truth_fn = (ground_truth_fn
                                 if ground_truth_fn is not None
                                 else default_ground_truth)
        self._lock = threading.RLock()
        self._tracked = {}      # token -> (segment, label, bytes)
        self._levels = {}       # (segment, label) -> bytes
        self._next_token = 0
        self._audits = []       # callables -> list of problem strings
        self.audit_problems = []    # last sweep's findings (bounded)
        # cross-check state (refreshed by sweep())
        self.ground_truth_bytes = None
        self.unattributed_bytes = None
        self._baseline_unattributed = None
        self.high_watermark_bytes = 0
        self.growth_bytes_per_s = 0.0
        self._growth_seeded = False
        self._last_sweep_t = None
        self._last_sweep_used = None
        self._closed = False
        # monotonic counters (health()/heartbeat views; the fleet
        # router delta-folds them into fleet_mem_* restart-tolerantly)
        self.tracked_allocs = 0
        self.released_allocs = 0
        self.admission_checks = 0
        self.admission_rejections = 0
        self.sweeps = 0
        self.audit_failures = 0
        self._registry = registry
        self._g_seg = {}
        self._g = {}
        self._c = {}
        if registry is not None:
            g = self._g
            g["attributed"] = registry.gauge(
                "engine_mem_attributed_bytes",
                help="device bytes attributed to typed ledger "
                     "segments (tracked allocations + level sets)")
            g["unattributed"] = registry.gauge(
                "engine_mem_unattributed_bytes",
                help="ground-truth device bytes the allocation seams "
                     "did not attribute — the residual that makes "
                     "accounting drift visible, never silent")
            g["used_ratio"] = registry.gauge(
                "engine_mem_hbm_used_ratio",
                help="device bytes in use / capacity (0 when "
                     "capacity is unknown); the sentinel's sustained-"
                     "growth band watches this series")
            g["headroom"] = registry.gauge(
                "engine_mem_headroom_bytes",
                help="forecast free device bytes (capacity - used; "
                     "0 when capacity is unknown)")
            g["watermark"] = registry.gauge(
                "engine_mem_high_watermark_bytes",
                help="peak device bytes in use observed by the "
                     "ledger's sweeps")
            g["growth"] = registry.gauge(
                "engine_mem_growth_bytes_per_s",
                help="EWMA growth of device bytes in use between "
                     "sweeps — the headroom-exhaustion forecast's "
                     "slope")
            c = self._c
            c["tracked_allocs"] = registry.counter(
                "engine_mem_tracked_allocs_total",
                help="allocations attributed through ledger.track at "
                     "the framework's allocation seams")
            c["released_allocs"] = registry.counter(
                "engine_mem_released_allocs_total",
                help="tracked allocations released back (the other "
                     "end of the lifetime the seams own)")
            c["admission_checks"] = registry.counter(
                "engine_mem_admission_checks_total",
                help="would_fit admission hints consulted before KV "
                     "page allocation")
            c["admission_rejections"] = registry.counter(
                "engine_mem_admission_rejections_total",
                help="admissions the hint judged would NOT fit "
                     "(advisory mode counts, hard mode also rejects)")
            c["audit_failures"] = registry.counter(
                "engine_mem_audit_failures_total",
                help="ledger sweep audit problems (e.g. prefix-index "
                     "refcounts disagreeing with live page-table "
                     "references — the release-on-failover leak "
                     "class)")
            c["sweeps"] = registry.counter(
                "engine_mem_sweeps_total",
                help="ground-truth cross-check sweeps taken")
            for m in g.values():
                m.set(0)
        with _active_lock:
            _active.append(self)

    # -- attribution -------------------------------------------------------

    @staticmethod
    def _seg_label(tag, label):
        tag = str(tag)
        if tag in SEGMENTS:
            return tag, ("" if label is None else str(label))
        # unknown tags fold into "other" with the tag kept as label —
        # a misspelled seam shows up in the tree, never vanishes
        return "other", (tag if label is None
                         else f"{tag},{label}")

    def track(self, tag, buf, label=None):
        """Attribute a live allocation: ``tag`` a SEGMENTS name (an
        unknown tag folds into "other" labeled with it), ``buf`` any
        nest of arrays. Returns a token for ``release()``."""
        return self.track_bytes(tag, nbytes_of(buf), label=label)

    def track_bytes(self, tag, nbytes, label=None):
        """``track`` for sizes known without a buffer in hand (e.g.
        restored artifact blobs)."""
        seg, lab = self._seg_label(tag, label)
        n = max(int(nbytes), 0)
        with self._lock:
            token = self._next_token
            self._next_token += 1
            self._tracked[token] = (seg, lab, n)
            self.tracked_allocs += 1
            if self._c:
                self._c["tracked_allocs"].inc()
            self._refresh_gauges_locked()
        return token

    def release(self, token):
        """Drop a tracked allocation; returns its bytes (0 for an
        unknown/already-released token — release is idempotent)."""
        with self._lock:
            rec = self._tracked.pop(token, None)
            if rec is None:
                return 0
            self.released_allocs += 1
            if self._c:
                self._c["released_allocs"].inc()
            self._refresh_gauges_locked()
            return rec[2]

    def set_level(self, tag, nbytes, label=None):
        """Absolute byte level for a segment recomputed at its seam
        (idempotent; 0 clears). The level channel for inventories the
        owner already keeps (prefix sidecars, optimizer state)."""
        seg, lab = self._seg_label(tag, label)
        n = max(int(nbytes), 0)
        with self._lock:
            if n:
                self._levels[(seg, lab)] = n
            else:
                self._levels.pop((seg, lab), None)
            self._refresh_gauges_locked()

    def add_audit(self, fn):
        """Register a zero-arg callable returning a list of problem
        strings, run by every sweep (the prefix refcount audit's
        attach point). Failures count, never raise."""
        self._audits.append(fn)

    # -- readers -----------------------------------------------------------

    def attributed_bytes(self):
        with self._lock:
            return self._attributed_locked()

    def _attributed_locked(self):
        return (sum(n for _, _, n in self._tracked.values())
                + sum(self._levels.values()))

    def segment_tree(self):
        """{segment: {"bytes": total, "labels": {label: bytes}}} over
        both channels — the /memory endpoint's and flight dumps'
        payload."""
        with self._lock:
            tree = {}
            for seg, lab, n in self._tracked.values():
                node = tree.setdefault(seg, {"bytes": 0, "labels": {}})
                node["bytes"] += n
                node["labels"][lab] = node["labels"].get(lab, 0) + n
            for (seg, lab), n in self._levels.items():
                node = tree.setdefault(seg, {"bytes": 0, "labels": {}})
                node["bytes"] += n
                node["labels"][lab] = node["labels"].get(lab, 0) + n
            return tree

    def segments(self):
        """Flat {segment: bytes} (mem_diff's unit)."""
        return {seg: node["bytes"]
                for seg, node in self.segment_tree().items()}

    def used_bytes(self):
        """Best current estimate of device bytes in use: the last
        ground truth when we have one, floored at the live attributed
        sum (attribution mutates between sweeps; ground truth only at
        sweeps)."""
        with self._lock:
            att = self._attributed_locked()
            gt = self.ground_truth_bytes
            return att if gt is None else max(int(gt), att)

    def headroom_bytes(self):
        cap = self.capacity_bytes
        if cap is None:
            return None
        return max(cap - self.used_bytes(), 0)

    def used_ratio(self):
        cap = self.capacity_bytes
        if not cap:
            return None
        return min(max(self.used_bytes() / float(cap), 0.0), 1.0)

    @property
    def residual_alarm(self):
        """True when the unattributed residual grew past its baseline
        by more than max(floor, ratio * baseline) — the leak drill's
        alarm. Baseline = first sweep after arming (or the last
        ``mark_baseline()``)."""
        with self._lock:
            un, base = self.unattributed_bytes, \
                self._baseline_unattributed
            if un is None or base is None:
                return False
            slack = max(self.residual_alarm_floor,
                        int(self.residual_alarm_ratio * base))
            return (un - base) > slack

    def mark_baseline(self):
        """Pin the CURRENT residual as the alarm baseline (engines
        call this after warmup, once the steady working set exists)."""
        self.sweep(force=True)
        with self._lock:
            self._baseline_unattributed = self.unattributed_bytes

    def conservation(self, tolerance=0.01):
        """The cross-check invariant, checkable: typed segments +
        unattributed must equal ground truth within ``tolerance``
        (relative). Over-attribution — a seam counting bytes the
        device no longer holds — is the only way it breaks, which is
        exactly the bug class it exists to catch."""
        self.sweep(force=True)
        with self._lock:
            att = self._attributed_locked()
            gt = self.ground_truth_bytes
            un = self.unattributed_bytes
            if gt is None or un is None:
                return {"ok": None, "attributed_bytes": att,
                        "unattributed_bytes": un,
                        "ground_truth_bytes": gt, "rel_err": None}
            err = abs((att + un) - gt) / float(max(gt, 1))
            return {"ok": err <= float(tolerance),
                    "attributed_bytes": att, "unattributed_bytes": un,
                    "ground_truth_bytes": gt, "rel_err": round(err, 6)}

    # -- admission hints ---------------------------------------------------

    def would_fit(self, nbytes):
        """Would an allocation of ``nbytes`` fit the forecast
        headroom? True/False, or None when capacity is unknown (the
        hint cannot answer; callers treat None as "proceed")."""
        hr = self.headroom_bytes()
        if hr is None:
            return None
        return int(nbytes) <= hr

    def admission_check(self, nbytes):
        """The engine's pre-page-allocation consult: counts the check
        (and the would-not-fit verdicts) and returns would_fit's
        answer. Counter-only — policy (advisory vs hard) is the
        caller's."""
        fits = self.would_fit(nbytes)
        with self._lock:
            self.admission_checks += 1
            if self._c:
                self._c["admission_checks"].inc()
            if fits is False:
                self.admission_rejections += 1
                if self._c:
                    self._c["admission_rejections"].inc()
        return fits

    # -- sweep (ground truth + forecast) -----------------------------------

    def sweep(self, force=False, now=None):
        """Refresh ground truth, the unattributed residual, the high
        watermark, the EWMA growth forecast and the audit findings.
        Rate-limited (``min_sweep_interval_s``) unless forced; driven
        from health()/close — never the dispatch hot path."""
        t = time.monotonic() if now is None else float(now)
        with self._lock:
            if not force and self._last_sweep_t is not None \
                    and t - self._last_sweep_t \
                    < self.min_sweep_interval_s:
                return False
        problems = []
        for fn in list(self._audits):
            try:
                problems.extend(fn() or [])
            except Exception as e:  # noqa: BLE001 — an audit bug must
                # not take the sweep (or the serving process) down
                problems.append(f"audit raised {type(e).__name__}: "
                                f"{e}")
        try:
            gt, cap = self._ground_truth_fn()
        except Exception:  # noqa: BLE001 — ground truth is optional
            gt, cap = None, None
        with self._lock:
            self.sweeps += 1
            if self._c:
                self._c["sweeps"].inc()
            if problems:
                self.audit_failures += len(problems)
                if self._c:
                    self._c["audit_failures"].inc(len(problems))
            self.audit_problems = problems[:16]
            if cap is not None and self.capacity_bytes is None:
                self.capacity_bytes = int(cap)
            att = self._attributed_locked()
            if gt is not None:
                self.ground_truth_bytes = int(gt)
                self.unattributed_bytes = max(int(gt) - att, 0)
                if self._baseline_unattributed is None:
                    self._baseline_unattributed = \
                        self.unattributed_bytes
            used = att if gt is None else max(int(gt), att)
            self.high_watermark_bytes = max(self.high_watermark_bytes,
                                            used)
            if self._last_sweep_t is not None \
                    and t > self._last_sweep_t \
                    and self._last_sweep_used is not None:
                rate = ((used - self._last_sweep_used)
                        / (t - self._last_sweep_t))
                if not self._growth_seeded:
                    self.growth_bytes_per_s = rate
                    self._growth_seeded = True
                else:
                    a = self.ewma_alpha
                    self.growth_bytes_per_s = \
                        (1 - a) * self.growth_bytes_per_s + a * rate
            self._last_sweep_t = t
            self._last_sweep_used = used
            self._refresh_gauges_locked()
        if problems:
            self._flight_note(problems)
        return True

    def _flight_note(self, problems):
        """Audit findings are postmortem evidence — note them to the
        flight recorder when it is importable; never raise."""
        try:
            from . import flightrec
            flightrec.note("mem_audit_failure", name=self.name,
                           problems=problems[:4])
        except Exception:  # noqa: BLE001 — evidence attach never raises
            pass

    def _refresh_gauges_locked(self):
        if not self._g:
            return
        att = self._attributed_locked()
        self._g["attributed"].set(att)
        if self.unattributed_bytes is not None:
            self._g["unattributed"].set(self.unattributed_bytes)
        cap = self.capacity_bytes
        gt = self.ground_truth_bytes
        used = att if gt is None else max(int(gt), att)
        if cap:
            self._g["used_ratio"].set(
                min(max(used / float(cap), 0.0), 1.0))
            self._g["headroom"].set(max(cap - used, 0))
        self._g["watermark"].set(max(self.high_watermark_bytes, used))
        self._g["growth"].set(round(self.growth_bytes_per_s, 3))

    # -- exports -----------------------------------------------------------

    def seconds_to_exhaustion(self):
        """Headroom / EWMA growth — None when capacity is unknown or
        usage is flat/shrinking (no exhaustion forecast)."""
        hr = self.headroom_bytes()
        if hr is None or self.growth_bytes_per_s <= 0.0:
            return None
        return hr / self.growth_bytes_per_s

    def stats(self):
        """Flat monotonic counters for the router's restart-tolerant
        delta fold (the _fold_spec/_fold_profile idiom)."""
        with self._lock:
            return {"tracked_allocs": int(self.tracked_allocs),
                    "released_allocs": int(self.released_allocs),
                    "admission_checks": int(self.admission_checks),
                    "admission_rejections":
                        int(self.admission_rejections),
                    "audit_failures": int(self.audit_failures)}

    def digest(self, sweep=True):
        """Bounded heartbeat digest (host-side JSON, a few hundred
        bytes) — the shape the fleet router folds into fleet_mem_*
        counters and the MEM%/HEADROOM rollup."""
        if sweep:
            self.sweep()
        with self._lock:
            att = self._attributed_locked()
            gt = self.ground_truth_bytes
            used = att if gt is None else max(int(gt), att)
            cap = self.capacity_bytes
            return {"attributed_bytes": att,
                    "unattributed_bytes": self.unattributed_bytes,
                    "used_bytes": used,
                    "capacity_bytes": cap,
                    "used_ratio": (None if not cap else round(
                        min(max(used / float(cap), 0.0), 1.0), 6)),
                    "headroom_bytes": (None if cap is None
                                       else max(cap - used, 0)),
                    "high_watermark_bytes":
                        max(self.high_watermark_bytes, used),
                    "growth_bytes_per_s":
                        round(self.growth_bytes_per_s, 3),
                    "residual_alarm": self.residual_alarm,
                    "audit_problems": list(self.audit_problems),
                    "segments": {seg: node["bytes"] for seg, node
                                 in self.segment_tree().items()},
                    "stats": self.stats()}

    def report(self, window_s=None, sweep=True):
        """The ``/memory`` endpoint body: the digest plus the full
        labeled segment tree and forecast. ``window_s`` is accepted
        for route symmetry with /profile and ignored (a ledger is a
        level, not a ring)."""
        d = self.digest(sweep=sweep)
        d.update(name=self.name, armed=True, window_s=window_s,
                 tree=self.segment_tree(),
                 live_tokens=len(self._tracked),
                 seconds_to_exhaustion=self.seconds_to_exhaustion(),
                 conservation=self.conservation())
        return d

    def save(self, path, extra=None):
        """Persist a snapshot (mem_diff's input) via write-then-rename
        — valid JSON or absent, never torn (load_snapshot of a torn
        copy reads as empty)."""
        doc = {"memledger": 1, "name": self.name,
               "digest": self.digest(),
               "tree": self.segment_tree()}
        if extra:
            doc.update(extra)
        try:
            body = json.dumps(doc, sort_keys=True, allow_nan=False)
        except ValueError:
            body = json.dumps(_finite(doc), sort_keys=True,
                              allow_nan=False)
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        _atomic().atomic_replace(path, body.encode("utf-8"))
        return path

    def close(self):
        """Final sweep + detach from the active registry. Idempotent;
        tracked tokens are left as-is (the process owns the buffers,
        not the ledger)."""
        if self._closed:
            return
        self._closed = True
        try:
            self.sweep(force=True)
        except Exception:  # noqa: BLE001 — close never raises
            pass
        with _active_lock:
            if self in _active:
                _active.remove(self)


# -- module-level active-ledger registry ------------------------------------
#
# The flight recorder, the anomaly sentinel and the optimizer seam
# attach "where is device memory" evidence without holding a ledger
# reference — they ask for the most recently armed one.

_active = []
_active_lock = threading.Lock()


def active_ledger():
    """The most recently armed, still-open ledger (or None)."""
    with _active_lock:
        for led in reversed(_active):
            if not led._closed:
                return led
    return None


def current_memory():
    """``report()`` of the active ledger, or None — the guarded
    attach point for flight dumps."""
    led = active_ledger()
    if led is None:
        return None
    try:
        return led.report()
    except Exception:  # noqa: BLE001 — evidence attach never raises
        return None


def load_snapshot(path):
    """Snapshot file -> {"segments": {...}, "attributed", ...} for
    mem_diff. Torn/absent/unparseable files read as an empty snapshot,
    never an exception (the load_folded discipline)."""
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return {}
    if not isinstance(doc, dict) or doc.get("memledger") != 1:
        return {}
    return doc
