"""Per-tenant usage accounting with bounded cardinality.

"Millions of users" (ROADMAP north star) means per-tenant attribution
cannot be a dict that grows one entry per user: the fleet needs the
HEAVY HITTERS — who is consuming the tokens, the KV pages, the queue —
inside a fixed memory budget, with the error bound stated instead of
hidden. This module is that layer:

- ``SpaceSavingSketch`` — the Metwally et al. space-saving top-K
  algorithm. At most ``capacity`` tracked tenants; an increment for an
  untracked tenant past capacity EVICTS the minimum-weight entry and
  INHERITS its weight (recorded per entry as ``err``, the classic
  overestimate bound: ``true_weight >= weight - err`` and every tenant
  whose true weight exceeds ``min_weight`` is guaranteed tracked).
  Crucially the evict-and-inherit move conserves every accumulator, so
  **the sketch's per-field sums equal the exact fleet totals at all
  times** — the invariant the chaos wave asserts (per-tenant token
  totals sum exactly to fleet totals) holds by construction, not
  sampling luck.
- ``TenantAccountant`` — the fleet-facing wrapper: thread-safe
  ``account()`` of tokens in/out, queue-wait seconds, KV-page-seconds
  and request counts per tenant; a ``report()`` the ``/tenants``
  endpoint serves (top-K rows, per-entry error bounds, exact totals,
  eviction count); and ``usage()``, the weight read the router's
  priority shedding folds in (heaviest tenants shed first within a
  priority band).

The ``tenant=`` label itself rides ``FleetRouter.submit`` →
``ReplicaClient`` → the transport verbs (Inproc + Proc frames) →
``ServingEngine.submit``; the engine accounts what only it can see
(KV-page-seconds, admission queue wait) and stamps them on each
result, the router accounts fleet-level totals at resolve time.

Stdlib-only by contract (standalone-loadable via bench._obs_mod).
"""
from __future__ import annotations

import threading

__all__ = ["SpaceSavingSketch", "TenantAccountant", "USAGE_FIELDS"]

#: the accumulators every entry (and the exact-totals row) carries
USAGE_FIELDS = ("tokens_in", "tokens_out", "queue_wait_s",
                "kv_page_s", "requests", "prefix_hit_pages",
                "prefix_pages", "spec_proposed", "spec_accepted")


class SpaceSavingSketch:
    """Space-saving top-K heavy hitters over a weight + side fields.

    capacity: max tracked keys. ``weight`` drives tracking/eviction
    (callers use tokens in+out); the side fields ride along and are
    conserved through evictions (the inheritor absorbs them), so
    per-field sums over the sketch stay EXACT fleet totals.
    """

    def __init__(self, capacity=128):
        if int(capacity) < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self._entries = {}   # key -> {"weight", "err", fields...}
        self.evictions = 0
        self.totals = {f: 0 for f in USAGE_FIELDS}
        self.total_weight = 0

    def add(self, key, weight, **fields):
        """Fold one observation for ``key``. Unknown field names
        raise — silent typos would quietly unbalance the totals."""
        bad = set(fields) - set(USAGE_FIELDS)
        if bad:
            raise ValueError(f"unknown usage fields {sorted(bad)}")
        weight = max(int(weight), 0)
        self.total_weight += weight
        for f, v in fields.items():
            self.totals[f] += v
        ent = self._entries.get(key)
        if ent is None:
            if len(self._entries) < self.capacity:
                ent = {"weight": 0, "err": 0}
                ent.update({f: 0 for f in USAGE_FIELDS})
                self._entries[key] = ent
            else:
                # evict the minimum-weight entry; the newcomer
                # inherits its weight (as err — the overestimate
                # bound) AND its side accumulators, conserving sums
                victim_key = min(self._entries,
                                 key=lambda k: (
                                     self._entries[k]["weight"], k))
                ent = self._entries.pop(victim_key)
                ent["err"] = ent["weight"]
                self._entries[key] = ent
                self.evictions += 1
        ent["weight"] += weight
        for f, v in fields.items():
            ent[f] += v
        return ent

    def usage(self, key):
        """The tracked weight for ``key`` (an overestimate by at most
        that entry's ``err``), 0 when untracked — i.e. provably light."""
        ent = self._entries.get(key)
        return 0 if ent is None else ent["weight"]

    def top(self, k=None):
        """Entries by descending weight (name-tiebroken), each with
        its error bound."""
        rows = sorted(self._entries.items(),
                      key=lambda kv: (-kv[1]["weight"], kv[0]))
        if k is not None:
            rows = rows[:int(k)]
        return [dict(ent, tenant=key) for key, ent in rows]

    @property
    def error_bound(self):
        """Max overestimate across tracked entries (0 until the first
        eviction — below capacity the sketch is exact)."""
        return max((e["err"] for e in self._entries.values()),
                   default=0)

    def __len__(self):
        return len(self._entries)


class TenantAccountant:
    """Thread-safe per-tenant usage accounting over a space-saving
    sketch, with the registry export and report shape the fleet's
    ``/tenants`` endpoint serves.

    capacity: sketch bound (tenants tracked at once).
    registry: MetricsRegistry for ``tenants_tracked`` /
        ``tenant_sketch_evictions_total`` (None = unmetered).
    """

    def __init__(self, capacity=128, registry=None):
        self.sketch = SpaceSavingSketch(capacity=capacity)
        self._lock = threading.Lock()
        self._g_tracked = None
        self._m_evict = None
        if registry is not None:
            self._g_tracked = registry.gauge(
                "tenants_tracked",
                help="tenants currently tracked by the space-saving "
                     "sketch (bounded by its capacity)")
            self._m_evict = registry.counter(
                "tenant_sketch_evictions_total",
                help="sketch evictions (min-weight tenant displaced "
                     "by a newcomer; its usage is inherited, totals "
                     "stay exact)")

    def account(self, tenant, *, tokens_in=0, tokens_out=0,
                queue_wait_s=0.0, kv_page_s=0.0, requests=0,
                prefix_hit_pages=0, prefix_pages=0,
                spec_proposed=0, spec_accepted=0):
        """Fold one request's usage for ``tenant`` (None is skipped —
        untagged traffic costs nothing here; the ROUTER maps untagged
        to 'anon' so fleet sums stay exact regardless)."""
        if tenant is None:
            return
        with self._lock:
            ev0 = self.sketch.evictions
            self.sketch.add(str(tenant), int(tokens_in) + int(tokens_out),
                            tokens_in=int(tokens_in),
                            tokens_out=int(tokens_out),
                            queue_wait_s=float(queue_wait_s),
                            kv_page_s=float(kv_page_s),
                            requests=int(requests),
                            prefix_hit_pages=int(prefix_hit_pages),
                            prefix_pages=int(prefix_pages),
                            spec_proposed=int(spec_proposed),
                            spec_accepted=int(spec_accepted))
            if self._m_evict is not None \
                    and self.sketch.evictions > ev0:
                self._m_evict.inc(self.sketch.evictions - ev0)
            if self._g_tracked is not None:
                self._g_tracked.set(len(self.sketch))

    def usage(self, tenant):
        with self._lock:
            return 0 if tenant is None \
                else self.sketch.usage(str(tenant))

    def heaviest(self, k):
        """The k heaviest tenant names by sketch weight (descending,
        name-tiebroken) — the brownout ladder's clamp set: level L
        clamps exactly ``heaviest(L)``."""
        if int(k) < 1:
            return []
        with self._lock:
            return [r["tenant"] for r in self.sketch.top(int(k))]

    @property
    def tracked(self):
        with self._lock:
            return len(self.sketch)

    def report(self, k=None):
        """The ``/tenants`` payload: top-K rows (weight + err bound +
        the per-field accumulators), EXACT totals, sketch meta. The
        sum of any field over ``tenants`` equals ``totals[field]`` —
        by construction, asserted by the chaos wave."""
        with self._lock:
            rows = self.sketch.top(k)
            return {
                "capacity": self.sketch.capacity,
                "tracked": len(self.sketch),
                "evictions": self.sketch.evictions,
                "error_bound": self.sketch.error_bound,
                "exact_below_capacity": self.sketch.evictions == 0,
                "total_weight": self.sketch.total_weight,
                "totals": {f: self.sketch.totals[f]
                           for f in USAGE_FIELDS},
                "tenants": [
                    {"tenant": r["tenant"], "weight": r["weight"],
                     "err": r["err"],
                     **{f: round(r[f], 6) if isinstance(r[f], float)
                        else r[f] for f in USAGE_FIELDS}}
                    for r in rows]}
