"""Structured run telemetry — JSONL records + the hapi callback.

``TelemetryLogger`` writes one JSON object per line (one record per
train step / serve request / workload event) into a run directory,
with size-based rotation so a week-long run can't fill a disk, and a
``summarize()`` rollup (counts + numeric-field min/mean/max/last per
record kind) that powers the exportable run report.

``TelemetryCallback`` is the hapi side: drop it into ``Model.fit
(callbacks=[...])`` and every train step emits a record carrying
step_time, loss, grad-norm, samples/s and the TrainGuard/GradScaler
skip/rollback/found-inf counters, while the same values land in the
metrics registry (histograms/counters/gauges) for the metrics.json
export. On train end it writes ``metrics.json`` (registry snapshot +
recompile report) next to ``telemetry.jsonl``.

The callback is duck-typed against hapi's Callback protocol (it
implements the hook surface directly) so this module never imports
hapi — hapi.callbacks re-exports it without an import cycle.
"""
from __future__ import annotations

import json
import math
import numbers
import os
import time

__all__ = ["TelemetryLogger", "TelemetryCallback"]


def _json_default(o):
    try:
        return float(o)
    except (TypeError, ValueError):
        return repr(o)


def _finite(obj):
    """Map non-finite floats to None: json.dumps' default NaN/Infinity
    tokens are not RFC JSON and break jq/JS consumers — exactly on the
    NaN-storm runs this subsystem exists to record."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: _finite(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_finite(v) for v in obj]
    return obj


class TelemetryLogger:
    """Append-only JSONL with rotation.

    run_dir/filename is the live file; on crossing rotate_bytes it is
    rotated to filename.1 (older files shift up; at most max_rotated
    rotated files are kept, oldest dropped)."""

    def __init__(self, run_dir, filename="telemetry.jsonl",
                 rotate_bytes=16 * 1024 * 1024, max_rotated=3):
        os.makedirs(run_dir, exist_ok=True)
        self.run_dir = run_dir
        self.path = os.path.join(run_dir, filename)
        self.rotate_bytes = int(rotate_bytes)
        self.max_rotated = int(max_rotated)
        self.rotations = 0
        self._f = open(self.path, "a")
        self._bytes = os.path.getsize(self.path)
        self.records = 0

    # -- writing -----------------------------------------------------------
    def emit(self, kind, **fields):
        """Write one record: {"ts", "kind", **fields}. Returns the
        record dict."""
        rec = {"ts": round(time.time(), 6), "kind": kind}
        rec.update(fields)
        try:
            line = json.dumps(rec, default=_json_default,
                              allow_nan=False) + "\n"
        except ValueError:
            # a NaN loss (the storm the guard records) must still land
            # as valid JSON: normalize via a tolerant round-trip, then
            # null out the non-finite leaves
            # the inner dumps MUST keep allow_nan: it is the tolerant
            # normalization round-trip whose output _finite() then
            # nulls — the emitted line below carries allow_nan=False
            # tpulint: disable-next-line=OBS01
            raw = json.dumps(rec, default=_json_default)
            rec = _finite(json.loads(raw))
            line = json.dumps(rec, allow_nan=False) + "\n"
        self._f.write(line)
        self._bytes += len(line)
        self.records += 1
        if self._bytes >= self.rotate_bytes:
            self._rotate()
        return rec

    def _rotate(self):
        self._f.close()
        oldest = f"{self.path}.{self.max_rotated}"
        if os.path.exists(oldest):
            os.remove(oldest)
        for i in range(self.max_rotated - 1, 0, -1):
            src = f"{self.path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i + 1}")
        os.replace(self.path, f"{self.path}.1")
        self._f = open(self.path, "a")
        self._bytes = 0
        self.rotations += 1

    def flush(self):
        self._f.flush()

    def close(self):
        if not self._f.closed:
            self._f.close()

    # -- reading -----------------------------------------------------------
    def files(self):
        """All telemetry files, oldest first (rotated then live)."""
        out = []
        for i in range(self.max_rotated, 0, -1):
            p = f"{self.path}.{i}"
            if os.path.exists(p):
                out.append(p)
        if os.path.exists(self.path):
            out.append(self.path)
        return out

    def iter_records(self):
        for p in self.files():
            with open(p) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        yield json.loads(line)
                    except json.JSONDecodeError:
                        continue  # a torn last line must not kill rollup

    def summarize(self):
        """Rollup over every retained record: per-kind counts and
        numeric-field stats (min/mean/max/last)."""
        self.flush()
        by_kind = {}
        total = 0
        for rec in self.iter_records():
            total += 1
            kind = rec.get("kind", "?")
            slot = by_kind.setdefault(kind, {"count": 0, "fields": {}})
            slot["count"] += 1
            for k, v in rec.items():
                if k in ("kind", "ts") or not isinstance(
                        v, numbers.Number) or isinstance(v, bool):
                    continue
                st = slot["fields"].setdefault(
                    k, {"min": v, "max": v, "sum": 0.0, "n": 0,
                        "last": v})
                st["min"] = min(st["min"], v)
                st["max"] = max(st["max"], v)
                st["sum"] += v
                st["n"] += 1
                st["last"] = v
        for slot in by_kind.values():
            for st in slot["fields"].values():
                st["mean"] = st.pop("sum") / st.pop("n")
        return {"records": total, "rotations": self.rotations,
                "by_kind": by_kind}


class TelemetryCallback:
    """hapi train-loop instrumentation (pass via fit(callbacks=[...])).

    Per batch: step_time, loss, grad-norm (from the compiled step's
    fused reduction — Engine.last_grad_norm), samples/s, plus guard
    skip/rollback and scaler found-inf counters (diffed into monotonic
    registry counters). Per run: a train_begin/train_end pair, the
    summarize() rollup, and a metrics.json export.

    Beyond the counters, each step also publishes MFU two ways
    (docs/observability.md "analytic vs measured"): `train_mfu_measured`
    divides the compiled executable's XLA cost_analysis FLOPs
    (introspect.site_cost of the engine's train-step site) by step wall
    and the resolved chip peak; `train_mfu_analytic` does the same with
    the hand-derived `flops_per_step=` the caller supplies (omitted ->
    measured only). Either gauge is absent — never fabricated — when
    its FLOPs leg or the peak is unresolvable (CPU without
    PADDLE_TPU_PEAK_FLOPS). A per-step span lands on the callback's
    SpanRecorder (`.spans`, lane "train", guard outcomes as instants)
    and is exported to `spans.json` at train end — merge it with
    engine/serving/profiler recorders via spans.export_chrome for one
    Perfetto timeline. Every step is also note()d into the crash
    flight recorder.

    jsonl_every: emit a JSONL record every N batches (registry metrics
    update every batch regardless).
    """

    METRIC_NAMES = ("train_step_seconds", "train_steps_total",
                    "train_loss", "train_samples_per_s",
                    "train_grad_norm", "train_skipped_steps_total",
                    "train_rollbacks_total", "train_found_inf_total",
                    "train_mfu_measured", "train_mfu_analytic",
                    "train_peak_flops")

    def __init__(self, run_dir=None, logger=None, registry=None,
                 jsonl_every=1, write_metrics=True, flops_per_step=None,
                 write_spans=True):
        if run_dir is None and logger is None:
            raise ValueError("TelemetryCallback needs run_dir= or "
                             "logger=")
        self.run_dir = run_dir if run_dir is not None else logger.run_dir
        self.logger = logger
        self._owns_logger = logger is None
        self.jsonl_every = max(1, int(jsonl_every))
        self.write_metrics = write_metrics
        self.write_spans = write_spans
        self.flops_per_step = flops_per_step
        self._registry = registry
        self.model = None
        self.params = {}
        self._t0 = None
        self._seen = {}
        self.last_summary = None
        self.metrics_path = None
        self.spans_path = None
        # sibling modules are optional under standalone file-loading
        # (bench._obs_mod loads telemetry.py without the package)
        try:
            from . import introspect as _intro
            from .flightrec import note as _fnote
            from .spans import SpanRecorder
            self._intro = _intro
            self._fnote = _fnote
            self.spans = SpanRecorder(name="train")
        except ImportError:
            self._intro = None
            self._fnote = None
            self.spans = None
        self._peak = None
        self._peak_src = None

    # -- Callback protocol (duck-typed; hapi never imported here) ----------
    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    def _reg(self):
        if self._registry is None:
            from .metrics import get_registry
            self._registry = get_registry()
        return self._registry

    def on_train_begin(self, logs=None):
        if self.logger is None or self.logger._f.closed:
            self.logger = TelemetryLogger(self.run_dir)
            self._owns_logger = True
        # grad-norm collection is opt-in on the Engine (the in-step
        # reduction is free to fuse but not free to run); enable it
        # here, before the step first compiles
        eng = getattr(self.model, "_engine", None)
        if eng is not None and hasattr(eng, "enable_grad_norm"):
            eng.enable_grad_norm()
        # guard/scaler totals are lifetime-absolute on the guard object:
        # baseline them here so a second fit() on the same model diffs
        # only ITS OWN skips into the (often process-global) registry
        # instead of re-counting fit 1's history
        self._seen = {}
        guard = getattr(eng, "guard", None) if eng is not None else None
        if guard is not None:
            self._seen["skipped"] = int(guard.skipped_steps)
            self._seen["rollbacks"] = int(guard.rollbacks)
            if guard.scaler is not None:
                self._seen["found_inf"] = int(
                    guard.scaler.found_inf_count)
        self._t0 = None
        # one peak-FLOPs resolution per run (env override > device-kind
        # table > None); publishing the denominator makes every MFU
        # gauge auditable from the export alone
        if self._intro is not None:
            self._peak, self._peak_src = self._intro.resolve_peak_flops()
            if self._peak:
                self._reg().gauge(
                    "train_peak_flops",
                    help="peak FLOPs MFU is computed against "
                         f"({self._peak_src})").set(self._peak)
        self.logger.emit("train_begin",
                         epochs=self.params.get("epochs"),
                         steps=self.params.get("steps"))

    def on_train_batch_begin(self, step, logs=None):
        self._t0 = time.perf_counter()

    def _measured_flops(self):
        """XLA cost_analysis FLOPs of the engine's compiled train-step
        site (whichever variant this run built); None before the first
        compile or where the backend reports no flops key."""
        if self._intro is None:
            return None
        for site in ("train_step_guarded", "train_step"):
            e = self._intro.site_cost(site, tracer="engine")
            if e and e.get("flops"):
                return e["flops"]
        return None

    @staticmethod
    def _scalar(v):
        if isinstance(v, (list, tuple)):
            v = v[0] if v else None
        return float(v) if isinstance(v, numbers.Number) else None

    def _diff_counter(self, reg, name, key, absolute):
        """Fold an absolute (monotonic) source total into a registry
        counter by increments. The series registers on first call even
        at zero — a clean run exports skip/rollback counters of 0, not
        an absent metric."""
        if absolute is None:
            return None
        absolute = int(absolute)
        c = reg.counter(name)
        prev = self._seen.get(key, 0)
        if absolute > prev:
            c.inc(absolute - prev)
        self._seen[key] = absolute
        return absolute

    def on_train_batch_end(self, step, logs=None):
        logs = logs or {}
        now = time.perf_counter()
        dt = (now - self._t0) if self._t0 is not None else None
        self._t0 = None
        reg = self._reg()
        eng = getattr(self.model, "_engine", None)

        loss = self._scalar(logs.get("loss"))
        bs = self._scalar(logs.get("batch_size"))
        samples_per_s = (bs / dt) if (bs and dt) else None
        grad_norm = None
        gn = getattr(eng, "last_grad_norm", None)
        if gn is not None:
            try:
                import numpy as np
                grad_norm = float(np.asarray(gn))
            except Exception:  # noqa: BLE001 — telemetry must not kill fit
                grad_norm = None

        if dt is not None:
            reg.histogram(
                "train_step_seconds",
                help="hapi train step wall time").observe(dt)
        reg.counter("train_steps_total",
                    help="train batches seen by fit()").inc()
        if loss is not None:
            reg.gauge("train_loss", help="last train loss").set(loss)
        if samples_per_s is not None:
            reg.gauge("train_samples_per_s",
                      help="last step's samples/s").set(samples_per_s)
        if grad_norm is not None:
            reg.gauge("train_grad_norm",
                      help="last step's global grad L2 norm").set(
                          grad_norm)

        # guard/scaler counters: fit() puts the absolute totals into
        # the batch logs when a guard is attached; fall back to the
        # guard object for direct Engine use
        guard = getattr(eng, "guard", None)
        skipped = self._scalar(logs.get("skipped"))
        rollbacks = self._scalar(logs.get("rollbacks"))
        found_inf = self._scalar(logs.get("found_inf"))
        if guard is not None:
            if skipped is None:
                skipped = guard.skipped_steps
            if rollbacks is None:
                rollbacks = guard.rollbacks
            if found_inf is None and guard.scaler is not None:
                found_inf = guard.scaler.found_inf_count
        skipped = self._diff_counter(
            reg, "train_skipped_steps_total", "skipped", skipped)
        rollbacks = self._diff_counter(
            reg, "train_rollbacks_total", "rollbacks", rollbacks)
        found_inf = self._diff_counter(
            reg, "train_found_inf_total", "found_inf", found_inf)

        # MFU both ways (docs/observability.md): measured rides the
        # compiled executable's cost_analysis, analytic the caller's
        # convention — published side by side so drift is queryable
        mfu_measured = mfu_analytic = None
        if self._peak and dt:
            cf = self._measured_flops()
            if cf:
                mfu_measured = cf / dt / self._peak
                reg.gauge("train_mfu_measured",
                          help="compiled-FLOPs MFU (XLA cost_analysis "
                               "/ step wall / chip peak)").set(
                              mfu_measured)
            if self.flops_per_step:
                mfu_analytic = self.flops_per_step / dt / self._peak
                reg.gauge("train_mfu_analytic",
                          help="analytic-FLOPs MFU (caller convention "
                               "/ step wall / chip peak)").set(
                              mfu_analytic)

        outcome = guard.last_outcome if guard is not None else None
        step_n = getattr(eng, "_step", None)
        if self.spans is not None and dt is not None:
            self.spans.add("train_step", now - dt, now, tid="train",
                           cat="train",
                           args={"step": step_n, "loss": loss})
            if outcome in ("skipped", "rolled_back"):
                self.spans.instant(f"guard_{outcome}", tid="train",
                                   cat="train", args={"step": step_n})
        if self._fnote is not None:
            self._fnote("train_step", step=step_n, loss=loss,
                        step_time_s=None if dt is None else round(dt, 6),
                        outcome=outcome)

        n = int(reg.counter("train_steps_total").value)
        if n % self.jsonl_every == 0:
            rec = {"step": getattr(eng, "_step", n), "loss": loss,
                   "step_time_s": None if dt is None else round(dt, 6),
                   "samples_per_s": None if samples_per_s is None
                   else round(samples_per_s, 3),
                   "grad_norm": grad_norm, "batch_size": bs,
                   "mfu_measured": None if mfu_measured is None
                   else round(mfu_measured, 5),
                   "mfu_analytic": None if mfu_analytic is None
                   else round(mfu_analytic, 5)}
            if guard is not None:
                rec.update(skipped=skipped, rollbacks=rollbacks,
                           outcome=guard.last_outcome)
            if found_inf is not None:
                rec["found_inf"] = found_inf
            self.logger.emit("train_step",
                             **{k: v for k, v in rec.items()
                                if v is not None or k == "loss"})

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        self.logger.emit("epoch_end", epoch=epoch)

    def on_train_end(self, logs=None):
        guard = getattr(getattr(self.model, "_engine", None), "guard",
                        None)
        end = {}
        if guard is not None:
            end.update(guard.stats())
        self.last_summary = self.logger.summarize()
        self.logger.emit("train_end",
                         records=self.last_summary["records"], **end)
        self.logger.flush()
        if self.write_metrics:
            from .trace import report_all
            self.metrics_path = self._reg().dump(
                os.path.join(self.run_dir, "metrics.json"),
                extra={"recompile_report": report_all()})
        if self.write_spans and self.spans is not None \
                and self.spans.events():
            # the run's host-scheduling timeline, Perfetto-openable on
            # its own; merge more lanes (engine serving spans, profiler
            # regions) via spans.export_chrome([...]) instead
            self.spans_path = self.spans.export(
                os.path.join(self.run_dir, "spans.json"))
        if self._owns_logger:
            self.logger.close()

    # remaining hook surface (CallbackList calls these unconditionally)
    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass

    def on_predict_begin(self, logs=None):
        pass

    def on_predict_end(self, logs=None):
        pass
