"""Compiled-executable introspection — what XLA actually built.

Every FLOP/MFU number the bench reported before this module was
*analytic*: a hand-derived 6N+12Lhs convention multiplied by a
hardcoded peak. The compiler knows better — each compiled executable
carries its own ``cost_analysis()`` (real FLOPs, bytes accessed) and
``memory_analysis()`` (argument/output/temp bytes). This module
captures both per RecompileTracer jit site, so "measured MFU"
(compiled FLOPs / step wall / chip peak) becomes a queryable run fact
that can DRIFT from the analytic one — and that drift is the story
(a fused kernel XLA didn't build, a recompute policy doubling the
backward, an attention variant the convention ignores).

Capture rides the tracer: a site is introspected at most once per
trace (i.e. per compile), via an AOT ``jitted.lower(*args).compile()``
replay with ALL trace accounting suppressed (the replay must never
read as a recompile — ``trace.py`` checks ``introspecting()`` at its
counter bump). The replay costs one extra trace + compile of the same
program; sites whose observed compile exceeded
``PADDLE_TPU_INTROSPECT_MAX_S`` (default 120s — the 1.3B-on-tunnel
case) are skipped with a recorded reason, and
``PADDLE_TPU_INTROSPECT=0`` switches the whole layer off.

API-shape guards: jax 0.4.x returns ``cost_analysis()`` as a
one-element list of dicts, 0.6.x returns the dict directly, CPU-only
builds may return None or omit the ``flops`` key — all normalize to
a plain dict (or None) here. ``memory_analysis()`` is a
``CompiledMemoryStats`` when available, None otherwise.

Stdlib-only at import (bench's lean workers file-load this module);
jax is imported inside functions. When loaded standalone the relative
registry import is unavailable — pass ``registry=`` explicitly there.
"""
from __future__ import annotations

import os
import threading
import time

__all__ = ["resolve_peak_flops", "normalize_cost", "normalize_memory",
           "capture_site", "site_cost", "cost_report", "measured_mfu",
           "enabled", "clear", "PEAK_FLOPS_BY_DEVICE_KIND"]

# bf16 matmul peak per chip, matched by lowercase substring of
# jax's device_kind string (e.g. "TPU v5 lite", "TPU v4"). MFU is
# reported against the bf16 peak regardless of the dtype actually
# used, so an fp32 run shows honestly low MFU rather than flattering
# itself (the long-standing bench.py convention).
PEAK_FLOPS_BY_DEVICE_KIND = (
    ("v5 lite", 197e12), ("v5litepod", 197e12), ("v5e", 197e12),
    ("v5p", 459e12), ("v5", 459e12),
    ("v6 lite", 918e12), ("v6e", 918e12), ("trillium", 918e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)

_lock = threading.Lock()
_sites = {}            # (tracer_name, site) -> capture dict
_skipped = {}          # (tracer_name, site) -> reason str
_introspecting = threading.local()
# thread ids currently inside a replay, readable from OTHER threads:
# the continuous profiler (contprof.py) skips them so an AOT replay
# never pollutes a serving profile. set.add/discard are GIL-atomic.
_introspecting_threads = set()


def enabled():
    return os.environ.get("PADDLE_TPU_INTROSPECT", "1").lower() \
        not in ("0", "false", "off")


def introspecting():
    """True while this thread is inside an AOT introspection replay —
    trace.py suppresses ALL trace accounting under it, so the replay
    can never read as a (unexpected) recompile."""
    return getattr(_introspecting, "on", False)


def _max_compile_budget():
    try:
        return float(os.environ.get("PADDLE_TPU_INTROSPECT_MAX_S", 120))
    except ValueError:
        return 120.0


# -- peak-FLOPs resolution -------------------------------------------------

def resolve_peak_flops(device_kind=None):
    """(peak_flops, source) for MFU denominators.

    Resolution order: env ``PADDLE_TPU_PEAK_FLOPS`` (any backend —
    how CPU smoke runs exercise the MFU plumbing), then the
    per-device-kind table (TPU only). (None, reason) when neither
    applies — callers report MFU as null, never against a made-up
    peak."""
    env = os.environ.get("PADDLE_TPU_PEAK_FLOPS")
    if env:
        try:
            return float(env), "env:PADDLE_TPU_PEAK_FLOPS"
        except ValueError:
            pass  # fall through to the table
    if device_kind is None:
        try:
            import jax
            dev = jax.devices()[0]
            if dev.platform != "tpu":
                return None, f"no-table:{dev.platform}"
            device_kind = dev.device_kind
        except Exception:  # noqa: BLE001 — resolution must never raise
            return None, "no-device"
    kind_l = str(device_kind).lower()
    for frag, peak in PEAK_FLOPS_BY_DEVICE_KIND:
        if frag in kind_l:
            return peak, f"table:{frag}"
    return None, f"unknown-device-kind:{device_kind}"


def measured_mfu(flops, step_seconds, peak=None):
    """compiled FLOPs / step wall / peak, or None when any leg is
    missing (the honest null the bench stanzas record)."""
    if not flops or not step_seconds:
        return None
    if peak is None:
        peak, _ = resolve_peak_flops()
    if not peak:
        return None
    return flops / step_seconds / peak


# -- analysis normalization ------------------------------------------------

def normalize_cost(ca):
    """jax 0.4.x (list-of-dict) vs 0.6.x (dict) cost_analysis shapes
    -> {"flops", "bytes_accessed", "transcendentals"} (values may be
    None where the backend reports no such key)."""
    if ca is None:
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return None

    def num(key):
        v = ca.get(key)
        try:
            return float(v) if v is not None else None
        except (TypeError, ValueError):
            return None
    return {"flops": num("flops"),
            "bytes_accessed": num("bytes accessed"),
            "transcendentals": num("transcendentals")}


def normalize_memory(ms):
    """CompiledMemoryStats -> plain dict. peak_bytes is the
    argument+output+temp upper bound (XLA reports no single live-peak
    number through this API; temp is the scratch high-water mark)."""
    if ms is None:
        return None
    out = {}
    for field, name in (("argument_size_in_bytes", "argument_bytes"),
                        ("output_size_in_bytes", "output_bytes"),
                        ("temp_size_in_bytes", "temp_bytes"),
                        ("alias_size_in_bytes", "alias_bytes"),
                        ("generated_code_size_in_bytes", "code_bytes")):
        v = getattr(ms, field, None)
        if v is not None:
            out[name] = int(v)
    if not out:
        return None
    out["peak_bytes"] = (out.get("argument_bytes", 0)
                         + out.get("output_bytes", 0)
                         + out.get("temp_bytes", 0))
    return out


# -- capture ---------------------------------------------------------------

def capture_site(tracer_name, site, jitted, args, kwargs, wall_s=0.0,
                 registry=None):
    """AOT-replay `jitted` on the call's args and record its compiled
    cost/memory analysis under (tracer_name, site). Called by the
    RecompileTracer exactly when a site traced; never raises — a
    failed capture records its reason and returns None.

    The replay happens under the `introspecting()` flag so the
    re-trace (and any nested tracer sites it re-executes) bumps no
    counters and flags no unexpected retraces."""
    key = (tracer_name, site)
    if not enabled():
        return None
    if wall_s > _max_compile_budget():
        with _lock:
            _skipped[key] = (f"compile took {wall_s:.1f}s > "
                             f"PADDLE_TPU_INTROSPECT_MAX_S budget")
        return None
    _introspecting.on = True
    _introspecting_threads.add(threading.get_ident())
    try:
        compiled = jitted.lower(*args, **kwargs).compile()
        cost = normalize_cost(compiled.cost_analysis())
        mem = normalize_memory(compiled.memory_analysis())
    except Exception as e:  # noqa: BLE001 — introspection never kills a step
        with _lock:
            _skipped[key] = f"{type(e).__name__}: {e}"
        return None
    finally:
        _introspecting.on = False
        _introspecting_threads.discard(threading.get_ident())
    entry = {"tracer": tracer_name, "site": site,
             "ts": round(time.time(), 6),
             "flops": (cost or {}).get("flops"),
             "bytes_accessed": (cost or {}).get("bytes_accessed"),
             "transcendentals": (cost or {}).get("transcendentals"),
             "memory": mem, "captures": 1}
    with _lock:
        prev = _sites.get(key)
        if prev is not None:
            entry["captures"] = prev["captures"] + 1
        _sites[key] = entry
        _skipped.pop(key, None)
    _publish(entry, registry)
    return entry


def _publish(entry, registry):
    if registry is None:
        try:
            from .metrics import get_registry
            registry = get_registry()
        except ImportError:
            return  # standalone-loaded module with no registry handed in
    labels = {"tracer": entry["tracer"], "site": entry["site"]}
    if entry.get("flops") is not None:
        registry.gauge("xla_cost_flops",
                       help="compiled-executable FLOPs (XLA "
                            "cost_analysis) per jit site",
                       labels=labels).set(entry["flops"])
    if entry.get("bytes_accessed") is not None:
        registry.gauge("xla_cost_bytes_accessed",
                       help="compiled-executable HBM bytes accessed "
                            "per jit site",
                       labels=labels).set(entry["bytes_accessed"])
    mem = entry.get("memory") or {}
    for field in ("argument_bytes", "output_bytes", "temp_bytes",
                  "peak_bytes"):
        if field in mem:
            registry.gauge(f"xla_memory_{field}",
                           help="compiled-executable memory "
                                f"({field.replace('_', ' ')}) per site",
                           labels=labels).set(mem[field])


# -- queries ---------------------------------------------------------------

def site_cost(site, tracer=None):
    """Latest capture for `site` (optionally pinned to a tracer name);
    None when never captured. Latest-wins across same-named tracers
    (two Engines both report as 'engine')."""
    with _lock:
        if tracer is not None:
            e = _sites.get((tracer, site))
            return dict(e) if e else None
        best = None
        for (_t, s), e in _sites.items():
            if s == site and (best is None or e["ts"] >= best["ts"]):
                best = e
        return dict(best) if best else None


def cost_report():
    """The `cost_report` section of the exported run report: every
    captured site plus the sites introspection skipped (and why) and
    the resolved peak-FLOPs."""
    peak, src = resolve_peak_flops()
    with _lock:
        sites = {f"{t}/{s}": dict(e) for (t, s), e in
                 sorted(_sites.items())}
        skipped = {f"{t}/{s}": r for (t, s), r in
                   sorted(_skipped.items())}
    return {"sites": sites, "skipped": skipped,
            "peak_flops": peak, "peak_flops_source": src,
            "enabled": enabled()}


def clear():
    """Drop every captured site (test hygiene)."""
    with _lock:
        _sites.clear()
        _skipped.clear()
