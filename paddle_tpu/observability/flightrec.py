"""Crash flight recorder — the last N run facts survive the incident.

When a run dies (guard rollback storm, wedged dispatch, SIGTERM
preemption, unhandled exception in fit()/serve), the postmortem
question is always "what were the last few steps doing". This module
keeps a bounded ring of telemetry records (train steps, serve
dispatches, request finishes, guard outcomes — whatever the
instrumented layers ``note()``) and, on a trigger, dumps the ring
plus a registry snapshot and the recompile report to
``flight_<reason>.json`` — always RFC-valid JSON (a storm's NaN loss
nulls out), always atomic, never clobbering an earlier dump (numeric
suffixes).

Dump directory resolution (at dump time, not construction — the env
may be set per campaign stage): explicit ``run_dir`` >
``PADDLE_TPU_FLIGHT_DIR`` > ``BENCH_TELEMETRY_DIR`` >
``<tempdir>/paddle_tpu_flight``. Never the CWD — a chaos suite must
not litter the repo root.

Triggers are wired through the resilience seams: TrainGuard dumps on
rollback, ServingEngine on a watchdog wedge, ``Model.fit`` on
preemption and on an unhandled exception, ``ServingEngine.step`` on
an unhandled exception — so chaos tests can assert a parseable dump
exists for every failure mode they inject. ``note()`` is one deque
append under a lock; ``dump()`` never raises (a broken disk must not
mask the original failure).

Stdlib-only at import; sibling observability modules are imported
lazily inside ``dump`` (and skipped when standalone-loaded).
"""
from __future__ import annotations

import json
import math
import os
import tempfile
import threading
import time

__all__ = ["FlightRecorder", "get_recorder", "note", "dump"]

_atomic_mod = None


def _atomic():
    """The shared crash-safe-write helper (io/atomic.py), resolved
    LAZILY so this module stays stdlib-only at import: the package
    path would pull paddle_tpu.io (numpy/jax) eagerly, and the
    standalone file-load mode (bench lean workers, see bench._obs_mod)
    has no package context at all — there the helper is loaded
    straight from its file, which is fine because atomic.py is itself
    stdlib-only by contract."""
    global _atomic_mod
    if _atomic_mod is None:
        try:
            from ..io import atomic as mod
        except ImportError:
            import importlib.util as ilu
            path = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                os.pardir, "io", "atomic.py")
            spec = ilu.spec_from_file_location(
                "_bench_obs_io_atomic", path)
            mod = ilu.module_from_spec(spec)
            spec.loader.exec_module(mod)
        _atomic_mod = mod
    return _atomic_mod


def _finite(obj):
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: _finite(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_finite(v) for v in obj]
    return obj


def _default_dir():
    return (os.environ.get("PADDLE_TPU_FLIGHT_DIR")
            or os.environ.get("BENCH_TELEMETRY_DIR")
            or os.path.join(tempfile.gettempdir(), "paddle_tpu_flight"))


class FlightRecorder:
    """Bounded ring of {"ts", "kind", ...} records + dump-on-trigger.

    capacity: ring size — oldest records evict first, so the ring is
        always the LAST `capacity` facts in arrival order.
    run_dir: dump directory (None = resolve from env at dump time).
    registry: MetricsRegistry snapshotted into every dump (None =
        the process-global one, resolved lazily).
    """

    def __init__(self, capacity=256, run_dir=None, registry=None):
        import collections
        self.capacity = int(capacity)
        self._ring = collections.deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self.run_dir = run_dir
        self._registry = registry
        self.dumps = []            # paths written, in order
        self._seq = 0              # total records ever noted

    # -- recording ---------------------------------------------------------
    def note(self, kind, **fields):
        """Append one record. O(1), host-side, never raises."""
        rec = {"ts": round(time.time(), 6), "kind": kind}
        rec.update(fields)
        with self._lock:
            rec["seq"] = self._seq
            self._seq += 1
            self._ring.append(rec)
        return rec

    def records(self):
        with self._lock:
            return [dict(r) for r in self._ring]

    def clear(self):
        with self._lock:
            self._ring.clear()
            self.dumps = []
            self._seq = 0

    # -- dumping -----------------------------------------------------------
    def _resolve_dir(self):
        return self.run_dir or _default_dir()

    def _unique_path(self, d, reason):
        return _atomic().unique_path(d, f"flight_{reason}")

    def dump(self, reason, extra=None):
        """Write the flight record for `reason`; returns the path or
        None (a failed write must never mask the original failure —
        the reason a dump is happening at all)."""
        try:
            doc = {"reason": str(reason),
                   "ts": round(time.time(), 6),
                   "records": self.records()}
            if extra:
                doc.update(extra)
            reg = self._registry
            try:
                if reg is None:
                    from .metrics import get_registry
                    reg = get_registry()
                doc["registry"] = reg.snapshot()
            except Exception:  # noqa: BLE001
                doc["registry"] = None
            try:
                from .trace import report_all
                doc["recompile_report"] = report_all()
            except Exception:  # noqa: BLE001
                doc["recompile_report"] = None
            d = self._resolve_dir()
            os.makedirs(d, exist_ok=True)
            path = self._unique_path(d, reason)
            try:
                text = json.dumps(doc, indent=1, allow_nan=False)
            except ValueError:
                text = json.dumps(_finite(doc), indent=1,
                                  allow_nan=False)
            # shared crash-safe write (io/atomic.py): the dump itself
            # must never be a torn artifact for the postmortem to trip on
            _atomic().atomic_replace(path, text)
            self.dumps.append(path)
            return path
        except Exception:  # noqa: BLE001 — see docstring
            return None


_default = None
_default_lock = threading.Lock()


def get_recorder():
    """The process-global recorder every instrumented layer notes
    into (capacity via PADDLE_TPU_FLIGHT_CAP, default 256)."""
    global _default
    with _default_lock:
        if _default is None:
            try:
                cap = int(os.environ.get("PADDLE_TPU_FLIGHT_CAP", 256))
            except ValueError:
                cap = 256
            _default = FlightRecorder(capacity=cap)
        return _default


def note(kind, **fields):
    return get_recorder().note(kind, **fields)


def dump(reason, extra=None):
    return get_recorder().dump(reason, extra=extra)
