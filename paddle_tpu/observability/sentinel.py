"""Online anomaly sentinel — the live counterpart of the offline
metrics_diff canary gate.

The Gemma-on-Cloud-TPU serving decomposition (PAPERS.md) names the
regressions that matter mid-wave: TTFT creep, decode throughput
collapse, queue-wait growth, and the silent killers (journal errors,
a recompile where the counts were frozen). Round 12's SLO burn rates
catch promise violations against FIXED thresholds; this module
catches *change* — it learns each signal's normal band from the
telemetry history plane (``observability.history``) and fires when
the live value leaves it:

- ``_Band``: EWMA mean + EWMA absolute deviation, read as a robust
  z-score (``(x - mean) / (1.4826 * ewma_dev)``, MAD-style scaling,
  with a relative floor so a perfectly flat clean wave does not turn
  microscopic jitter into an alarm). Breaching observations are NOT
  folded into the band — an anomaly must not widen its own band into
  acceptance.
- signal kinds: ``quantile`` (quantile-over-time of a histogram,
  e.g. TTFT p99), ``rate`` (per-second counter increase, e.g. decode
  tok/s — direction ``low`` — or journal errors — any positive rate
  after a zero baseline), and ``delta`` (ANY increase of a
  monotonic scalar read from a callback — the fleet compile report:
  the zero-recompile contract needs no band, one new trace is the
  anomaly).
- firing: ``min_consecutive`` breaching evaluations arm-and-dump ONE
  ``fleet_anomaly`` flight record (flightrec; re-armed only after the
  signal returns in band — a sustained regression is one postmortem,
  not a dump per poll), increment
  ``fleet_anomaly_fired_total{signal=...}`` and hold
  ``fleet_anomaly_active{signal=...}`` at 1. The router folds
  ``alerting`` into ``health()["anomaly"]`` exactly like SLO burn
  alerts, so placement/operators/the supervisor see it live.
- ``replay()``: run the same detector offline over a SAVED history
  snapshot — how the campaign proves the sentinel stays quiet across
  the committed clean golden wave and how ``tools/fleet_top.py
  --snapshot`` triages a post-mortem archive.

Stdlib-only by contract (standalone-loadable via bench._obs_mod);
flightrec/metrics are sibling stdlib modules, imported lazily.
"""
from __future__ import annotations

import threading
import time

__all__ = ["AnomalySentinel", "default_signals"]


def default_signals(window_s=5.0):
    """The fleet registry's watch list (series the FleetRouter
    publishes; a signal whose series has no data yet simply reads
    None and neither learns nor fires)."""
    w = float(window_s)
    return (
        {"name": "ttft_p99", "kind": "quantile",
         "series": "fleet_ttft_seconds", "q": 0.99, "window_s": w,
         "direction": "high"},
        {"name": "decode_tok_s", "kind": "rate",
         "series": "fleet_tokens_out_total", "window_s": w,
         "direction": "low", "demand_gate": "fleet_pending"},
        {"name": "queue_wait_p99", "kind": "quantile",
         "series": "fleet_placement_wait_seconds", "q": 0.99,
         "window_s": w, "direction": "high"},
        {"name": "journal_errors", "kind": "rate",
         "series": "fleet_journal_errors_total", "window_s": w,
         "direction": "high"},
        # device-memory pressure: the memory ledger's used-ratio
        # gauge. Sustained growth out of the learned band (a leak, a
        # runaway working set) trips the debounced flight dump with
        # the segment tree attached; a flat series — even near full —
        # is a steady state, not an anomaly.
        {"name": "mem_used_ratio", "kind": "gauge",
         "series": "engine_mem_hbm_used_ratio", "window_s": w,
         "direction": "high"},
        {"name": "recompiles", "kind": "delta", "series": None},
    )


class _Band:
    """EWMA mean + EWMA |deviation| with robust-z readout."""

    __slots__ = ("alpha", "z", "warmup", "rel_floor", "abs_floor",
                 "mean", "dev", "n")

    # rel_floor < 1/z by a margin: the floor caps |z| at 1/rel_floor
    # for a TOTAL collapse (x=0 → |z| = mean/(rel_floor*mean)), so a
    # floor of 0.25 against the default z=4 would make a full
    # throughput collapse read exactly 4.0 — never strictly above
    def __init__(self, alpha=0.2, z=4.0, warmup=8, rel_floor=0.2,
                 abs_floor=1e-9):
        self.alpha = float(alpha)
        self.z = float(z)
        self.warmup = int(warmup)
        self.rel_floor = float(rel_floor)
        self.abs_floor = float(abs_floor)
        self.mean = None
        self.dev = 0.0
        self.n = 0

    def observe(self, x, direction="both"):
        """Fold x; returns (z_score, breach). During warmup the band
        only learns; a breaching x is NEVER folded in (the band must
        not chase the anomaly)."""
        x = float(x)
        if self.mean is None:
            self.mean, self.n = x, 1
            return 0.0, False
        scale = max(1.4826 * self.dev,
                    self.rel_floor * abs(self.mean), self.abs_floor)
        zs = (x - self.mean) / scale
        breach = self.n >= self.warmup and abs(zs) > self.z and (
            direction == "both"
            or (direction == "high" and zs > 0)
            or (direction == "low" and zs < 0))
        if not breach:
            a = self.alpha
            self.dev = (1 - a) * self.dev + a * abs(x - self.mean)
            self.mean = (1 - a) * self.mean + a * x
            self.n += 1
        return zs, breach


class AnomalySentinel:
    """Online detector over a HistoryStore.

    history: observability.history.HistoryStore the signals read.
    signals: iterable of signal dicts (default: default_signals) —
        {"name", "kind": quantile|rate|delta, "series", "q",
         "window_s", "direction": high|low|both}.
    registry: MetricsRegistry for fleet_anomaly_* (None = unmetered).
    compile_fn: zero-arg callable returning a fleet compile report
        ({"replicas": {...}, "unexpected_retraces": n}) for the
        ``delta`` signal (FleetRouter.compile_report). None disables
        that signal.
    z / alpha / warmup / rel_floor: band knobs (per-signal overrides
        via the signal dict win).
    min_consecutive: breaching evaluations before a FIRE (debounce).
    eval_interval_s: maybe_evaluate cadence (default: the history
        store's scrape interval).
    flight: dump a ``fleet_anomaly`` flight record on fire (one per
        excursion; re-arms when the signal clears).
    """

    def __init__(self, history, *, signals=None, registry=None,
                 compile_fn=None, z=4.0, alpha=0.2, warmup=8,
                 rel_floor=0.2, min_consecutive=2,
                 eval_interval_s=None, flight=True):
        self.history = history
        self.signals = [dict(s) for s in
                        (signals if signals is not None
                         else default_signals())]
        names = [s["name"] for s in self.signals]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate signal names: {names}")
        self.compile_fn = compile_fn
        self.min_consecutive = int(min_consecutive)
        self.eval_interval_s = (float(eval_interval_s)
                                if eval_interval_s is not None
                                else getattr(history, "interval_s",
                                             1.0) or 1.0)
        self.flight = bool(flight)
        self._bands = {}
        for s in self.signals:
            self._bands[s["name"]] = _Band(
                alpha=float(s.get("alpha", alpha)),
                z=float(s.get("z", z)),
                warmup=int(s.get("warmup", warmup)),
                rel_floor=float(s.get("rel_floor", rel_floor)))
        self._streak = {n: 0 for n in names}
        self._armed = {n: True for n in names}
        self._active = {n: False for n in names}
        self._last_compile_total = None
        self._last_eval = 0.0
        self._state = {}
        self._lock = threading.Lock()
        self._m_fired = {}
        self._g_active = {}
        self._registry = registry
        self.fired_total = 0
        # export every signal's series at 0 NOW: the history plane
        # must carry them from the first scrape, or a canary gate
        # comparing two instants could never see the clean->fired
        # transition (a series missing on one side is skipped)
        for n in names:
            self._fired_counter(n)
            self._active_gauge(n)

    # -- metric export -----------------------------------------------------

    def _fired_counter(self, signal):
        if self._registry is None:
            return None
        c = self._m_fired.get(signal)
        if c is None:
            c = self._registry.counter(
                "fleet_anomaly_fired_total",
                help="anomaly-sentinel excursions fired (one per "
                     "excursion, debounced)", labels={"signal": signal})
            self._m_fired[signal] = c
        return c

    def _active_gauge(self, signal):
        if self._registry is None:
            return None
        g = self._g_active.get(signal)
        if g is None:
            g = self._registry.gauge(
                "fleet_anomaly_active",
                help="1 while the signal is outside its learned band",
                labels={"signal": signal})
            self._g_active[signal] = g
        return g

    # -- signal readout ----------------------------------------------------

    def _read(self, sig, now):
        kind = sig.get("kind", "quantile")
        if kind == "quantile":
            return self.history.quantile_over_time(
                sig["series"], float(sig.get("q", 0.99)),
                float(sig.get("window_s", 5.0)), now=now)
        if kind == "rate":
            return self.history.rate(
                sig["series"], float(sig.get("window_s", 5.0)),
                now=now)
        if kind == "gauge":
            # latest raw sample of a plain gauge series inside the
            # window (quantile_over_time is histogram-only); no data
            # reads None — "no news", neither learns nor fires
            w = float(sig.get("window_s", 5.0))
            rows = self.history.query(sig["series"], t0=now - w,
                                      t1=now, res="raw")
            if not rows:
                return None
            last = rows[-1]
            v = last.get("max", last.get("v"))
            return None if v is None else float(v)
        if kind == "delta":
            if self.compile_fn is None:
                return None
            try:
                rep = self.compile_fn()
            except Exception:  # noqa: BLE001 — a scrape hiccup is
                return None    # "no news", not an anomaly
            total = int(rep.get("unexpected_retraces", 0))
            for counts in (rep.get("replicas") or {}).values():
                total += sum(int(v) for v in (counts or {}).values())
            return total
        raise ValueError(f"unknown signal kind {kind!r}")

    def _demand_ok(self, sig, now):
        """True when the signal's ``demand_gate`` series (a gauge,
        e.g. fleet_pending) reads >= ``demand_min`` (default 1)
        anywhere inside the signal's window — i.e. the fleet actually
        had work to do. Signals without a gate always pass."""
        gate = sig.get("demand_gate")
        if gate is None:
            return True
        window = float(sig.get("window_s", 5.0))
        rows = self.history.query(gate, t0=now - window, t1=now,
                                  res="raw")
        if not rows:
            return False   # gate series absent: suppress, don't guess
        need = float(sig.get("demand_min", 1))
        return any((r.get("max", r.get("v", 0)) or 0) >= need
                   for r in rows)

    # -- evaluation --------------------------------------------------------

    def maybe_evaluate(self, now=None):
        """evaluate() iff the cadence elapsed; None otherwise. The
        attach point a control loop (FleetRouter.step) drives."""
        ts = time.time() if now is None else float(now)
        if ts - self._last_eval < self.eval_interval_s:
            return None
        return self.evaluate(now=ts)

    def evaluate(self, now=None):
        """One pass over every signal; returns (and caches) the state
        dict {signal: {"value", "z", "mean", "breach", "alert",
        "kind"}}. ``alert`` holds while the excursion lasts; the FIRST
        evaluation that reaches ``min_consecutive`` breaches dumps the
        flight record and bumps the fired counter."""
        ts = time.time() if now is None else float(now)
        state = {}
        with self._lock:
            self._last_eval = ts
            for sig in self.signals:
                name = sig["name"]
                row = {"kind": sig.get("kind", "quantile"),
                       "series": sig.get("series"), "value": None,
                       "z": None, "mean": None, "breach": False,
                       "alert": False}
                if sig.get("kind") == "delta":
                    total = self._read(sig, ts)
                    row["value"] = total
                    if total is not None:
                        base = self._last_compile_total
                        if base is None:
                            self._last_compile_total = total
                        elif total > base:
                            row["breach"] = True
                            row["z"] = float(total - base)
                            # the new level becomes the baseline once
                            # fired — ONE excursion per compile event
                            self._last_compile_total = total
                        else:
                            self._last_compile_total = total
                else:
                    v = self._read(sig, ts)
                    if v is not None and not self._demand_ok(sig, ts):
                        # zero-demand guard: a throughput collapse is
                        # only an anomaly while there IS work pending
                        # — a client simply going quiet must read as
                        # "no data" (clears/never fires), not as a
                        # replica regression
                        v = None
                    row["value"] = v
                    if v is not None:
                        band = self._bands[name]
                        zs, breach = band.observe(
                            v, sig.get("direction", "both"))
                        row.update(z=round(zs, 4), breach=breach,
                                   mean=None if band.mean is None
                                   else round(band.mean, 6))
                self._step_alerts(name, sig, row, ts)
                state[name] = row
            self._state = state
        return state

    def _step_alerts(self, name, sig, row, ts):
        if row["breach"]:
            self._streak[name] += 1
        else:
            self._streak[name] = 0
            self._active[name] = False
            self._armed[name] = True
        fire_at = 1 if sig.get("kind") == "delta" \
            else self.min_consecutive
        if self._streak[name] >= fire_at:
            self._active[name] = True
            if self._armed[name]:
                self._armed[name] = False
                self.fired_total += 1
                c = self._fired_counter(name)
                if c is not None:
                    c.inc()
                if self.flight:
                    self._flight_dump(name, sig, row, ts)
        g = self._active_gauge(name)
        if g is not None:
            g.set(1 if self._active[name] else 0)
        row["alert"] = self._active[name]

    def _flight_dump(self, name, sig, row, ts):
        """One parseable ``fleet_anomaly`` postmortem per excursion —
        never raises (same contract as every flight trigger)."""
        try:
            from . import flightrec
            flightrec.note("fleet_anomaly", signal=name,
                           value=row["value"], z=row["z"])
            extra = {"signal": name, "signal_spec": dict(sig),
                     "value": row["value"], "z": row["z"],
                     "mean": row["mean"], "eval_ts": ts,
                     "streak": self._streak[name]}
            series = sig.get("series")
            if series is not None:
                extra["recent"] = self.history.query(
                    series, t0=ts - 4 * float(sig.get("window_s", 5.0)),
                    t1=ts, res="raw", limit=64)
            # what the host was actually DOING when the signal tripped:
            # the continuous profiler's last ~minute of folded stacks
            # (None when no profiler is armed in this process); its
            # absence must never cost the dump itself
            try:
                from . import contprof
                extra["profile"] = contprof.current_profile()
            except ImportError:  # standalone file-load (bench._obs_mod)
                pass
            # ...and where device memory stood: the active memory
            # ledger's segment tree + headroom forecast (None when no
            # ledger is armed) — the mem_used_ratio signal's postmortem
            try:
                from . import memledger
                extra["memory"] = memledger.current_memory()
            except ImportError:  # standalone file-load (bench._obs_mod)
                pass
            flightrec.dump("fleet_anomaly", extra=extra)
        except Exception:  # noqa: BLE001
            pass

    # -- rollups -----------------------------------------------------------

    def state(self):
        with self._lock:
            return {n: dict(r) for n, r in self._state.items()}

    def alerting(self):
        """Signal names currently out of band — the health() rollup
        (cached from the last evaluate; cheap enough for HTTP
        threads)."""
        with self._lock:
            return sorted(n for n, r in self._state.items()
                          if r.get("alert"))

    def health(self):
        """The ``health()["anomaly"]`` shape, mirroring the SLO
        rollup: {"alerting": [...], "signals": {...}}."""
        with self._lock:
            return {"alerting": sorted(
                        n for n, r in self._state.items()
                        if r.get("alert")),
                    "signals": {n: {"alert": r.get("alert", False),
                                    "value": r.get("value"),
                                    "z": r.get("z")}
                                for n, r in self._state.items()}}

    # -- offline replay ----------------------------------------------------

    @classmethod
    def replay(cls, history, *, signals=None, step_s=None, **kw):
        """Run the detector over a saved history (no registry, no
        flight dumps): walk the archive's time span at ``step_s``
        (default: its scrape interval) and return every firing as
        {"t", "signal", "value", "z"}. Empty list == the archive is
        clean — the committed-golden quiet check."""
        first, last = history.span()
        if first is None:
            return []
        step = float(step_s) if step_s is not None \
            else max(float(getattr(history, "interval_s", 1.0)), 1e-3)
        sen = cls(history, signals=signals, registry=None,
                  compile_fn=None, flight=False,
                  eval_interval_s=0.0, **kw)
        firings = []
        t = first
        while t <= last + step / 2:
            armed_before = dict(sen._armed)
            state = sen.evaluate(now=t)
            # an armed -> disarmed transition IS a fire (re-arming
            # only happens when the signal clears)
            for n, r in state.items():
                if armed_before.get(n, True) and not sen._armed[n]:
                    firings.append({"t": t, "signal": n,
                                    "value": r["value"], "z": r["z"]})
            t += step
        return firings
