"""Span records -> one Perfetto/Chrome-trace timeline.

The profiler already times *regions* (aggregate stats) and jax dumps
*device* traces (xplane); what was missing is the HOST SCHEDULING
story with real timestamps: when did request 7 sit in the queue, when
did its prefill run, which decode dispatches carried it, where did a
guard skip stall the train loop. A ``SpanRecorder`` holds a bounded
ring of timestamped spans and exports them as Chrome trace events
(``{"traceEvents": [...]}``) that Perfetto/chrome://tracing open
directly — and several recorders (serving, train, profiler regions)
merge into ONE timeline via ``export_chrome``.

Conventions:
- time base: ``time.perf_counter()`` for durations, mapped to epoch
  microseconds through a base pair captured at module import — all
  recorders in a process share it, so merged timelines align;
- lanes: each span names a ``tid`` lane (e.g. ``req3``, ``decode``);
  lanes get stable integer tids plus ``thread_name`` metadata events;
- ``ph: "X"`` complete events for spans, ``ph: "i"`` instants for
  annotations (page release, eviction, guard skip).

Stdlib-only; safe to call at host step boundaries (one deque append
under a lock per span).
"""
from __future__ import annotations

import json
import math
import os
import threading
import time
from collections import deque

__all__ = ["SpanRecorder", "export_chrome"]

# one shared epoch<->perf_counter base so independently-created
# recorders (serving engine, telemetry callback, profiler) merge into
# an aligned timeline
_EPOCH_BASE = time.time()
_PERF_BASE = time.perf_counter()


def _finite(obj):
    """Non-finite floats -> None (RFC-valid JSON for jq/Perfetto).
    (Duplicated across the observability modules by contract — each
    stays standalone-loadable from bench._obs_mod.)"""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: _finite(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_finite(v) for v in obj]
    return obj


def _to_epoch_us(perf_t):
    return (_EPOCH_BASE + (perf_t - _PERF_BASE)) * 1e6


def _suppressed():
    """True inside an introspection AOT replay: span emission is
    suppressed exactly like the tracer's counter bumps, so a replay
    that re-executes instrumented host code can never add phantom
    spans to a timeline (or perturb a span-count assertion)."""
    try:
        from .introspect import introspecting
    except ImportError:  # standalone file-load (bench._obs_mod)
        return False
    return introspecting()


class SpanRecorder:
    """Bounded ring of host spans, Chrome-trace exportable."""

    def __init__(self, name="run", maxlen=4096):
        self.name = name
        self._events = deque(maxlen=maxlen)
        self._lock = threading.Lock()
        self._lanes = {}           # lane name -> int tid
        # ring-overflow accounting: "dropped is visible, never
        # silent" — the count surfaces in export_chrome metadata and
        # the exporter /report, like trace-store and capture drops
        self.evicted = 0

    @staticmethod
    def now():
        """The recorder's clock (perf_counter seconds) — pass the
        returned value back to add()."""
        return time.perf_counter()

    def _lane(self, tid):
        lane = self._lanes.get(tid)
        if lane is None:
            lane = self._lanes[tid] = len(self._lanes)
        return lane

    # -- recording ---------------------------------------------------------
    def add(self, name, t0, t1=None, tid="main", cat="host", args=None):
        """One complete span: [t0, t1] in perf_counter seconds
        (t1 None = now). Returns the event dict."""
        if _suppressed():
            return None
        if t1 is None:
            t1 = time.perf_counter()
        ev = {"name": name, "cat": cat, "ph": "X",
              "ts": _to_epoch_us(t0),
              "dur": max((t1 - t0) * 1e6, 0.0),
              "tid": tid, "args": dict(args or {})}
        with self._lock:
            self._lane(tid)
            if len(self._events) == self._events.maxlen:
                self.evicted += 1
            self._events.append(ev)
        return ev

    def instant(self, name, tid="main", cat="host", args=None):
        """Zero-duration annotation (eviction, page release, skip)."""
        if _suppressed():
            return None
        ev = {"name": name, "cat": cat, "ph": "i", "s": "t",
              "ts": _to_epoch_us(time.perf_counter()),
              "tid": tid, "args": dict(args or {})}
        with self._lock:
            self._lane(tid)
            if len(self._events) == self._events.maxlen:
                self.evicted += 1
            self._events.append(ev)
        return ev

    def span(self, name, tid="main", cat="host", **args):
        """Context manager form: ``with rec.span("prefill_32",
        tid="req3"): ...``"""
        rec = self

        class _Span:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                rec.add(name, self.t0, tid=tid, cat=cat, args=args)
        return _Span()

    # -- reading/export ----------------------------------------------------
    def events(self):
        with self._lock:
            return list(self._events)

    def to_chrome(self, pid=None):
        """Chrome trace events for this recorder: lane metadata
        (process/thread names) + the recorded spans with integer
        pid/tid (the strict reading of the trace-event format)."""
        pid = pid if pid is not None else self.name
        with self._lock:
            evs = list(self._events)
            lanes = dict(self._lanes)
        out = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": self.name}}]
        for lane_name, tid in sorted(lanes.items(), key=lambda kv: kv[1]):
            out.append({"name": "thread_name", "ph": "M", "pid": pid,
                        "tid": tid, "args": {"name": str(lane_name)}})
        for ev in evs:
            row = dict(ev)
            row["pid"] = pid
            row["tid"] = lanes.get(row["tid"], 0)
            out.append(row)
        return out

    def export(self, path, extra_recorders=()):
        """Write this recorder (+ any extras) as one Chrome trace
        JSON. Returns the path."""
        return export_chrome(path, [self, *extra_recorders])

    def clear(self):
        with self._lock:
            self._events.clear()


def export_chrome(path, recorders):
    """Merge several SpanRecorders into one Chrome trace file —
    Perfetto shows each recorder as a named process, each lane as a
    named thread, on one shared timeline (the spans all ride the same
    epoch base). Atomic write; returns the path."""
    events = []
    for i, rec in enumerate(recorders):
        events.extend(rec.to_chrome(pid=i + 1))
    events.sort(key=lambda e: (e.get("ts", 0), e.get("ph") != "M"))
    doc = {"traceEvents": events, "displayTimeUnit": "ms",
           "metadata": {"evicted_spans": {
               rec.name: int(getattr(rec, "evicted", 0))
               for rec in recorders}}}
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        try:
            json.dump(doc, f, allow_nan=False)
        except ValueError:
            # a NaN span arg (e.g. a loss annotation mid-storm) must
            # still land as valid JSON Perfetto will open
            f.seek(0)
            f.truncate()
            json.dump(_finite(doc), f, allow_nan=False)
    os.replace(tmp, path)
    return path
