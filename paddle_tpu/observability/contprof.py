"""Continuous host-side sampling profiler with serving-phase tags.

Every round since r7 grew the *host* leg of the serving hot path —
prefix fingerprinting, draft/rewind bookkeeping, placement scoring,
journal appends — yet spans can only time regions somebody remembered
to instrument. This module closes the blind spot: a daemon thread
walks ``sys._current_frames()`` at a configurable rate (default 19 Hz,
``PADDLE_TPU_PROFILE`` / ``PADDLE_TPU_PROFILE_HZ``), folds every
thread's stack into a bounded weighted trie, and tags each sample with
the thread's current **serving phase** — a marker set exactly where
``ServingEngine``/``FleetRouter`` already open spans (``prefill_<b>``
/ ``decode`` / ``spec_verify`` / ``prefix_admit`` / ``placement`` /
``journal``; unmarked threads read as ``idle``) — so a profile answers
"host wall time, by phase, by frame".

Design contracts, matching the rest of the observability plane:

- **Host-side only, zero-recompile untouched.** The sampler never
  imports jax, never touches devices, and skips threads that are
  inside an ``introspecting()`` AOT replay (the introspect module
  publishes their thread ids) — profiling ON must leave compile
  counts frozen, chaos-asserted.
- **Self-measuring, never silent.** ``profile_overhead_ratio`` gauges
  the sampler's own duty cycle (EWMA of sample-cost / period) and the
  rate automatically halves while the ratio sits above a 1% cap
  (``profile_backoffs_total`` counts each step down, floor at
  ``min_hz``); when the stack trie hits its node bound the sample's
  weight lands on the deepest existing node and
  ``profile_samples_dropped_total`` counts the truncation.
- **Stdlib-only, standalone-loadable** (``bench._obs_mod``): no
  intra-package imports at module scope; ``io/atomic`` is file-loaded
  lazily for the write-then-rename persistence discipline.

Exports: ``fold()``/``folded_text()`` (collapsed one-line-per-stack
text, ``phase:decode;mod.fn;mod.fn2 N``), ``save()``/``load_folded()``
(torn-tolerant: a truncated copy loses at most the tail line),
``flamegraph_html()`` (self-contained — the folded profile rides an
embedded JSON ``<script>`` a machine can parse back out), ``digest()``
(bounded per-phase top-K leaf frames — the shape that rides replica
heartbeats into the router's fleet hotspot rollup) and ``report()``
(the ``/profile?window=S`` endpoint body). ``tools/profile_diff.py``
consumes two folded profiles and gates on wall-share deltas.
"""
from __future__ import annotations

import collections
import json
import math
import os
import sys
import threading
import time

__all__ = ["ContinuousProfiler", "phase", "set_phase", "current_phase",
           "active_profiler", "current_profile", "load_folded",
           "fold_shares", "IDLE_PHASE"]

IDLE_PHASE = "idle"


def _finite(obj):
    """Map non-finite floats to None for the JSON exports (the
    metrics.py discipline, duplicated — this module stays
    standalone-loadable, no intra-package imports at module scope)."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: _finite(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_finite(v) for v in obj]
    return obj

# -- serving-phase markers --------------------------------------------------
#
# A plain module-level dict keyed by thread id: single-key reads and
# writes are GIL-atomic, so the sampler thread can read markers set by
# dispatch threads with no lock on the hot path. A thread with no
# marker samples as "idle" — honest for the control loop's wait slots.

_phases = {}


def set_phase(name):
    """Set (or with ``None`` clear) the calling thread's phase."""
    tid = threading.get_ident()
    if name is None:
        _phases.pop(tid, None)
    else:
        _phases[tid] = str(name)


def current_phase(tid=None):
    """The phase marker of ``tid`` (default: calling thread), or
    None."""
    return _phases.get(threading.get_ident() if tid is None else tid)


class phase:
    """Context manager marking the calling thread's serving phase for
    the duration of a block; re-entrant (restores the outer phase on
    exit, so a journal append inside placement reads ``journal`` then
    goes back to ``placement``)."""

    __slots__ = ("name", "_prev", "_tid")

    def __init__(self, name):
        self.name = str(name)

    def __enter__(self):
        self._tid = threading.get_ident()
        self._prev = _phases.get(self._tid)
        _phases[self._tid] = self.name
        return self

    def __exit__(self, *exc):
        if self._prev is None:
            _phases.pop(self._tid, None)
        else:
            _phases[self._tid] = self._prev
        return False


def _introspecting_tids():
    """Thread ids currently inside an AOT introspection replay —
    published by introspect.py under either its package name or the
    bench standalone-load key. No import: if the module was never
    loaded, no replay can be running."""
    for key in ("paddle_tpu.observability.introspect",
                "_bench_obs_introspect"):
        mod = sys.modules.get(key)
        if mod is not None:
            tids = getattr(mod, "_introspecting_threads", None)
            if tids:
                return tids
    return ()


# -- env knobs --------------------------------------------------------------

def profile_enabled_from_env(default=False):
    """The ``PADDLE_TPU_PROFILE`` arm switch (default OFF: never-armed
    engines stay byte-identical to the legacy goldens, the same
    dormancy contract spec-decode follows)."""
    raw = os.environ.get("PADDLE_TPU_PROFILE")
    if raw is None:
        return bool(default)
    return raw.lower() in ("1", "true", "on")


def profile_hz_from_env(default=19.0):
    """``PADDLE_TPU_PROFILE_HZ`` (default 19 — deliberately prime, so
    the sampler can't phase-lock with 10/100 Hz periodic work and
    systematically miss it)."""
    try:
        hz = float(os.environ.get("PADDLE_TPU_PROFILE_HZ", default))
    except ValueError:
        return float(default)
    return hz if hz > 0 else float(default)


def _atomic():
    """io/atomic.py, lazily — package import when available, straight
    file-load otherwise (standalone mode has no package context)."""
    global _atomic_mod
    if _atomic_mod is None:
        try:
            from ..io import atomic as mod
        except ImportError:
            import importlib.util as ilu
            path = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                os.pardir, "io", "atomic.py")
            spec = ilu.spec_from_file_location(
                "_bench_obs_io_atomic", path)
            mod = ilu.module_from_spec(spec)
            spec.loader.exec_module(mod)
        _atomic_mod = mod
    return _atomic_mod


_atomic_mod = None


# -- the profiler -----------------------------------------------------------

class ContinuousProfiler:
    """Always-on sampling profiler for one process.

    ``start()`` spawns the daemon sampler; ``stop()`` joins it. All
    public readers (fold/digest/report) take the internal lock, so
    exporter HTTP threads can scrape a live profiler safely.
    """

    def __init__(self, *, hz=None, registry=None, name="host",
                 max_nodes=4096, max_depth=48, overhead_cap=0.01,
                 min_hz=1.0, topk=32, recent_samples=8192):
        self.name = str(name)
        self.hz = float(hz) if hz is not None else profile_hz_from_env()
        self.base_hz = self.hz
        self.max_nodes = int(max_nodes)
        self.max_depth = int(max_depth)
        self.overhead_cap = float(overhead_cap)
        self.min_hz = float(min_hz)
        self.topk = int(topk)
        self._lock = threading.Lock()
        self._root = [0, {}]          # [self_weight, {label: node}]
        self._nodes = 1
        self._recent = collections.deque(maxlen=int(recent_samples))
        self._intern = {}             # stack-key tuple -> itself
        self._phase_counts = {}       # phase -> samples
        self._phase_leaf = {}         # phase -> {leaf frame: samples}
        self.samples = 0
        self.dropped = 0
        self.backoffs = 0
        self.overhead_ratio = 0.0
        self._ewma_seeded = False
        self.started_at = None
        self._stop = threading.Event()
        self._thread = None
        self._g_overhead = self._g_hz = None
        self._c_samples = self._c_dropped = self._c_backoffs = None
        if registry is not None:
            self._g_overhead = registry.gauge(
                "profile_overhead_ratio",
                help="continuous profiler duty cycle (EWMA of "
                     "sample cost / sampling period); Hz backs off "
                     "above the cap")
            self._g_hz = registry.gauge(
                "profile_hz",
                help="continuous profiler's current sampling rate "
                     "(backed off below the configured rate when the "
                     "overhead cap is hit)")
            self._c_samples = registry.counter(
                "profile_samples_total",
                help="stack samples folded into the profile trie")
            self._c_dropped = registry.counter(
                "profile_samples_dropped_total",
                help="samples truncated at the trie node bound "
                     "(weight kept at the deepest existing node — "
                     "the cap is never silent)")
            self._c_backoffs = registry.counter(
                "profile_backoffs_total",
                help="automatic Hz halvings taken to stay under the "
                     "overhead cap")
            self._g_overhead.set(0.0)
            self._g_hz.set(self.hz)

    # -- lifecycle --------------------------------------------------------

    def start(self):
        if self._thread is not None:
            return self
        self.started_at = time.time()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=f"contprof-{self.name}",
            daemon=True)
        self._thread.start()
        with _active_lock:
            if self not in _active:
                _active.append(self)
        return self

    def stop(self, timeout=2.0):
        t = self._thread
        if t is None:
            return
        self._stop.set()
        t.join(timeout)
        self._thread = None
        with _active_lock:
            if self in _active:
                _active.remove(self)

    @property
    def running(self):
        return self._thread is not None and self._thread.is_alive()

    def _run(self):
        while not self._stop.wait(1.0 / self.hz):
            t0 = time.perf_counter()
            try:
                self._sample(time.time())
            except Exception:   # noqa: BLE001 — the profiler must
                pass            # never take the serving process down
            self._note_duty(time.perf_counter() - t0)

    # -- sampling ---------------------------------------------------------

    def _stack_of(self, frame):
        labels = []
        depth = 0
        while frame is not None and depth < self.max_depth:
            mod = frame.f_globals.get("__name__", "?")
            labels.append(f"{mod}.{frame.f_code.co_name}")
            frame = frame.f_back
            depth += 1
        labels.reverse()
        return tuple(labels)

    def _sample(self, now):
        me = threading.get_ident()
        intro = _introspecting_tids()
        frames = sys._current_frames()
        with self._lock:
            for tid, frame in frames.items():
                if tid == me or tid in intro:
                    continue
                ph = _phases.get(tid, IDLE_PHASE)
                stack = self._stack_of(frame)
                self._insert(ph, stack)
                self.samples += 1
                if self._c_samples is not None:
                    self._c_samples.inc()
                self._phase_counts[ph] = \
                    self._phase_counts.get(ph, 0) + 1
                leaf = stack[-1] if stack else "?"
                self._leaf_bump(ph, leaf)
                key = ("phase:" + ph,) + stack
                key = self._intern.setdefault(key, key)
                if len(self._intern) > 4 * self._recent.maxlen:
                    self._intern.clear()
                self._recent.append((now, key))

    def _insert(self, ph, stack):
        node = self._root
        truncated = False
        for label in ("phase:" + ph,) + stack:
            child = node[1].get(label)
            if child is None:
                if self._nodes >= self.max_nodes:
                    truncated = True
                    break
                child = [0, {}]
                node[1][label] = child
                self._nodes += 1
            node = child
        node[0] += 1
        if truncated:
            self.dropped += 1
            if self._c_dropped is not None:
                self._c_dropped.inc()

    def _leaf_bump(self, ph, leaf):
        d = self._phase_leaf.setdefault(ph, {})
        d[leaf] = d.get(leaf, 0) + 1
        if len(d) > 4 * self.topk:
            # bounded approximate top-K: evict the lightest half.
            # Frames that re-enter restart their count — fine for a
            # hotspot digest, documented, and the full trie still
            # holds the exact weights.
            keep = sorted(d.items(), key=lambda kv: -kv[1])
            self._phase_leaf[ph] = dict(keep[:2 * self.topk])

    def _note_duty(self, cost_s):
        """Fold one sampling pass's cost into the duty-cycle EWMA and
        back the rate off while it sits above the cap. Exposed for the
        deterministic backoff tests (no real sampling needed)."""
        period = 1.0 / max(self.hz, 1e-9)
        ratio = min(1.0, max(0.0, cost_s) / period)
        if not self._ewma_seeded:
            self.overhead_ratio = ratio
            self._ewma_seeded = True
        else:
            self.overhead_ratio = (0.8 * self.overhead_ratio
                                   + 0.2 * ratio)
        if self.overhead_ratio > self.overhead_cap \
                and self.hz > self.min_hz:
            self.hz = max(self.min_hz, self.hz / 2.0)
            self.backoffs += 1
            # halving Hz halves the duty cycle going forward; reflect
            # it now so one spike can't cascade straight to min_hz
            self.overhead_ratio /= 2.0
            if self._c_backoffs is not None:
                self._c_backoffs.inc()
            if self._g_hz is not None:
                self._g_hz.set(self.hz)
        if self._g_overhead is not None:
            self._g_overhead.set(self.overhead_ratio)

    # -- folding / export --------------------------------------------------

    def fold(self, window_s=None, now=None):
        """Collapsed profile as {'phase:p;mod.fn;...': weight}. With
        ``window_s``, folded from the bounded recent-sample ring
        (newest ``recent_samples`` samples) instead of the full
        trie."""
        out = {}
        with self._lock:
            if window_s is None:
                stack = [((), self._root)]
                while stack:
                    path, node = stack.pop()
                    if node[0] > 0 and path:
                        out[";".join(path)] = \
                            out.get(";".join(path), 0) + node[0]
                    for label, child in node[1].items():
                        stack.append((path + (label,), child))
            else:
                cutoff = (time.time() if now is None else now) \
                    - float(window_s)
                for t, key in self._recent:
                    if t >= cutoff:
                        k = ";".join(key)
                        out[k] = out.get(k, 0) + 1
        return out

    def folded_text(self, window_s=None, now=None):
        """The collapsed-stack text format (one ``stack weight`` line,
        sorted): flamegraph.pl-compatible and profile_diff's input."""
        folded = self.fold(window_s=window_s, now=now)
        return "\n".join(f"{k} {v}" for k, v in sorted(folded.items()))

    def save(self, path, window_s=None):
        """Persist the folded profile via write-then-rename. The text
        format is torn-tolerant by construction: ``load_folded`` of a
        truncated copy drops at most the tail line."""
        header = (f"# contprof folded v1 name={self.name} "
                  f"hz={self.hz:g} samples={self.samples} "
                  f"dropped={self.dropped}\n")
        body = self.folded_text(window_s=window_s)
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        _atomic().atomic_replace(
            path, (header + body + "\n").encode("utf-8"))
        return path

    def digest(self, topk=8):
        """Bounded per-phase hotspot digest — the shape that rides
        replica heartbeats (host-side JSON, a few hundred bytes)."""
        with self._lock:
            phases = dict(self._phase_counts)
            top = {ph: sorted(d.items(), key=lambda kv: -kv[1])[:topk]
                   for ph, d in self._phase_leaf.items()}
        return {"samples": self.samples, "dropped": self.dropped,
                "backoffs": self.backoffs,
                "overhead_ratio": round(self.overhead_ratio, 6),
                "hz": self.hz, "phases": phases,
                "top": {ph: [[f, int(n)] for f, n in rows]
                        for ph, rows in top.items()}}

    def stats(self):
        """Flat monotonic counters for the router's restart-tolerant
        delta fold (the _fold_spec/_fold_prefix idiom)."""
        return {"samples": int(self.samples),
                "dropped": int(self.dropped),
                "backoffs": int(self.backoffs)}

    def report(self, window_s=None):
        """The ``/profile?window=S`` endpoint body."""
        return {"name": self.name, "running": self.running,
                "hz": self.hz, "base_hz": self.base_hz,
                "overhead_ratio": round(self.overhead_ratio, 6),
                "overhead_cap": self.overhead_cap,
                "samples": self.samples, "dropped": self.dropped,
                "backoffs": self.backoffs, "nodes": self._nodes,
                "window_s": window_s,
                "folded": self.folded_text(window_s=window_s),
                "digest": self.digest()}

    def flamegraph_html(self, path=None, window_s=None, title=None):
        """Self-contained flamegraph: the folded profile is embedded
        as a JSON ``<script>`` block (machine-parseable back out — the
        profile_smoke stage does exactly that) and a small inline
        renderer draws the flame as nested divs. No external assets,
        openable from a triage dir years later."""
        folded = self.fold(window_s=window_s)
        doc = {"name": self.name, "title": title or
               f"contprof {self.name}", "samples": self.samples,
               "dropped": self.dropped, "hz": self.hz,
               "window_s": window_s, "folded": folded}
        try:
            payload = json.dumps(doc, sort_keys=True, allow_nan=False)
        except ValueError:
            payload = json.dumps(_finite(doc), sort_keys=True,
                                 allow_nan=False)
        # "</" would close the script tag early inside inline JSON
        payload = payload.replace("</", "<\\/")
        html_text = _FLAME_TEMPLATE.replace("__PROFILE_JSON__", payload)
        if path is None:
            return html_text
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        _atomic().atomic_replace(path, html_text.encode("utf-8"))
        return path


_FLAME_TEMPLATE = """<!doctype html>
<html><head><meta charset="utf-8"><title>contprof flamegraph</title>
<style>
body { font: 12px monospace; margin: 12px; background: #fff; }
#flame div.fr { position: absolute; height: 16px; overflow: hidden;
  white-space: nowrap; border: 1px solid #fff; box-sizing: border-box;
  cursor: default; }
#flame { position: relative; }
#info { margin: 8px 0; color: #444; }
</style></head><body>
<h3 id="t"></h3><div id="info"></div><div id="flame"></div>
<script id="profile-data" type="application/json">__PROFILE_JSON__</script>
<script>
var doc = JSON.parse(document.getElementById("profile-data").text);
document.getElementById("t").textContent = doc.title;
var root = {c: {}, w: 0};
var total = 0;
Object.keys(doc.folded).forEach(function (k) {
  var w = doc.folded[k]; total += w;
  var node = root;
  k.split(";").forEach(function (label) {
    node = node.c[label] || (node.c[label] = {c: {}, w: 0});
    node.sub = (node.sub || 0) + w;
  });
  node.w += w;
});
document.getElementById("info").textContent =
  total + " samples @ " + doc.hz + " Hz" +
  (doc.dropped ? " (" + doc.dropped + " truncated)" : "");
var flame = document.getElementById("flame");
var W = Math.max(600, window.innerWidth - 40);
var maxDepth = 0;
function draw(node, label, x, width, depth) {
  if (depth >= 0 && width >= 1) {
    var d = document.createElement("div");
    d.className = "fr";
    d.style.left = x + "px"; d.style.top = depth * 17 + "px";
    d.style.width = width + "px";
    var hue = label.indexOf("phase:") === 0 ? 210 : 30;
    d.style.background = "hsl(" + hue + ", 70%, " +
      (85 - (depth % 5) * 4) + "%)";
    d.textContent = label;
    d.title = label + " — " + (node.sub || node.w) + " samples (" +
      (100 * (node.sub || node.w) / Math.max(total, 1)).toFixed(1) +
      "%)";
    flame.appendChild(d);
    if (depth > maxDepth) maxDepth = depth;
  }
  var cx = x;
  Object.keys(node.c).sort().forEach(function (k) {
    var child = node.c[k];
    var cw = W * (child.sub || child.w) / Math.max(total, 1);
    draw(child, k, cx, cw, depth + 1);
    cx += cw;
  });
}
draw(root, "", 0, W, -1);
flame.style.height = (maxDepth + 2) * 17 + "px";
</script></body></html>
"""


# -- module-level active-profiler registry ---------------------------------
#
# The anomaly sentinel and the flight recorder attach "what was the
# process actually doing" evidence without holding a profiler
# reference — they ask for the most recently started one.

_active = []
_active_lock = threading.Lock()


def active_profiler():
    """The most recently started, still-running profiler (or None)."""
    with _active_lock:
        for p in reversed(_active):
            if p.running:
                return p
    return None


def current_profile(window_s=60.0):
    """``report(window_s)`` of the active profiler, or None — the
    guarded attach point for flight dumps."""
    p = active_profiler()
    if p is None:
        return None
    try:
        return p.report(window_s=window_s)
    except Exception:   # noqa: BLE001 — evidence attach never raises
        return None


# -- loaders / share math ---------------------------------------------------

def load_folded(path):
    """Folded-profile file -> {stack: weight}. Torn-tolerant: comment,
    blank and unparseable lines are skipped (a truncated tail line
    either still parses — smaller weight — or drops); an unreadable
    file is an empty profile, never an exception."""
    out = {}
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            data = f.read()
    except OSError:
        return out
    for line in data.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        stack, _, weight = line.rpartition(" ")
        if not stack:
            continue
        try:
            n = int(weight)
        except ValueError:
            continue
        if n > 0:
            out[stack] = out.get(stack, 0) + n
    return out


def fold_shares(folded):
    """{stack: weight} -> ({phase: share}, {leaf_frame: share}) with
    shares in [0, 1] of total weight — the units profile_diff gates
    on. Self-weight by leaf frame; the phase is the stack's
    ``phase:*`` head (``idle`` when a profile predates phase tags)."""
    total = float(sum(folded.values())) or 1.0
    phases, frames = {}, {}
    for stack, w in folded.items():
        parts = stack.split(";")
        ph = parts[0][6:] if parts[0].startswith("phase:") \
            else IDLE_PHASE
        phases[ph] = phases.get(ph, 0.0) + w / total
        leaf = parts[-1] if parts else "?"
        frames[leaf] = frames.get(leaf, 0.0) + w / total
    return phases, frames
