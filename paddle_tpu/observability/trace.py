"""RecompileTracer — every XLA trace becomes a queryable run fact.

"Zero-recompile" was a bench-only assertion (ServingEngine counted
traces privately; the Engine counted nothing). This tracer is the one
mechanism both ride: ``tracer.jit(site, fn, **jit_kwargs)`` returns a
jitted callable whose body bumps a per-site counter exactly when jax
(re)traces — the same ground truth the serving zero-recompile contract
already used — and whose host wrapper, ONLY on a call that traced,
records an event carrying:

- the site name ("decode", "prefill_32", "train_step", ...);
- the argument shape/dtype signature (computed lazily, never on the
  steady-state hot path);
- a wall timestamp and the call's wall time (trace + compile +
  dispatch — the cost a recompile cliff actually charges);
- whether the trace was UNEXPECTED: a signature this site has already
  traced once. First-time signatures (a new prefill bucket, an
  intentional shape change) are expected; re-tracing a seen signature
  means a compiled program was dropped and rebuilt — the cliff the
  MLPerf/TPU-pod postmortems say to hunt first.

Per-call steady-state overhead is two dict reads and a perf_counter —
no device sync, no shape walking. Tracers register in a process-wide
WeakSet; ``report_all()`` merges every live tracer's report into the
run report bench.py exports next to metrics.json.
"""
from __future__ import annotations

import collections
import hashlib
import threading
import time

__all__ = ["RecompileTracer", "get_tracer", "all_tracers", "report_all"]

# REENTRANT: close() runs from GC finalizers (Engine's
# weakref.finalize, ServingEngine.__del__), and a cyclic collection
# can fire on an allocation made while this same thread already holds
# the lock (report_all builds dicts under it) — a plain Lock would
# self-deadlock there
_all_lock = threading.RLock()
# strong refs, deliberately: a bench worker's Engine (and its tracer)
# is often garbage before the end-of-run report is written — a weak
# registry would silently drop exactly the sites the report is for.
# Cost is bounded per tracer (counts + a maxlen event deque), and a
# long-lived host that retires engines bounds the COUNT by calling
# tracer.close() (Engine/ServingEngine finalizers do), which folds the
# tracer's aggregates into _closed_agg — a CUMULATIVE per-tracer-name
# rollup, never evicted, so an unexpected retrace recorded by engine
# #3 of a 500-engine sweep still shows in the final report (a bounded
# list of individual reports would silently drop it).
_all_tracers = []
_closed_agg = {}


def _leaf_sig(x):
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return f"{x.dtype}{list(x.shape)}"
    return type(x).__name__


def _signature(args, kwargs):
    import jax
    leaves = jax.tree_util.tree_leaves((args, kwargs))
    parts = [_leaf_sig(l) for l in leaves]
    s = ";".join(parts)
    if len(s) > 512:
        digest = hashlib.sha1(s.encode()).hexdigest()[:12]
        s = f"{parts[0]};...;{parts[-1]} ({len(parts)} leaves, " \
            f"sha1:{digest})"
    return s


class RecompileTracer:
    """Per-subsystem trace accounting (Engine and ServingEngine each
    own one; ad-hoc code can share ``get_tracer()``)."""

    def __init__(self, name="default", registry=None, max_events=256):
        self.name = name
        self._counts = {}          # site -> total traces
        self._sigs = {}            # site -> set of seen signatures
        self._unexpected = {}      # site -> retraces of a seen sig
        self._events = collections.deque(maxlen=max_events)
        self._registry = registry
        self._closed = False
        with _all_lock:
            _all_tracers.append(self)

    # -- wrapping ----------------------------------------------------------
    def jit(self, site, fn, introspect=True, **jit_kwargs):
        """jax.jit(fn) with trace accounting at `site`. The inner bump
        runs exactly when jax traces (compiles); the outer wrapper
        stays host-side and records the event + signature only on a
        call that traced. On such a call the site's compiled
        executable is also introspected (cost/memory analysis — see
        introspect.py) via an AOT replay whose re-trace is SUPPRESSED
        from all accounting here: both the counter bump and the
        host-side note check ``introspecting()``, so the replay can
        never masquerade as a recompile (nested sites included —
        train_step re-traced inside train_step_multi's replay stays
        silent too). ``introspect=False`` keeps the accounting but
        skips the AOT replay — for user-facing one-shot compiles
        (to_static) where doubling the compile buys nothing."""
        import jax
        try:
            from .introspect import introspecting
        except ImportError:  # standalone file-load (bench._obs_mod)
            def introspecting():
                return False
        counts = self._counts

        def traced(*args, **kw):
            if not introspecting():
                counts[site] = counts.get(site, 0) + 1
            return fn(*args, **kw)

        jfn = jax.jit(traced, **jit_kwargs)
        tracer = self

        def call(*args, **kw):
            if introspecting():
                return jfn(*args, **kw)
            before = counts.get(site, 0)
            t0 = time.perf_counter()
            out = jfn(*args, **kw)
            if counts.get(site, 0) != before:
                wall = time.perf_counter() - t0
                tracer._note(site, args, kw, wall)
                if introspect:
                    tracer._introspect(site, jfn, args, kw, wall)
            return out

        call.site = site
        call.jitted = jfn
        # drop-in for a bare jax.jit: callers introspect the compiled
        # function (Engine AOT-lowers grad/apply steps to audit
        # donation; tests clear one function's executable cache)
        for attr in ("lower", "clear_cache", "eval_shape", "trace"):
            if hasattr(jfn, attr):
                setattr(call, attr, getattr(jfn, attr))
        return call

    def _note(self, site, args, kwargs, wall_s):
        try:
            sig = _signature(args, kwargs)
        except Exception:  # noqa: BLE001 — accounting must never kill a step
            sig = "<unavailable>"
        seen = self._sigs.setdefault(site, set())
        unexpected = sig in seen
        seen.add(sig)
        if unexpected:
            self._unexpected[site] = self._unexpected.get(site, 0) + 1
        self._events.append({
            "site": site, "signature": sig,
            "ts": round(time.time(), 6),
            "compile_s": round(wall_s, 6),
            "unexpected": unexpected,
        })
        reg = self._registry
        if reg is not None:
            reg.counter("recompile_traces_total",
                        help="XLA traces (== compiles) per jit site",
                        labels={"tracer": self.name,
                                "site": site}).inc()
            if unexpected:
                reg.counter(
                    "recompile_unexpected_retraces_total",
                    help="re-traces of an already-seen signature",
                    labels={"tracer": self.name, "site": site}).inc()
            reg.histogram("recompile_wall_seconds",
                          help="wall time of calls that traced",
                          labels={"tracer": self.name}).observe(wall_s)

    def _introspect(self, site, jfn, args, kwargs, wall_s):
        """Capture the freshly-compiled executable's cost/memory
        analysis (introspect.capture_site). Failure-proof: a broken
        AOT path records a skip reason, never kills the step."""
        try:
            from .introspect import capture_site
            capture_site(self.name, site, jfn, args, kwargs,
                         wall_s=wall_s, registry=self._registry)
        except Exception:  # noqa: BLE001 — accounting must never kill a step
            pass

    # -- manual accounting (sites not built via .jit) ----------------------
    def count_trace(self, site):
        """Bump `site` from inside a hand-rolled traced body (legacy
        callers); no signature/event is recorded."""
        self._counts[site] = self._counts.get(site, 0) + 1

    def forget(self, site):
        """Drop a site's accounting. For dynamically-minted sites
        (to_static wrappers releasing theirs on GC) so a
        wrapper-churning process doesn't grow the tracer — and its
        report — without bound. A site that recorded an UNEXPECTED
        retrace is kept: that signal must survive the wrapper that
        produced it, or churn could launder a real recompile out of
        the report. Returns True when the site was dropped."""
        if self._unexpected.get(site):
            return False
        self._counts.pop(site, None)
        self._sigs.pop(site, None)
        self._unexpected.pop(site, None)
        return True

    # -- queries -----------------------------------------------------------
    def counts(self):
        return dict(self._counts)

    def unexpected_retraces(self):
        return sum(self._unexpected.values())

    def events(self, site=None):
        return [e for e in self._events
                if site is None or e["site"] == site]

    def report(self):
        """The queryable recompile report: per-site trace totals,
        distinct signatures, unexpected retraces, plus the bounded
        event log."""
        sites = {}
        for site, n in sorted(self._counts.items()):
            sites[site] = {
                "traces": n,
                "signatures": len(self._sigs.get(site, ())),
                "unexpected_retraces": self._unexpected.get(site, 0),
            }
        return {"tracer": self.name, "sites": sites,
                "unexpected_retraces": self.unexpected_retraces(),
                "events": list(self._events)}

    def close(self):
        """Retire this tracer: drop it from the live set (so repeated
        engine construction can't grow memory for the process
        lifetime) while keeping its site aggregates — minus the event
        log and signature sets — visible to report_all(), folded into
        the cumulative per-name rollup. Safe to call twice; the
        wrapped jitted callables keep working, they just stop
        contributing new facts to the merged report."""
        with _all_lock:
            try:
                _all_tracers.remove(self)
            except ValueError:
                return  # already closed
            self._closed = True
            rep = self.report()
            if not rep["sites"]:
                return
            agg = _closed_agg.setdefault(
                self.name, {"tracer": self.name, "sites": {},
                            "unexpected_retraces": 0, "events": [],
                            "closed": True, "closed_tracers": 0})
            for site, row in rep["sites"].items():
                dst = agg["sites"].setdefault(
                    site, {"traces": 0, "signatures": 0,
                           "unexpected_retraces": 0})
                dst["traces"] += row["traces"]
                # distinct-per-tracer counts summed: an upper bound on
                # process-wide distinct signatures (the sets are gone)
                dst["signatures"] += row["signatures"]
                dst["unexpected_retraces"] += row["unexpected_retraces"]
            agg["unexpected_retraces"] += rep["unexpected_retraces"]
            agg["closed_tracers"] += 1


_default = RecompileTracer(name="default")


def get_tracer():
    return _default


def all_tracers():
    with _all_lock:
        return list(_all_tracers)


def report_all():
    """Merge every live tracer's report (plus the compact reports of
    closed ones) — the `recompile_report` section of the exported run
    report. `unexpected_retraces` == 0 is the queryable form of the
    zero-recompile claim."""
    with _all_lock:
        # one lock acquisition across live builds AND the closed-agg
        # read, plus a final _closed re-check: a tracer whose GC
        # finalizer closes it mid-report (the RLock re-entry the module
        # comment anticipates) folds into _closed_agg and is then
        # dropped from the live pass — counted once, never twice
        pairs = [(t, t.report()) for t in list(_all_tracers)]
        tracers = [{**r, "sites": {s: dict(v)
                                   for s, v in r["sites"].items()}}
                   for r in list(_closed_agg.values())]
        tracers += [r for t, r in pairs if not t._closed]
    tracers = [t for t in tracers if t["sites"]]
    tracers.sort(key=lambda t: t["tracer"])
    return {"tracers": tracers,
            "unexpected_retraces": sum(t["unexpected_retraces"]
                                       for t in tracers)}
