"""Telemetry history plane — a bounded in-process time-series store.

Everything the observability stack exposed before this module was
instantaneous: the MetricsRegistry is a point-in-time snapshot, the
SLOTracker forgets past its horizon, and regression detection existed
only as the offline ``tools/metrics_diff.py`` canary at campaign end.
This module keeps *history*: a ``HistoryStore`` scrapes any
``MetricsRegistry`` on a cadence into per-series rings with a
raw → 10s → 60s downsampling ladder, and answers the questions a
scale/tune decision (ROADMAP items 3 and 5) or an online anomaly
detector (``observability.sentinel``) needs:

- ``query(key, t0, t1, res)`` — range read at a resolution;
- ``rate(key, window_s)`` — per-second increase of a counter (or a
  histogram's count), monotonic-reset tolerant;
- ``quantile_over_time(key, q, window_s)`` — bucket-delta quantile of
  a histogram over a window (what "TTFT p99 over the last 5s" means,
  computed from cumulative bucket counts at the window edges);
- ``registry_snapshot_at(t)`` — a full registry-snapshot
  reconstruction at any past instant, which is what lets ONE history
  archive drive the ``tools/metrics_diff.py --at/--vs`` canary gate
  at any two points in time.

Retention is bounded per series per resolution (deque rings): the raw
ring holds the recent past at scrape cadence, the 10s and 60s rungs
hold progressively longer horizons at progressively coarser grain —
the classic TSDB ladder, sized so a day of 1 Hz scrape stays a few MB.

Persistence follows the write-ahead journal's torn-tail discipline,
not trust: ``save()`` writes length-prefixed, CRC-checksummed JSONL
lines through ``io/atomic.py``'s write-then-rename, and ``load()``
drops (and counts) any line that is short, fails its checksum, or
does not parse — a snapshot truncated at ANY byte offset reloads
cleanly, never duplicates a sample, and loses at most the tail
(fuzz-pinned by tests/test_history.py).

Stdlib-only by contract: loadable standalone via ``bench._obs_mod``
(tools/metrics_diff.py reads archives with no jax, no package
import). The io/atomic helper is resolved lazily with a file-load
fallback, exactly like flightrec does.
"""
from __future__ import annotations

import json
import math
import os
import threading
import time
import zlib
from collections import deque

__all__ = ["HistoryStore", "DEFAULT_RUNGS"]

_FORMAT = 1

#: (bucket_seconds, retained_samples) downsampling ladder on top of
#: the raw ring — raw at scrape cadence, then 10s, then 60s.
DEFAULT_RUNGS = ((10.0, 360), (60.0, 1440))

_atomic_mod = None


def _atomic():
    """io/atomic.py, lazily — package import when available, straight
    file-load otherwise (standalone mode has no package context; the
    helper is stdlib-only by contract). Same pattern as flightrec."""
    global _atomic_mod
    if _atomic_mod is None:
        try:
            from ..io import atomic as mod
        except ImportError:
            import importlib.util as ilu
            path = os.path.join(
                os.path.dirname(os.path.abspath(__file__)),
                os.pardir, "io", "atomic.py")
            spec = ilu.spec_from_file_location(
                "_bench_obs_io_atomic", path)
            mod = ilu.module_from_spec(spec)
            spec.loader.exec_module(mod)
        _atomic_mod = mod
    return _atomic_mod


def _finite(obj):
    """Non-finite floats -> None (RFC-valid JSON). Duplicated across
    the stdlib-only observability modules on purpose — each stays
    standalone-loadable."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: _finite(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_finite(v) for v in obj]
    return obj


def _frame(rec):
    """One length-prefixed, CRC-checksummed line (the journal's wire
    format, duplicated here so this module stays standalone-loadable
    — serving_fleet.journal imports jax-adjacent packages)."""
    try:
        payload = json.dumps(rec, separators=(",", ":"),
                             allow_nan=False)
    except ValueError:
        payload = json.dumps(_finite(rec), separators=(",", ":"),
                             allow_nan=False)
    raw = payload.encode("utf-8")
    crc = zlib.crc32(raw) & 0xFFFFFFFF
    return b"%08x %08x " % (len(raw), crc) + raw + b"\n"


def _parse_line(line):
    """Record dict for one frame line, or None when torn/corrupt."""
    if len(line) < 19 or line[8:9] != b" " or line[17:18] != b" ":
        return None
    try:
        n = int(line[:8], 16)
        crc = int(line[9:17], 16)
    except ValueError:
        return None
    raw = line[18:]
    if len(raw) != n or (zlib.crc32(raw) & 0xFFFFFFFF) != crc:
        return None
    try:
        rec = json.loads(raw.decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        return None
    return rec if isinstance(rec, dict) else None


class _Series:
    """One metric series' history across every resolution.

    Sample shapes (compact lists, JSON-ready):
      counter:   [ts, value]                      (value cumulative)
      gauge:     [ts, last, min, max]
      histogram: [ts, count, sum, min, max, [cumulative bucket counts]]
    Downsampled rungs keep the LAST cumulative sample per bucket for
    counters/histograms (cumulative series need no averaging) and
    last/min/max for gauges.
    """

    __slots__ = ("key", "name", "labels", "mtype", "bounds", "rings")

    def __init__(self, key, name, labels, mtype, bounds, raw_samples,
                 rungs):
        self.key = key
        self.name = name
        self.labels = dict(labels or {})
        self.mtype = mtype
        self.bounds = None if bounds is None else tuple(bounds)
        self.rings = {"raw": deque(maxlen=int(raw_samples))}
        for sec, keep in rungs:
            self.rings[f"{sec:g}s"] = deque(maxlen=int(keep))

    def sample_of(self, ts, entry):
        if self.mtype == "counter":
            return [ts, entry["value"]]
        if self.mtype == "gauge":
            v = entry["value"]
            return [ts, v, v, v]
        return [ts, entry["count"], entry["sum"], entry.get("min"),
                entry.get("max"), list(entry["counts"])]

    def append(self, ts, entry, rungs):
        s = self.sample_of(ts, entry)
        self.rings["raw"].append(s)
        for sec, _keep in rungs:
            ring = self.rings[f"{sec:g}s"]
            # bucket identity by floor(ts/sec); the SAMPLE keeps the
            # real last-update timestamp, so a cumulative value is
            # always "as of its own ts" — a bucket-start stamp would
            # let a coarse sample smuggle future increments behind a
            # past timestamp and poison window deltas / --at reads
            tb = math.floor(ts / sec)
            if ring and math.floor(ring[-1][0] / sec) == tb:
                if self.mtype == "gauge":
                    last = ring[-1]
                    ring[-1] = [ts, s[1],
                                min(last[2], s[2]), max(last[3], s[3])]
                else:
                    ring[-1] = list(s)
            else:
                ring.append(list(s))


class HistoryStore:
    """Bounded TSDB over one MetricsRegistry.

    registry: the registry to scrape (None = attach later / load-only
        stores; scrape() then requires one passed explicitly).
    interval_s: ``maybe_scrape`` cadence (the raw ring's grain).
    raw_samples: raw ring bound per series.
    rungs: ((bucket_seconds, retained_samples), ...) downsampling
        ladder (DEFAULT_RUNGS: 10s and 60s).
    max_series: series-cardinality bound — beyond it NEW series are
        dropped (counted in ``dropped_series``), never existing rings.
    """

    def __init__(self, registry=None, *, interval_s=1.0,
                 raw_samples=600, rungs=DEFAULT_RUNGS, max_series=512):
        self.registry = registry
        self.interval_s = float(interval_s)
        self.raw_samples = int(raw_samples)
        self.rungs = tuple((float(s), int(k)) for s, k in rungs)
        self.max_series = int(max_series)
        self._series = {}
        self._lock = threading.Lock()
        self._last_scrape = 0.0
        self._thread = None
        self._stop = threading.Event()
        self.scrapes = 0
        self.dropped_series = 0
        self.load_dropped = 0

    # -- scraping ----------------------------------------------------------

    def scrape(self, now=None, registry=None):
        """Fold one registry snapshot into the rings. ``now`` is epoch
        seconds (tests pass explicit values for determinism)."""
        reg = registry if registry is not None else self.registry
        if reg is None:
            raise ValueError("HistoryStore has no registry to scrape")
        ts = time.time() if now is None else float(now)
        snap = reg.snapshot()
        with self._lock:
            for key, entry in snap["metrics"].items():
                ser = self._series.get(key)
                if ser is None:
                    if len(self._series) >= self.max_series:
                        self.dropped_series += 1
                        continue
                    ser = _Series(key, entry["name"], entry["labels"],
                                  entry["type"], entry.get("bounds"),
                                  self.raw_samples, self.rungs)
                    self._series[key] = ser
                ser.append(ts, entry, self.rungs)
            self.scrapes += 1
            self._last_scrape = ts
        return ts

    def maybe_scrape(self, now=None):
        """scrape() iff ``interval_s`` elapsed since the last one;
        returns the scrape ts or None. The pull-shaped attach point a
        control loop (FleetRouter.step) drives."""
        ts = time.time() if now is None else float(now)
        if ts - self._last_scrape < self.interval_s:
            return None
        return self.scrape(now=ts)

    def start(self):
        """Optional background scraper (daemon thread) for hosts with
        no control loop to ride. stop() (or close()) ends it."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.scrape()
                except Exception:  # noqa: BLE001 — a scrape must never
                    pass           # kill the scraper thread

        self._thread = threading.Thread(
            target=loop, daemon=True, name="paddle-tpu-history")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=2.0)
        self._thread = None

    close = stop

    # -- reading -----------------------------------------------------------

    def keys(self):
        with self._lock:
            return sorted(self._series)

    def index(self):
        """Per-series catalogue rows (the /history endpoint's index)."""
        out = []
        with self._lock:
            for key, ser in sorted(self._series.items()):
                # first/last across EVERY ring: the rungs remember
                # further back than the raw ring — relative --at/--vs
                # offsets anchor on the archive's true reach
                firsts = [r[0][0] for r in ser.rings.values() if r]
                lasts = [r[-1][0] for r in ser.rings.values() if r]
                out.append({
                    "key": key, "name": ser.name,
                    "labels": dict(ser.labels), "type": ser.mtype,
                    "resolutions": {
                        res: len(ring)
                        for res, ring in ser.rings.items()},
                    "first_ts": min(firsts) if firsts else None,
                    "last_ts": max(lasts) if lasts else None})
        return out

    def query(self, key, t0=None, t1=None, res="raw", limit=None):
        """Samples of one series in [t0, t1] at a resolution, oldest
        first. Histograms omit their bucket vectors here (big); use
        quantile_over_time / registry_snapshot_at for bucket math."""
        with self._lock:
            ser = self._series.get(key)
            if ser is None:
                return []
            ring = ser.rings.get(res)
            if ring is None:
                return []
            rows = [s for s in ring
                    if (t0 is None or s[0] >= t0)
                    and (t1 is None or s[0] <= t1)]
        if limit is not None:
            rows = rows[-int(limit):]
        out = []
        for s in rows:
            if ser.mtype == "counter":
                out.append({"t": s[0], "v": s[1]})
            elif ser.mtype == "gauge":
                out.append({"t": s[0], "v": s[1], "min": s[2],
                            "max": s[3]})
            else:
                out.append({"t": s[0], "count": s[1], "sum": s[2],
                            "min": s[3], "max": s[4]})
        return out

    def _window_samples(self, key, t0, t1):
        """Samples covering [t0, t1]: raw where it reaches, coarser
        rungs ONLY for the part of the window before the finer ring's
        earliest sample (the ladder's whole point — and the finer
        data must win where both exist, or a coarse bucket's single
        end-of-bucket sample would flatten the deltas raw can see).
        Returned oldest-first, plus one anchor just before t0."""
        ser = self._series.get(key)
        if ser is None:
            return None, []
        picked = {}
        anchor = None   # latest sample strictly before the window —
        #                 ONE anchor only, or the delta walk would
        #                 count increase that happened before t0
        reach = None    # earliest instant finer resolutions cover
        for res in ["raw"] + [f"{sec:g}s" for sec, _ in
                              sorted(self.rungs)]:
            ring = ser.rings.get(res)
            if not ring:
                continue
            hi = t1 if reach is None else min(reach, t1)
            for s in ring:
                if t0 <= s[0] < hi or (reach is None
                                       and s[0] == hi):
                    picked.setdefault(s[0], s)
                elif s[0] < t0 and (anchor is None
                                    or s[0] > anchor[0]):
                    anchor = s
            reach = ring[0][0] if reach is None \
                else min(reach, ring[0][0])
        if anchor is not None:
            picked.setdefault(anchor[0], anchor)
        return ser, [picked[t] for t in sorted(picked)]

    def increase(self, key, t0, t1):
        """Monotonic increase of a counter (or histogram count) over
        [t0, t1] — sum of positive deltas, so a counter reset (process
        restart) never reads as a negative rate."""
        with self._lock:
            ser, rows = self._window_samples(key, t0, t1)
            if ser is None or len(rows) < 2:
                return None
            vals = [s[1] for s in rows]
        inc = 0
        for a, b in zip(vals, vals[1:]):
            if b > a:
                inc += b - a
        return inc

    def rate(self, key, window_s, now=None):
        """Per-second increase over the trailing window (None when
        the series is unknown or has < 2 samples in reach)."""
        t1 = (self._last_scrape if now is None else float(now))
        inc = self.increase(key, t1 - float(window_s), t1)
        if inc is None:
            return None
        return inc / float(window_s)

    def quantile_over_time(self, key, q, window_s, now=None):
        """Interpolated quantile of a histogram's observations that
        landed INSIDE the trailing window, from the cumulative bucket
        counts at the window edges. None when the series is not a
        histogram, out of reach, or saw no events in the window."""
        t1 = (self._last_scrape if now is None else float(now))
        t0 = t1 - float(window_s)
        with self._lock:
            ser, rows = self._window_samples(key, t0, t1)
            if ser is None or ser.mtype != "histogram" \
                    or ser.bounds is None or len(rows) < 2:
                return None
            first, last = rows[0], rows[-1]
            delta = [b - a for a, b in zip(first[5], last[5])]
            lo_all = last[3]
            hi_all = last[4]
        total = sum(d for d in delta if d > 0)
        if total <= 0:
            return None
        target = float(q) * total
        cum = 0
        bounds = ser.bounds
        for i, c in enumerate(delta):
            if c <= 0:
                continue
            lo = bounds[i - 1] if i > 0 else (
                lo_all if lo_all is not None else 0.0)
            hi = bounds[i] if i < len(bounds) else (
                hi_all if hi_all is not None else bounds[-1])
            lo = min(lo, hi)
            if cum + c >= target:
                frac = (target - cum) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            cum += c
        return hi_all

    def value_at(self, key, t):
        """The series' sample at-or-before epoch ``t`` (finest
        resolution that has one), or None."""
        with self._lock:
            ser = self._series.get(key)
            if ser is None:
                return None
            for res in ["raw"] + [f"{sec:g}s" for sec, _ in
                                  sorted(self.rungs)]:
                ring = ser.rings.get(res)
                if not ring:
                    continue
                at = [s for s in ring if s[0] <= t]
                if at:
                    return ser, at[-1]
        return None

    def registry_snapshot_at(self, t):
        """Reconstruct a ``MetricsRegistry.snapshot()``-shaped doc as
        of epoch ``t`` — the input ``tools/metrics_diff.py --at/--vs``
        feeds to its differ, so one history archive supports the
        canary gate at any two points in time. Series with no sample
        at-or-before ``t`` are omitted (they did not exist yet)."""
        metrics = {}
        for key in self.keys():
            hit = self.value_at(key, t)
            if hit is None:
                continue
            ser, s = hit
            base = {"name": ser.name, "labels": dict(ser.labels),
                    "type": ser.mtype}
            if ser.mtype == "counter":
                base["value"] = s[1]
            elif ser.mtype == "gauge":
                base["value"] = s[1]
            else:
                base.update(bounds=list(ser.bounds or ()),
                            counts=list(s[5]), count=s[1], sum=s[2],
                            min=s[3], max=s[4])
            metrics[key] = base
        return {"ts": float(t), "metrics": metrics}

    def span(self):
        """(first_ts, last_ts) across every series (None, None when
        empty) — what relative --at/--vs offsets anchor to."""
        first = last = None
        for row in self.index():
            if row["first_ts"] is not None:
                first = row["first_ts"] if first is None \
                    else min(first, row["first_ts"])
            if row["last_ts"] is not None:
                last = row["last_ts"] if last is None \
                    else max(last, row["last_ts"])
        return first, last

    # -- persistence (journal framing + io/atomic rename) ------------------

    def save(self, path):
        """Snapshot every ring to ``path``: checksummed JSONL lines
        (header first, then one line per series-resolution chunk)
        through the shared write-then-rename discipline. A reader of a
        PARTIAL copy (crash mid-replace is impossible, but operators
        truncate, disks lie) drops at most the tail."""
        lines = [_frame({"kind": "history_header", "format": _FORMAT,
                         "saved_ts": round(time.time(), 6),
                         "interval_s": self.interval_s,
                         "raw_samples": self.raw_samples,
                         "rungs": [list(r) for r in self.rungs]})]
        with self._lock:
            for key, ser in sorted(self._series.items()):
                for res, ring in ser.rings.items():
                    if not ring:
                        continue
                    lines.append(_frame({
                        "kind": "series", "key": key,
                        "name": ser.name, "labels": ser.labels,
                        "mtype": ser.mtype,
                        "bounds": None if ser.bounds is None
                        else list(ser.bounds),
                        "res": res, "samples": [list(s) for s in ring]}))
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        _atomic().atomic_replace(path, b"".join(lines))
        return path

    @classmethod
    def load(cls, path):
        """Rebuild a store from a snapshot. Torn/corrupt lines are
        dropped and counted (``load_dropped``) — never raised on, and
        a line that survives its checksum is applied exactly once, so
        truncation at any byte offset costs at most the tail."""
        store = cls(registry=None)
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            return store
        seen = set()
        for line in data.split(b"\n"):
            if not line:
                continue
            rec = _parse_line(line)
            if rec is None:
                store.load_dropped += 1
                continue
            kind = rec.get("kind")
            if kind == "history_header":
                store.interval_s = float(rec.get("interval_s", 1.0))
                store.raw_samples = int(rec.get("raw_samples", 600))
                store.rungs = tuple(
                    (float(s), int(k))
                    for s, k in rec.get("rungs") or DEFAULT_RUNGS)
            elif kind == "series":
                key, res = rec.get("key"), rec.get("res")
                if key is None or res is None or (key, res) in seen:
                    continue   # a duplicated chunk never duplicates
                seen.add((key, res))
                ser = store._series.get(key)
                if ser is None:
                    ser = _Series(key, rec.get("name", key),
                                  rec.get("labels"), rec.get("mtype"),
                                  rec.get("bounds"),
                                  store.raw_samples, store.rungs)
                    store._series[key] = ser
                ring = ser.rings.get(res)
                if ring is None:
                    continue   # rung retired between save and load
                for s in rec.get("samples") or []:
                    ring.append(list(s))
                if ring:
                    store._last_scrape = max(store._last_scrape,
                                             ring[-1][0])
        return store
