"""Process/env management (ref: python/paddle/distributed/parallel.py).

Single-controller JAX model: one Python process per host drives all local
chips; `rank` maps to jax.process_index() (multi-host) and world size to
process_count — NOT one process per device like the reference's NCCL
launcher. Collectives tests emulate N ranks with a virtual CPU mesh.
"""
from __future__ import annotations

import os

import jax

_initialized = False


def init_parallel_env():
    """ref: paddle.distributed.init_parallel_env. Multi-host initialization
    (jax.distributed) happens via launch(); single-host this is a no-op."""
    global _initialized
    coord = os.environ.get("PADDLE_TPU_COORDINATOR")
    if coord and not _initialized:
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=int(os.environ.get("PADDLE_TPU_NUM_PROCESSES", "1")),
            process_id=int(os.environ.get("PADDLE_TPU_PROCESS_ID", "0")))
    _initialized = True
    return ParallelEnv()


def is_initialized():
    return _initialized


def get_rank(group=None):
    return jax.process_index()


def get_world_size(group=None):
    return jax.process_count()


class ParallelEnv:
    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return 0

    @property
    def dev_id(self):
        return 0

    @property
    def nranks(self):
        return get_world_size()

    @property
    def local_rank(self):
        return get_rank()
