"""Tensor sharding placement API (ref: paddle.distributed.shard_tensor /
dtensor-style Placements in python/paddle/distributed/auto_parallel).

Maps 1:1 onto jax NamedSharding: Shard(d) -> PartitionSpec entry at dim d,
Replicate() -> None. Because jax arrays are global-view (like the
reference's dist_tensor with global shape), shard_tensor is just a
device_put with a NamedSharding.
"""
from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..tensor import Tensor
from .mesh import DeviceMesh, get_mesh


class Placement:
    pass


class Shard(Placement):
    def __init__(self, dim):
        self.dim = dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, o):
        return isinstance(o, Shard) and o.dim == self.dim


class Replicate(Placement):
    def __repr__(self):
        return "Replicate()"

    def __eq__(self, o):
        return isinstance(o, Replicate)


class Partial(Placement):
    """Pending-reduction placement; materialised as replicate after psum."""

    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type

    def __eq__(self, o):
        return isinstance(o, Partial)


def _placements_to_spec(ndim, mesh, placements):
    spec = [None] * ndim
    for axis_name, p in zip(mesh.axis_names, placements):
        if isinstance(p, Shard):
            if spec[p.dim] is None:
                spec[p.dim] = axis_name
            elif isinstance(spec[p.dim], tuple):
                spec[p.dim] = spec[p.dim] + (axis_name,)
            else:
                spec[p.dim] = (spec[p.dim], axis_name)
    return PartitionSpec(*spec)


def shard_tensor(data, mesh=None, placements=None, dtype=None,
                 stop_gradient=None):
    """ref: paddle.distributed.shard_tensor(data, mesh, placements)."""
    t = data if isinstance(data, Tensor) else Tensor(data)
    m = mesh.mesh if isinstance(mesh, DeviceMesh) else (mesh or get_mesh())
    placements = placements or [Replicate()] * len(m.axis_names)
    spec = _placements_to_spec(t._value.ndim, m, placements)
    sharding = NamedSharding(m, spec)
    out = Tensor(jax.device_put(t._value, sharding),
                 stop_gradient=t.stop_gradient if stop_gradient is None
                 else stop_gradient)
    return out


def reshard(x, mesh=None, placements=None):
    return shard_tensor(x, mesh, placements)


def shard_layer(layer, process_mesh, shard_fn=None, input_fn=None,
                output_fn=None):
    """ref: paddle.distributed.shard_layer — places every parameter of the
    layer onto the mesh (replicated unless shard_fn says otherwise)."""
    m = process_mesh.mesh if isinstance(process_mesh, DeviceMesh) else process_mesh
    for name, sub in layer.named_sublayers(include_self=True):
        if shard_fn is not None:
            shard_fn(name, sub, process_mesh)
        else:
            for pname, p in sub._parameters.items():
                if p is None:
                    continue
                sharding = NamedSharding(m, PartitionSpec())
                p._value = jax.device_put(p._value, sharding)
    return layer


def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    return shard_tensor(fn(*args, **kwargs), mesh, placements)
