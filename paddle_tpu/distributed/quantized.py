"""Quantized (8-bit) collectives — EQuARX-style gradient all-reduce.

ref: the reference's DistributedStrategy fp16/bf16 allreduce + the
EQuARX paper's int8 scheme (SURVEY §6 perf levers: "8-bit-collective
option"). Wire bytes are the scaling bottleneck once ICI is saturated:
an fp32 ring all-reduce moves 2·N·4 bytes per device; this moves
2·N·1 (+ scales), a ~4x cut, in exchange for bounded quantization error
on the gradient sync.

TPU-native shape: there is no NCCL hook to patch — the collective IS a
program op. `quantized_all_reduce` is written for use inside
`shard_map` over the dp axis (where our pipeline/tp kernels already
live), lowering to `all_to_all`/`all_gather` on int8 payloads that XLA
puts on ICI:

  stage 1 (reduce-scatter): quantize the local vector per rank-chunk
     (int8, per-block absmax scales), all_to_all so rank i holds every
     rank's chunk i, dequantize, sum -> rank i owns the reduced chunk i
     in full precision.
  stage 2 (gather): re-quantize the reduced chunk, all_gather, dequant.

Two quantization passes => error ~2 ulp(int8-block) — measured <1%
relative on gradient-like data across 8 ranks (tests); exact on integer-valued data
within the int8 range. Callers wanting bit-exact training keep the
default fp path; this is opt-in, like the reference's strategy flag.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["quantized_all_reduce", "quantize_int8_blockwise",
           "dequantize_int8_blockwise"]


def quantize_int8_blockwise(x, block=256):
    """[..., m] -> (int8 [..., m], f32 scales [..., m/block]).
    Per-block absmax scaling; m must divide by `block`."""
    lead = x.shape[:-1]
    m = x.shape[-1]
    xb = x.reshape(lead + (m // block, block)).astype(jnp.float32)
    amax = jnp.max(jnp.abs(xb), axis=-1, keepdims=True)
    scale = jnp.where(amax == 0, 1.0, amax / 127.0)
    q = jnp.clip(jnp.round(xb / scale), -127, 127).astype(jnp.int8)
    return q.reshape(lead + (m,)), scale.squeeze(-1)


def dequantize_int8_blockwise(q, scale, block=256):
    lead = q.shape[:-1]
    m = q.shape[-1]
    qb = q.reshape(lead + (m // block, block)).astype(jnp.float32)
    return (qb * scale[..., None]).reshape(lead + (m,))


def quantized_all_reduce(x, axis_name, block=256):
    """All-reduce (sum) over `axis_name` with int8 wire format.

    Must run inside shard_map/pjit where `axis_name` is bound. Returns
    the summed array in x's dtype. Payload on the interconnect is int8
    plus one f32 scale per `block` elements (~x4 less than fp32).
    """
    if hasattr(jax.lax, "axis_size"):
        n = jax.lax.axis_size(axis_name)
    else:  # jax 0.4.x has no lax.axis_size — psum of 1 is the idiom
        n = int(jax.lax.psum(1, axis_name))
    orig_dtype = x.dtype
    shape = x.shape
    flat = x.reshape(-1).astype(jnp.float32)
    # pad so the vector splits into n rank-chunks of block-multiples
    unit = n * block
    pad = (-flat.size) % unit
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    chunks = flat.reshape(n, -1)                       # [n, m]
    q, s = quantize_int8_blockwise(chunks, block)      # [n, m], [n, m/b]
    # stage 1: all_to_all -> row j becomes rank j's version of MY chunk
    qt = jax.lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0,
                            tiled=False)
    st = jax.lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0,
                            tiled=False)
    partial = jnp.sum(dequantize_int8_blockwise(qt, st, block), axis=0)
    # stage 2: re-quantize the reduced chunk and gather all chunks
    q2, s2 = quantize_int8_blockwise(partial, block)   # [m], [m/b]
    qg = jax.lax.all_gather(q2, axis_name, axis=0)     # [n, m]
    sg = jax.lax.all_gather(s2, axis_name, axis=0)
    out = dequantize_int8_blockwise(qg, sg, block).reshape(-1)
    if pad:
        out = out[:-pad]
    return out.reshape(shape).astype(orig_dtype)
