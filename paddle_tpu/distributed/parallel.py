"""DataParallel wrapper (ref: python/paddle/distributed/parallel.py).

On the reference, DataParallel registers allreduce hooks per grad bucket.
TPU-native: data parallelism is a sharding, not a wrapper — Engine shards
the batch over the 'dp' mesh axis and XLA psums grads. This class keeps
script parity (model = paddle.DataParallel(model)) and marks the layer so
Engine knows the intent.
"""
from __future__ import annotations

from ..nn.layer import Layer


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers  # registered as sublayer via __setattr__
        self.find_unused_parameters = find_unused_parameters

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass

    @property
    def _inner(self):
        return self._layers
