"""Sharding-consistency validator (SURVEY §2.11).

The reference ships a race detector for its multi-stream CUDA runtime;
XLA's single-dispatch model has no data races, so the failure mode that
replaces it is a WRONG SHARDING: a spec that names a missing mesh axis, a
dim not divisible by its axis, or two pytrees (params vs opt state) whose
placements silently diverge. This module asserts those invariants before
they become cryptic XLA errors three layers deep.
"""
from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["validate_spec", "validate_tree", "validate_model",
           "assert_same_placement", "ShardingError"]


class ShardingError(ValueError):
    pass


def _axes_of(entry):
    if entry is None:
        return ()
    return (entry,) if isinstance(entry, str) else tuple(entry)


def validate_spec(shape, spec, mesh: Mesh, name="<array>"):
    """Check one PartitionSpec against an array shape and a mesh: every
    named axis exists, no axis is used twice, every sharded dim divides
    evenly (XLA would pad; the reference's mpu asserts the same)."""
    if spec is None:
        return
    entries = tuple(spec)
    if len(entries) > len(shape):
        raise ShardingError(
            f"{name}: spec {spec} has more entries than rank {len(shape)}")
    seen = set()
    for d, entry in enumerate(entries):
        for ax in _axes_of(entry):
            if ax not in mesh.axis_names:
                raise ShardingError(
                    f"{name}: spec {spec} names axis {ax!r} but mesh has "
                    f"{tuple(mesh.axis_names)}")
            if ax in seen:
                raise ShardingError(
                    f"{name}: axis {ax!r} appears twice in {spec}")
            seen.add(ax)
            size = mesh.shape[ax]
            if shape[d] % size != 0:
                raise ShardingError(
                    f"{name}: dim {d} (={shape[d]}) not divisible by mesh "
                    f"axis {ax!r} (={size}) in spec {spec}")


def _placed_spec(x):
    sh = getattr(x, "sharding", None)
    if isinstance(sh, NamedSharding):
        return sh.spec
    return None


def validate_tree(tree, mesh: Mesh, specs=None):
    """Validate every array leaf of a pytree. specs: optional matching
    pytree of PartitionSpecs (e.g. from shard_model); defaults to each
    leaf's actual placement."""
    leaves = jax.tree_util.tree_leaves_with_path(tree)
    # None is a valid (replicated) spec entry, not an empty subtree
    spec_leaves = (jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: x is None or isinstance(x, P))
        if specs is not None else [None] * len(leaves))
    if specs is not None and len(spec_leaves) != len(leaves):
        raise ShardingError(
            f"specs tree has {len(spec_leaves)} leaves, data tree has "
            f"{len(leaves)}")
    for (path, leaf), spec in zip(leaves, spec_leaves):
        if not hasattr(leaf, "shape"):
            continue
        spec = spec if spec is not None else _placed_spec(leaf)
        validate_spec(leaf.shape, spec, mesh,
                      name=jax.tree_util.keystr(path))
    return True


def validate_model(model, mesh: Mesh):
    """Validate every parameter's sharding_spec (mpu convention) against
    the mesh — run after shard_model, before the first step."""
    for n, p in model.named_parameters():
        spec = getattr(p, "sharding_spec", None)
        validate_spec(tuple(p.shape), spec, mesh, name=n)
    return True


def assert_same_placement(a, b, names=("a", "b")):
    """Two same-structure pytrees (e.g. params vs their Adam moments) must
    shard identically, or GSPMD inserts silent resharding every step."""
    la = jax.tree_util.tree_leaves_with_path(a)
    lb = jax.tree_util.tree_leaves(b)
    if len(la) != len(lb):
        raise ShardingError(
            f"{names[0]} has {len(la)} leaves, {names[1]} has {len(lb)}")
    for (path, xa), xb in zip(la, lb):
        sa, sb = _placed_spec(xa), _placed_spec(xb)
        if (sa or P()) != (sb or P()):
            raise ShardingError(
                f"placement mismatch at {jax.tree_util.keystr(path)}: "
                f"{names[0]}={sa} vs {names[1]}={sb}")
    return True
