"""ref: paddle.distributed.sharding — GroupSharded (ZeRO) public API."""
from .fleet.sharding import (  # noqa: F401
    group_sharded_parallel, save_group_sharded_model, GroupShardedConfig,
)
