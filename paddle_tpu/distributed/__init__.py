"""paddle_tpu.distributed (ref: python/paddle/distributed/*).

The reference's distributed stack is NCCL/Gloo process groups driven by
c_allreduce/c_broadcast ops. TPU-native design: ONE jax.sharding.Mesh per
process describes the whole chip topology; parallelism is expressed as
NamedSharding placements + shard_map programs, and XLA inserts the ICI
collectives. The `collective` module exposes the reference's eager
collective API (all_reduce, all_gather, ...) implemented over shard_map for
script parity and tests.
"""
from .env import (  # noqa: F401
    get_rank, get_world_size, init_parallel_env, is_initialized, ParallelEnv,
)
from .mesh import (  # noqa: F401
    DeviceMesh, get_mesh, set_mesh, ProcessMesh,
)
from .collective import (  # noqa: F401
    all_gather, all_reduce, alltoall, alltoall_single, barrier, broadcast,
    new_group, recv, reduce, reduce_scatter, scatter, send, split_group,
    ReduceOp, wait,
)
from .sharding_api import (  # noqa: F401
    shard_tensor, shard_layer, Shard, Replicate, Partial, reshard,
)
from . import fleet  # noqa: F401
from . import sharding  # noqa: F401
from . import validate  # noqa: F401
from . import auto_parallel  # noqa: F401
from .parallel import DataParallel  # noqa: F401
from .launch_mod import launch, spawn  # noqa: F401
