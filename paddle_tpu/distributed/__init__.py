"""paddle_tpu.distributed (ref: python/paddle/distributed/*).

The reference's distributed stack is NCCL/Gloo process groups driven by
c_allreduce/c_broadcast ops. TPU-native design: ONE jax.sharding.Mesh per
process describes the whole chip topology; parallelism is expressed as
NamedSharding placements + shard_map programs, and XLA inserts the ICI
collectives. The `collective` module exposes the reference's eager
collective API (all_reduce, all_gather, ...) implemented over shard_map for
script parity and tests.
"""
from .env import (  # noqa: F401
    get_rank, get_world_size, init_parallel_env, is_initialized, ParallelEnv,
)
from .mesh import (  # noqa: F401
    DeviceMesh, get_mesh, set_mesh, ProcessMesh,
)
from .collective import (  # noqa: F401
    all_gather, all_gather_object, all_reduce, broadcast_object_list, alltoall, alltoall_single,
    barrier, broadcast, new_group, recv, reduce, reduce_scatter, scatter,
    send, split_group, ReduceOp, wait,
)
from .sharding_api import (  # noqa: F401
    shard_tensor, shard_layer, Shard, Replicate, Partial, reshard,
)
from . import fleet  # noqa: F401
from . import sharding  # noqa: F401
from . import validate  # noqa: F401
from . import auto_parallel  # noqa: F401
from .parallel import DataParallel  # noqa: F401
from .launch_mod import launch, spawn  # noqa: F401
from .quantized import quantized_all_reduce  # noqa: F401
from . import quantized  # noqa: F401


def get_group(id=0):
    """ref: paddle.distributed.get_group — the mesh IS the group here;
    returns a lightweight view of the global device set."""
    import jax

    class _Group:
        def __init__(self):
            self.ranks = list(range(jax.device_count()))
            self.nranks = jax.device_count()
            self.rank = get_rank()
            self.id = id

        def __repr__(self):
            return f"Group(id={self.id}, nranks={self.nranks})"
    return _Group()


def destroy_process_group(group=None):
    """ref: paddle.distributed.destroy_process_group — XLA collectives
    are compiled into programs, not a live process group; nothing to tear
    down (jax.distributed.shutdown exists for multi-host)."""
    return None


class rpc:
    """paddle.distributed.rpc gate: RPC-based parameter-server training is
    a CPU-cluster pattern the reference supports; on TPU pods the
    equivalent scale-out is SPMD over the Mesh (see docs/distributed.md).
    Every entry point raises with that pointer."""

    @staticmethod
    def _gate(*a, **k):
        raise NotImplementedError(
            "paddle.distributed.rpc (parameter-server RPC) is not part of "
            "the TPU design: scale out with jax.sharding.Mesh + GSPMD "
            "(docs/distributed.md). For multi-host control-plane needs use "
            "jax.distributed.initialize / paddle_tpu.distributed.launch.")

    init_rpc = _gate
    rpc_sync = _gate
    rpc_async = _gate
    shutdown = _gate


class stream:
    """ref: paddle.distributed.stream.* — stream-bound collectives.

    XLA's async dispatch IS the stream: collectives are compiled into the
    program and overlap automatically, so these alias the sync API
    (group and op forward through unchanged)."""

    @staticmethod
    def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True,
                   use_calc_stream=False):
        return all_reduce(tensor, op=op, group=group)

    @staticmethod
    def all_gather(tensor_list, tensor, group=None, sync_op=True,
                   use_calc_stream=False):
        return all_gather(tensor_list, tensor, group=group)

    @staticmethod
    def broadcast(tensor, src=0, group=None, sync_op=True,
                  use_calc_stream=False):
        return broadcast(tensor, src=src, group=group)
