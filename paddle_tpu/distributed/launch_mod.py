"""Multi-host launch (ref: python/paddle/distributed/launch).

The reference spawns one worker per GPU and wires them up over env vars
(PADDLE_MASTER / PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ID). JAX is
single-controller per host: launch() initializes jax.distributed across
hosts from env vars — ours (PADDLE_TPU_COORDINATOR / _NUM_PROCESSES /
_PROCESS_ID), the reference's names for drop-in script parity, or TPU
pod metadata auto-detection — then runs the training function once per
host.
"""
from __future__ import annotations

import os


def parse_env(environ=None):
    """Resolve the multi-host wiring from environment variables.

    Returns a dict:
      mode: 'explicit' (coordinator given) | 'tpu_pod' (pod metadata,
            jax auto-detects) | 'single' (no distributed env)
      coordinator_address / num_processes / process_id for 'explicit'.

    Precedence: PADDLE_TPU_* (ours) > PADDLE_* (reference parity:
    PADDLE_MASTER, PADDLE_TRAINERS_NUM, PADDLE_TRAINER_ID) > TPU pod
    metadata (TPU_WORKER_HOSTNAMES / MEGASCALE_COORDINATOR_ADDRESS).
    ref: python/paddle/distributed/launch/context/node.py env wiring.
    """
    env = os.environ if environ is None else environ
    # family precedence is WHOLESALE: mixing coordinator from one launcher
    # with world-size from another (stale exports) would hang initialize()
    # waiting for peers that never come
    if env.get("PADDLE_TPU_COORDINATOR"):
        coord = env["PADDLE_TPU_COORDINATOR"]
        num = env.get("PADDLE_TPU_NUM_PROCESSES", "1")
        pid = env.get("PADDLE_TPU_PROCESS_ID", "0")
    else:
        coord = env.get("PADDLE_MASTER")
        num = env.get("PADDLE_TRAINERS_NUM", "1")
        pid = env.get("PADDLE_TRAINER_ID", "0")
    if coord:
        try:
            num_i, pid_i = int(num), int(pid)
        except ValueError as e:
            raise ValueError(
                f"malformed distributed env: num_processes={num!r} "
                f"process_id={pid!r} (must be integers)") from e
        if not 0 <= pid_i < num_i:
            raise ValueError(
                f"process_id {pid_i} out of range for num_processes "
                f"{num_i} (PADDLE_TRAINER_ID must be 0-based)")
        return {"mode": "explicit", "coordinator_address": coord,
                "num_processes": num_i, "process_id": pid_i}
    if env.get("TPU_WORKER_HOSTNAMES") or \
            env.get("MEGASCALE_COORDINATOR_ADDRESS"):
        return {"mode": "tpu_pod"}
    return {"mode": "single"}


def launch(fn=None, args=()):
    """Initialize jax.distributed per parse_env(), then run `fn` once on
    this host (single-controller: the mesh covers every local device)."""
    import jax

    cfg = parse_env()
    if cfg["mode"] == "explicit":
        jax.distributed.initialize(
            coordinator_address=cfg["coordinator_address"],
            num_processes=cfg["num_processes"],
            process_id=cfg["process_id"])
    elif cfg["mode"] == "tpu_pod":
        jax.distributed.initialize()  # auto-detect from pod metadata
    if fn is not None:
        return fn(*args)


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """ref: paddle.distributed.spawn. Single-controller: run once; the mesh
    covers all local devices, so there is nothing to fork."""
    return func(*args)
