"""Multi-host launch (ref: python/paddle/distributed/launch).

The reference spawns one worker per GPU. JAX is single-controller per host:
launch() initializes jax.distributed across hosts from env vars
(PADDLE_TPU_COORDINATOR / _NUM_PROCESSES / _PROCESS_ID or TPU pod metadata)
then runs the training function once per host.
"""
from __future__ import annotations

import os

import jax


def launch(fn=None, args=()):
    coord = os.environ.get("PADDLE_TPU_COORDINATOR")
    if coord:
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=int(os.environ.get("PADDLE_TPU_NUM_PROCESSES", "1")),
            process_id=int(os.environ.get("PADDLE_TPU_PROCESS_ID", "0")))
    elif os.environ.get("TPU_WORKER_HOSTNAMES"):
        jax.distributed.initialize()  # auto-detect on TPU pods
    if fn is not None:
        return fn(*args)


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """ref: paddle.distributed.spawn. Single-controller: run once; the mesh
    covers all local devices, so there is nothing to fork."""
    return func(*args)
