"""Device mesh management.

ref: the reference's HybridCommunicateGroup topology
(python/paddle/distributed/fleet/base/topology.py) carves the NCCL world
into dp/mp/pp/sharding sub-groups. TPU-native: one jax.sharding.Mesh with
named axes; every sub-group is just an axis name. auto_parallel's
ProcessMesh maps here too.
"""
from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

_global_mesh: Mesh | None = None


def set_mesh(mesh: Mesh):
    global _global_mesh
    _global_mesh = mesh
    return mesh


def get_mesh() -> Mesh:
    global _global_mesh
    if _global_mesh is None:
        _global_mesh = Mesh(np.array(jax.devices()), ("dp",))
    return _global_mesh


def build_mesh(shape_dict) -> Mesh:
    """shape_dict: ordered {axis_name: size}; -1 means 'rest of devices'."""
    names = list(shape_dict)
    sizes = [shape_dict[n] for n in names]
    n_dev = len(jax.devices())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = n_dev // known
    total = int(np.prod(sizes))
    assert total == n_dev, f"mesh {dict(zip(names, sizes))} != {n_dev} devices"
    devs = np.array(jax.devices()).reshape(sizes)
    return Mesh(devs, tuple(names))


class DeviceMesh:
    """ref: paddle.distributed.auto_parallel ProcessMesh-alike."""

    def __init__(self, mesh_or_shape, dim_names=None):
        if isinstance(mesh_or_shape, Mesh):
            self._mesh = mesh_or_shape
        else:
            arr = np.asarray(mesh_or_shape)
            if arr.ndim == 1 and dim_names is None:
                dim_names = ("x",)
            devs = np.array(jax.devices())[arr.reshape(-1)].reshape(arr.shape)
            self._mesh = Mesh(devs, tuple(dim_names))

    @property
    def mesh(self):
        return self._mesh

    @property
    def shape(self):
        return dict(self._mesh.shape)

    @property
    def dim_names(self):
        return list(self._mesh.axis_names)

    def get_rank_by_dim_and_process_id(self, dim, pid):
        return pid

    def __enter__(self):
        self._ctx = self._mesh.__enter__()
        return self

    def __exit__(self, *a):
        return self._mesh.__exit__(*a)


ProcessMesh = DeviceMesh
