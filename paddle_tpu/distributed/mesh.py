"""Device mesh management.

ref: the reference's HybridCommunicateGroup topology
(python/paddle/distributed/fleet/base/topology.py) carves the NCCL world
into dp/mp/pp/sharding sub-groups. TPU-native: one jax.sharding.Mesh with
named axes; every sub-group is just an axis name. auto_parallel's
ProcessMesh maps here too.
"""
from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

_global_mesh: Mesh | None = None


def shard_map_compat(f, mesh, in_specs, out_specs, manual_axes=None,
                     check=False):
    """jax.shard_map across jax versions. jax >= 0.6 exposes the public
    `jax.shard_map(..., axis_names=manual, check_vma=...)`; 0.4.x only
    has `jax.experimental.shard_map.shard_map(..., auto=complement,
    check_rep=...)`. `manual_axes=None` means fully manual (all mesh
    axes); otherwise only the named axes are manual and the rest stay
    auto for GSPMD."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        kw = {"check_vma": check}
        if manual_axes is not None and len(mesh.axis_names) > 1:
            kw["axis_names"] = frozenset(manual_axes)
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  **kw)
    from jax.experimental.shard_map import shard_map as sm_old
    # 0.4.x's partial-auto shard_map is unreliable: the eager impl
    # raises NotImplementedError, and the jitted lowering emits a
    # PartitionId op the SPMD partitioner rejects (or aborts XLA
    # outright on multi-axis meshes). Lower fully manual instead —
    # semantics are preserved (axes absent from a spec replicate into
    # the body); only GSPMD sharding over the non-manual axes INSIDE
    # the mapped region is lost, and only on old-jax installs (real TPU
    # deployments run the new-jax branch above).
    return sm_old(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=check)


def pvary_compat(x, axes):
    """Mark `x` as varying over manual mesh axes. jax >= 0.6 tracks
    varying-manual-axes (VMA) types and wants an explicit
    lax.pcast/pvary; 0.4.x has neither, and with replication checking
    off (shard_map_compat passes check_rep=False) the annotation is
    simply unnecessary — identity there."""
    pc = getattr(jax.lax, "pcast", None)
    if pc is not None:
        return pc(x, tuple(axes), to="varying")
    pv = getattr(jax.lax, "pvary", None)
    if pv is not None:
        return pv(x, tuple(axes))
    return x


def set_mesh(mesh: Mesh):
    global _global_mesh
    _global_mesh = mesh
    return mesh


def get_mesh() -> Mesh:
    global _global_mesh
    if _global_mesh is None:
        _global_mesh = Mesh(np.array(jax.devices()), ("dp",))
    return _global_mesh


def build_mesh(shape_dict) -> Mesh:
    """shape_dict: ordered {axis_name: size}; -1 means 'rest of devices'."""
    names = list(shape_dict)
    sizes = [shape_dict[n] for n in names]
    n_dev = len(jax.devices())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = n_dev // known
    total = int(np.prod(sizes))
    assert total == n_dev, f"mesh {dict(zip(names, sizes))} != {n_dev} devices"
    devs = np.array(jax.devices()).reshape(sizes)
    return Mesh(devs, tuple(names))


class DeviceMesh:
    """ref: paddle.distributed.auto_parallel ProcessMesh-alike."""

    def __init__(self, mesh_or_shape, dim_names=None):
        if isinstance(mesh_or_shape, Mesh):
            self._mesh = mesh_or_shape
        else:
            arr = np.asarray(mesh_or_shape)
            if arr.ndim == 1 and dim_names is None:
                dim_names = ("x",)
            devs = np.array(jax.devices())[arr.reshape(-1)].reshape(arr.shape)
            self._mesh = Mesh(devs, tuple(dim_names))

    @property
    def mesh(self):
        return self._mesh

    @property
    def shape(self):
        return dict(self._mesh.shape)

    @property
    def dim_names(self):
        return list(self._mesh.axis_names)

    def get_rank_by_dim_and_process_id(self, dim, pid):
        return pid

    def __enter__(self):
        self._ctx = self._mesh.__enter__()
        return self

    def __exit__(self, *a):
        return self._mesh.__exit__(*a)


ProcessMesh = DeviceMesh
