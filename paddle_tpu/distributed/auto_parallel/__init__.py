"""Auto parallel (ref: python/paddle/distributed/auto_parallel/*).

The reference's auto_parallel plans a distributed program: a cost model
scores candidate shardings per op, a completion pass propagates them, and
the partitioner rewrites the graph. TPU-native split of labour:

- the *partitioner* is GSPMD — any placement we choose is mathematically
  correct, XLA inserts the collectives;
- so auto parallel here is exactly the PLANNER: pick per-parameter
  PartitionSpecs that minimise a memory+communication cost model, then
  place the params (everything downstream — Engine, eager, shard_map —
  follows placements automatically).

Planner heuristics (the same structure the reference's planner converges
to for dense nets): batch over 'dp'; consecutive Linears alternate
column/row (Megatron MLP pattern — one all-reduce per pair instead of
per layer); embeddings vocab-sharded; mpu layers keep their hand-annotated
specs; anything indivisible replicates.
"""
from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..mesh import DeviceMesh, ProcessMesh, get_mesh  # noqa: F401
from ..sharding_api import shard_tensor  # noqa: F401

__all__ = ["ShardingPlan", "plan_model", "apply_plan", "parallelize",
           "estimate_cost", "shard_op", "ProcessMesh", "shard_tensor",
           "Strategy"]


class Strategy:
    """ref: auto_parallel.Strategy — planner knobs."""

    def __init__(self, mp_axis="mp", dp_axis="dp", prefer_column_first=True,
                 min_shard_elems=1024):
        self.mp_axis = mp_axis
        self.dp_axis = dp_axis
        self.prefer_column_first = prefer_column_first
        self.min_shard_elems = min_shard_elems


class ShardingPlan(dict):
    """name -> PartitionSpec, with the cost the planner assigned."""

    cost: float = 0.0


def _divisible(dim, mesh, axis):
    return axis in mesh.axis_names and dim % mesh.shape[axis] == 0 \
        and dim >= mesh.shape[axis]


def estimate_cost(shape, spec, mesh, dtype_bytes=4):
    """Per-device bytes for a tensor under `spec` + a rough comm penalty:
    replicated tensors cost full memory; sharding the contraction dim of a
    matmul implies an all-reduce of the output (charged as output bytes).
    This is the reference cost model's memory term, simplified."""
    elems = int(np.prod(shape))
    denom = 1
    for entry in tuple(spec or ()):
        for ax in ((entry,) if isinstance(entry, str) else tuple(entry or ())):
            denom *= mesh.shape[ax]
    return elems * dtype_bytes / denom


def plan_model(model, mesh=None, strategy: Strategy = None) -> ShardingPlan:
    """Propose a PartitionSpec per parameter. Honors existing
    `sharding_spec` annotations (mpu layers are already placed the way the
    planner would)."""
    from ...nn.layers_common import Embedding, Linear

    mesh = mesh or get_mesh()
    st = strategy or Strategy()
    plan = ShardingPlan()
    column_next = st.prefer_column_first

    for lname, layer in model.named_sublayers(include_self=True):
        for pname, p in layer._parameters.items():
            if p is None:
                continue
            full = f"{lname}.{pname}" if lname else pname
            if full in plan:
                continue
            existing = getattr(p, "sharding_spec", None)
            if existing is not None:
                plan[full] = existing
                continue
            shape = tuple(p.shape)
            if int(np.prod(shape)) < st.min_shard_elems:
                plan[full] = P()
                continue
            spec = P()
            if isinstance(layer, Linear) and pname == "weight" \
                    and len(shape) == 2:
                if column_next and _divisible(shape[1], mesh, st.mp_axis):
                    spec = P(None, st.mp_axis)
                    column_next = False
                elif not column_next and _divisible(shape[0], mesh,
                                                    st.mp_axis):
                    spec = P(st.mp_axis, None)
                    column_next = True
            elif isinstance(layer, Linear) and pname == "bias":
                # matches the preceding weight: column-parallel bias shards
                w_key = f"{lname}.weight" if lname else "weight"
                w_spec = plan.get(w_key)
                if w_spec is not None and tuple(w_spec) \
                        and tuple(w_spec)[-1] == st.mp_axis:
                    spec = P(st.mp_axis)
            elif isinstance(layer, Embedding) and pname == "weight" \
                    and _divisible(shape[0], mesh, st.mp_axis):
                spec = P(st.mp_axis, None)
            plan[full] = spec
    plan.cost = sum(
        estimate_cost(tuple(p.shape), plan.get(n, P()), mesh)
        for n, p in model.named_parameters())
    return plan


def apply_plan(model, plan: ShardingPlan, mesh=None):
    """Place every parameter per the plan (device_put + record the spec so
    shard_map paths and the validator see it)."""
    mesh = mesh or get_mesh()
    from ..validate import validate_spec
    for n, p in model.named_parameters():
        spec = plan.get(n, P())
        validate_spec(tuple(p.shape), spec, mesh, name=n)
        p.sharding_spec = spec
        p._value = jax.device_put(p._value, NamedSharding(mesh, spec))
    return model


def parallelize(model, optimizer=None, mesh=None, strategy=None):
    """ref: auto_parallel's one-call entry (plan + partition). Returns
    (model, optimizer, plan)."""
    mesh = mesh or get_mesh()
    plan = plan_model(model, mesh, strategy)
    apply_plan(model, plan, mesh)
    return model, optimizer, plan


def shard_op(fn, mesh=None, in_specs=None, out_specs=None):
    """ref: auto_parallel.shard_op — constrain an op's output placement
    (GSPMD propagates the rest)."""
    mesh_ = mesh or get_mesh()

    def wrapped(*args, **kwargs):
        out = fn(*args, **kwargs)
        if out_specs is None:
            return out
        from ...tensor import Tensor

        def constrain(x, spec):
            if isinstance(x, Tensor):
                return Tensor(jax.lax.with_sharding_constraint(
                    x._value, NamedSharding(mesh_, spec)),
                    stop_gradient=x.stop_gradient)
            return jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh_, spec))
        return jax.tree_util.tree_map(
            constrain, out, out_specs,
            is_leaf=lambda t: isinstance(t, Tensor))
    return wrapped
