"""Hybrid-parallel placement helpers.

ref: the reference's fleet.meta_parallel.* (ColumnParallelLinear etc.)
allreduce activations per layer. TPU-native: parameters carry NamedShardings
and XLA GSPMD inserts the collectives — a Column/RowParallelLinear is a
Linear whose weight is sharded on the right axis.
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec

from ..nn.layer import Layer


# attribute name used to tag a parameter with its PartitionSpec
SPEC_ATTR = "_mesh_spec"


def annotate_param(param, spec: PartitionSpec):
    setattr(param, "name", param.name)  # keep slots happy
    param.optimize_attr[SPEC_ATTR] = spec
    return param


def param_spec(param) -> PartitionSpec:
    return param.optimize_attr.get(SPEC_ATTR, PartitionSpec())


def place_model_on_mesh(model: Layer, mesh):
    """device_put every param/buffer with its annotated (or replicated)
    sharding over `mesh`."""
    for _, p in model.named_parameters():
        spec = param_spec(p)
        p._value = jax.device_put(p._value, NamedSharding(mesh, spec))
    for _, b in model.named_buffers():
        b._value = jax.device_put(b._value, NamedSharding(mesh, PartitionSpec()))
    return model


def state_shardings(model: Layer, mesh):
    """name -> NamedSharding for the functional train step's in_shardings."""
    out = {}
    for n, p in model.named_parameters():
        out[n] = NamedSharding(mesh, param_spec(p))
    return out
