"""Collective communication API (ref: python/paddle/distributed/communication/*).

Design: the reference issues eager NCCL ops per rank. In single-controller
JAX there is no per-rank eager execution — collectives are *program* ops
that XLA lowers onto ICI. So:

- Inside a shard_map/pjit program (our pipeline/tensor/ring-parallel
  kernels, and anything the user writes with shard_map), these functions
  emit jax.lax collectives over the mesh axis carried by `group`.
- Eagerly, with world_size==1 (single host driving all chips), they are the
  identity — exactly the reference's behavior on a single rank.

Groups name mesh axes rather than rank lists: new_group on the reference
carves NCCL communicators; here it binds an axis name of the active Mesh.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from ..tensor import Tensor
from .env import get_world_size


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


@dataclass
class Group:
    """A communication group == a mesh axis (or all axes)."""
    axis_name: Optional[str] = None
    ranks: Optional[Sequence[int]] = None

    @property
    def nranks(self):
        if self.axis_name is None:
            return get_world_size()
        from .mesh import get_mesh
        return get_mesh().shape[self.axis_name]

    def get_group_rank(self, rank):
        return rank

    @property
    def process_ids(self):
        return list(self.ranks or range(self.nranks))


_default_group = Group()


def new_group(ranks=None, backend=None, axis_name=None, timeout=None):
    return Group(axis_name=axis_name, ranks=ranks)


def split_group(parent=None, split_sizes=None):
    return Group()


def _in_trace():
    try:
        from jax.core import trace_state_clean
        return not trace_state_clean()
    except Exception:
        return False


def _axis(group):
    if group is not None and group.axis_name is not None:
        return group.axis_name
    return None


def _apply(x, fn):
    if isinstance(x, Tensor):
        out = fn(x._value)
        x._value = out
        return x
    return fn(x)


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    ax = _axis(group)

    def fn(a):
        if ax is not None:
            red = {ReduceOp.SUM: jax.lax.psum, ReduceOp.MAX: jax.lax.pmax,
                   ReduceOp.MIN: jax.lax.pmin,
                   ReduceOp.AVG: jax.lax.pmean}.get(op)
            if red is None:  # PROD via exp/sum-log not safe; use all_gather
                g = jax.lax.all_gather(a, ax)
                return jnp.prod(g, axis=0)
            return red(a, ax)
        return a  # world_size==1 eager

    return _apply(tensor, fn)


def all_gather(tensor_list, tensor=None, group=None, sync_op=True, axis=0):
    """Reference form: all_gather(out_list, tensor). Inside a program with a
    group axis, returns the gathered array stacked on axis 0."""
    if tensor is None:  # functional form: all_gather(tensor, group=...)
        tensor, tensor_list = tensor_list, None
    ax = _axis(group)

    def fn(a):
        if ax is not None:
            return jax.lax.all_gather(a, ax, axis=0)
        return a[None] if tensor_list is not None else a

    arr = fn(tensor._value if isinstance(tensor, Tensor) else tensor)
    if tensor_list is not None:
        del tensor_list[:]
        n = arr.shape[0] if ax is not None else 1
        for i in range(n):
            tensor_list.append(Tensor(arr[i]))
        return tensor_list
    return Tensor(arr) if isinstance(tensor, Tensor) else arr


def all_gather_object(object_list, obj, group=None):
    del object_list[:]
    object_list.append(obj)
    return object_list


def broadcast_object_list(object_list, src=0, group=None):
    """ref: paddle.distributed.broadcast_object_list — no-op under the
    single-controller model (every rank already holds src's objects)."""
    return object_list


def reduce_scatter(tensor, tensor_list=None, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    ax = _axis(group)

    def fn(a):
        if ax is not None:
            return jax.lax.psum_scatter(a, ax, scatter_dimension=0, tiled=True)
        return a

    if tensor_list is not None:
        stacked = jnp.concatenate(
            [t._value if isinstance(t, Tensor) else t for t in tensor_list], axis=0)
        out = fn(stacked)
        return _apply(tensor, lambda a: out)
    return _apply(tensor, fn)


def broadcast(tensor, src=0, group=None, sync_op=True):
    ax = _axis(group)

    def fn(a):
        if ax is not None:
            # take src's value on every member of the axis
            idx = jax.lax.axis_index(ax)
            g = jax.lax.all_gather(a, ax)
            return g[src]
        return a

    return _apply(tensor, fn)


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    return all_reduce(tensor, op=op, group=group)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    ax = _axis(group)
    if tensor_list is not None and ax is not None:
        stacked = jnp.stack([t._value if isinstance(t, Tensor) else t
                             for t in tensor_list])

        def fn(a):
            idx = jax.lax.axis_index(ax)
            return stacked[idx]

        return _apply(tensor, fn)
    if tensor_list is not None:
        return _apply(tensor, lambda a: (
            tensor_list[0]._value if isinstance(tensor_list[0], Tensor)
            else tensor_list[0]))
    return tensor


def alltoall(out_tensor_list, in_tensor_list=None, group=None, sync_op=True):
    ax = _axis(group)
    if in_tensor_list is None:
        # functional: alltoall(x) with leading axis == group size
        def fn(a):
            if ax is not None:
                return jax.lax.all_to_all(a, ax, split_axis=0, concat_axis=0)
            return a
        return _apply(out_tensor_list, fn)
    if ax is None:
        del out_tensor_list[:]
        out_tensor_list.extend(in_tensor_list)
        return out_tensor_list
    stacked = jnp.stack([t._value if isinstance(t, Tensor) else t
                         for t in in_tensor_list])
    out = jax.lax.all_to_all(stacked, ax, split_axis=0, concat_axis=0)
    del out_tensor_list[:]
    for i in range(out.shape[0]):
        out_tensor_list.append(Tensor(out[i]))
    return out_tensor_list


def alltoall_single(out_tensor, in_tensor=None, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    ax = _axis(group)
    if in_tensor is None:
        in_tensor, out_tensor = out_tensor, None

    def fn(a):
        if ax is not None:
            return jax.lax.all_to_all(
                a.reshape((Group(ax).nranks, -1) + a.shape[1:]),
                ax, split_axis=0, concat_axis=0).reshape(a.shape)
        return a

    arr = fn(in_tensor._value if isinstance(in_tensor, Tensor) else in_tensor)
    if out_tensor is not None:
        return _apply(out_tensor, lambda _: arr)
    return Tensor(arr) if isinstance(in_tensor, Tensor) else arr


def send(tensor, dst=0, group=None, sync_op=True):
    """Point-to-point on TPU == collective_permute; expressible only inside a
    program (see pipeline.py's ppermute schedule). Eager p2p on one rank is
    a no-op, matching world_size==1."""
    if _in_trace():
        raise RuntimeError("use distributed.p2p.ppermute inside programs")
    return tensor


def recv(tensor, src=0, group=None, sync_op=True):
    if _in_trace():
        raise RuntimeError("use distributed.p2p.ppermute inside programs")
    return tensor


def isend(tensor, dst=0, group=None):
    return send(tensor, dst, group)


def irecv(tensor, src=0, group=None):
    return recv(tensor, src, group)


def barrier(group=None):
    # eager: sync all pending device work (the reference's stream sync)
    (jnp.zeros(()) + 0).block_until_ready()


def wait(tensor, group=None, use_calc_stream=True):
    arr = tensor._value if isinstance(tensor, Tensor) else tensor
    if hasattr(arr, "block_until_ready"):
        arr.block_until_ready()
    return tensor


def ppermute(x, axis_name, perm):
    """collective_permute (TPU's p2p primitive), usable in shard_map."""
    return jax.lax.ppermute(x, axis_name, perm)
