"""Mixture-of-Experts with expert parallelism over the 'ep' mesh axis.

ref parity: paddle.incubate.distributed.models.moe.MoELayer (gate +
all-to-all token dispatch + per-rank experts, GShard/Switch style) — the
reference dispatches variable token counts per expert through NCCL
alltoall.

TPU-native design: static shapes everywhere (XLA requires them), so
routing is capacity-based exactly like GShard (arXiv:2006.16668):

- gate: softmax top-k (k=1 Switch, k=2 GShard) + load-balancing aux loss
  (Switch Transformer eq. 4).
- dispatch/combine are einsums against a [tokens, experts, capacity]
  one-hot — overflowed tokens drop (identity residual), underflow pads.
- experts are ONE stacked weight tensor [E, d, h]: on a single chip the
  whole MoE is two einsums (MXU-friendly); under a mesh the E dim is
  sharded over 'ep' and the dispatch einsum's token->expert regrouping
  lowers to the alltoall the reference does by hand. An explicit
  shard_map + lax.all_to_all path (`moe_apply_ep`) is provided for the
  Megatron-style SPMD formulation and as the numerics reference.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ...nn import functional as F
from ...nn.initializer import Normal, ParamAttr, XavierUniform
from ...nn.layer import Layer
from ...tensor import Tensor
from ...autograd import apply_op

__all__ = ["MoELayer", "top_k_gating", "moe_apply_dense", "moe_apply_ep"]


def top_k_gating(logits, k=2, capacity=None, capacity_factor=1.25):
    """GShard top-k gating. logits [T, E] -> (dispatch [T, E, C] float32
    0/1 indicator, combine [T, E, C] float32, aux_loss scalar).

    Combine weights follow the GShard equation: the k selected gate values
    are renormalized to sum to 1 per token (capacity-dropped selections
    keep their share of the denominator, so a token that loses one of its
    k experts is attenuated rather than re-amplified)."""
    t, e = logits.shape
    if capacity is None:
        capacity = max(1, int(math.ceil(t * capacity_factor * k / e)))
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    dispatch = jnp.zeros((t, e, capacity), dtype=jnp.float32)
    combine = jnp.zeros((t, e, capacity), dtype=jnp.float32)
    gate_sum = jnp.zeros((t,), dtype=jnp.float32)
    remaining = probs
    # experts fill position counters across the k routing rounds so two
    # tokens never share a (expert, slot)
    fill = jnp.zeros((e,), dtype=jnp.int32)
    for _ in range(k):
        idx = jnp.argmax(remaining, axis=-1)                 # [T]
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.float32)   # [T, E]
        # position of each token within its chosen expert's queue
        pos = (jnp.cumsum(onehot, axis=0) - 1.0) + fill[None, :].astype(
            jnp.float32)
        pos_tok = jnp.sum(pos * onehot, axis=-1)             # [T]
        keep = pos_tok < capacity
        gate_raw = jnp.sum(probs * onehot, axis=-1)          # [T]
        gate_sum = gate_sum + gate_raw
        gate = gate_raw * keep                               # [T]
        slot = jax.nn.one_hot(pos_tok.astype(jnp.int32), capacity,
                              dtype=jnp.float32)             # [T, C]
        dispatch = dispatch + onehot[:, :, None] * slot[:, None, :] \
            * keep[:, None, None]
        combine = combine + gate[:, None, None] * onehot[:, :, None] \
            * slot[:, None, :]
        fill = fill + jnp.sum(onehot * keep[:, None],
                              axis=0).astype(jnp.int32)
        remaining = remaining * (1.0 - onehot)

    if k >= 2:
        # GShard renormalization: the k selected gates sum to 1. For k=1
        # (Switch) the raw prob must be kept — it is the router's main
        # gradient path through the expert output (p/p == 1 would sever it).
        combine = combine / jnp.maximum(gate_sum, 1e-9)[:, None, None]

    # Switch load-balance loss: E * sum_e f_e * p_e
    me = probs.mean(axis=0)                      # mean router prob per expert
    ce = jnp.mean(
        jax.nn.one_hot(jnp.argmax(probs, -1), e, dtype=jnp.float32), axis=0)
    aux = e * jnp.sum(me * ce)
    return dispatch, combine, aux


def _expert_ffn(xe, w1, b1, w2, b2, act):
    h = act(jnp.einsum("ecd,edh->ech", xe, w1) + b1[:, None, :])
    return jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]


def moe_apply_dense(x, gate_w, w1, b1, w2, b2, k=2, capacity_factor=1.25,
                    act=jax.nn.gelu):
    """Whole MoE as einsums (single chip or GSPMD: shard w1/w2 dim 0 over
    'ep' and XLA regroups tokens itself). x [T, D] -> ([T, D], aux)."""
    logits = x @ gate_w
    dispatch, combine, aux = top_k_gating(logits, k=k,
                                          capacity_factor=capacity_factor)
    xe = jnp.einsum("td,tec->ecd", x.astype(jnp.float32),
                    dispatch).astype(x.dtype)      # [E, C, D]
    ye = _expert_ffn(xe, w1, b1, w2, b2, act)
    y = jnp.einsum("ecd,tec->td", ye.astype(jnp.float32),
                   combine).astype(x.dtype)
    return y, aux


def moe_apply_ep(x, gate_w, w1, b1, w2, b2, *, axis_name, k=2,
                 capacity_factor=1.25, act=jax.nn.gelu):
    """Expert-parallel SPMD formulation — call INSIDE shard_map with the
    batch/tokens sharded over `axis_name` and the expert weights sharded on
    dim 0 (each rank owns E/ep experts).

    Same math as the reference MoELayer: local gating, alltoall to bring
    every rank its experts' tokens, local FFN, alltoall back, combine."""
    ep = lax.psum(1, axis_name)
    t_local = x.shape[0]
    e_local = w1.shape[0]
    e = e_local * ep
    logits = x @ gate_w
    # per-rank capacity (GShard): this rank's t_local tokens spread over
    # all e experts; each expert's total queue across ranks is ep*capacity
    capacity = max(1, int(math.ceil(t_local * capacity_factor * k / e)))
    dispatch, combine, aux = top_k_gating(logits, k=k, capacity=capacity)
    aux = lax.pmean(aux, axis_name)
    d = x.shape[-1]
    # local tokens grouped per GLOBAL expert: [E, C, D]
    xe = jnp.einsum("td,tec->ecd", x.astype(jnp.float32),
                    dispatch).astype(x.dtype)
    # alltoall (untiled: split_axis dim == ep is scattered, a new
    # source-rank dim appears at concat_axis): each rank ends up holding
    # every rank's token blocks for its OWN e_local experts
    xe = xe.reshape(ep, e_local, capacity, d)
    xe = lax.all_to_all(xe, axis_name, split_axis=0, concat_axis=2,
                        tiled=False)                   # [e_local, C, ep, D]
    xe = jnp.moveaxis(xe, 2, 1).reshape(e_local, ep * capacity, d)
    ye = _expert_ffn(xe, w1, b1, w2, b2, act)
    # reverse exchange: give every source rank back its slots
    ye = ye.reshape(e_local, ep, capacity, d)
    ye = jnp.moveaxis(ye, 1, 2)                        # [e_local, C, ep, D]
    ye = lax.all_to_all(ye, axis_name, split_axis=2, concat_axis=0,
                        tiled=False)                   # [ep, e_local, C, D]
    ye = ye.reshape(e, capacity, d)
    y = jnp.einsum("ecd,tec->td", ye.astype(jnp.float32),
                   combine).astype(x.dtype)
    return y, aux


class MoELayer(Layer):
    """ref: incubate moe.MoELayer(d_model, experts, gate, top_k).

    Stacked expert FFNs + softmax gate; `ep_axis` weights carry the
    sharding_spec P('ep', ...) so shard_model places experts across the
    mesh. forward returns the output; the last aux loss is kept on
    `self.aux_loss` (add `aux_weight * layer.aux_loss` to the loss like
    the reference's gate loss collection)."""

    def __init__(self, d_model, d_hidden, num_experts, top_k=2,
                 capacity_factor=1.25, ep_axis="ep", act="gelu",
                 weight_attr=None):
        super().__init__()
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        wa = weight_attr or ParamAttr(initializer=Normal(std=0.02))
        self.gate_weight = self.create_parameter(
            (d_model, num_experts),
            attr=ParamAttr(initializer=Normal(std=0.02)))
        self.w1 = self.create_parameter((num_experts, d_model, d_hidden),
                                        attr=wa)
        self.b1 = self.create_parameter((num_experts, d_hidden),
                                        is_bias=True)
        self.w2 = self.create_parameter((num_experts, d_hidden, d_model),
                                        attr=wa)
        self.b2 = self.create_parameter((num_experts, d_model),
                                        is_bias=True)
        for p in (self.w1, self.b1, self.w2, self.b2):
            p.sharding_spec = P(*([ep_axis] + [None] * (len(p.shape) - 1)))
        self._act = getattr(jax.nn, act)
        self.aux_loss = None

    def forward(self, x):
        shape = list(x.shape)
        d = shape[-1]

        def run(xv, gw, w1, b1, w2, b2):
            y, aux = moe_apply_dense(
                xv.reshape(-1, d), gw, w1, b1, w2, b2, k=self.top_k,
                capacity_factor=self.capacity_factor, act=self._act)
            return y.reshape(shape), aux

        out, aux = apply_op(run, x, self.gate_weight, self.w1, self.b1,
                            self.w2, self.b2)
        self.aux_loss = aux
        return out
