"""Pipeline parallelism over the 'pp' mesh axis.

ref parity: python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py
(PipelineParallel with FThenB / 1F1B microbatch schedules and p2p.send/recv
of activations between stage ranks) and meta_parallel/parallel_layers/
pp_layers.py (PipelineLayer / LayerDesc stage partitioning).

TPU-native design — the whole pipeline is ONE jitted SPMD program:

- stages live along the 'pp' axis of the device Mesh; stage parameters are
  stacked on a leading [pp] dim and shard_map hands each device its slice
  (where the reference materialises only the local stage's Layers per rank).
- microbatches march through a lax.scan over T = n_micro + S - 1 ticks;
  activations hop stage i -> i+1 by lax.ppermute over ICI (the reference's
  p2p send/recv pairs). The S-1 extra ticks are the pipeline bubble —
  identical cost shape to the reference's warmup/drain; drained stages
  compute on zeros (SPMD lock-step means the FLOPs happen either way).
- backward is jax.grad *through* the scan: ppermute transposes to the
  reverse shift. Schedule note: this compiles the FThenB dataflow; the
  reference's 1F1B is an op-ORDERING policy for memory, which under XLA
  belongs to the compiler's scheduler — its memory benefit is delivered
  here by per-microbatch jax.checkpoint (activations for at most one
  microbatch per stage are live at a time), not by hand-ordering ops.
- all other mesh axes (dp/mp/sp) stay *auto*: GSPMD keeps partitioning the
  batch and the tensor-parallel weights inside each stage, so dp x mp x pp
  hybrids compose with no extra code.
"""
from __future__ import annotations

import functools
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...nn.layer import Layer


def stack_stage_params(per_stage: Sequence):
    """Stack S equal-structure per-stage pytrees on a new leading [pp] dim."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_stage)


def unstack_stage_params(stacked, n_stages: int):
    return [jax.tree_util.tree_map(lambda a: a[i], stacked)
            for i in range(n_stages)]


def _pipeline_local(stage_params, x, *, stage_fn, n_stages, n_micro,
                    axis, remat):
    """Runs INSIDE shard_map over `axis`. stage_params leaves are the local
    [1, ...] shard; x is the full (pp-replicated) batch."""
    stage = jax.lax.axis_index(axis)
    local = jax.tree_util.tree_map(lambda a: a[0], stage_params)
    mb = x.shape[0] // n_micro
    micro = x.reshape((n_micro, mb) + x.shape[1:])
    f = jax.checkpoint(stage_fn) if remat else stage_fn

    fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]
    n_ticks = n_micro + n_stages - 1

    def tick(carry, t):
        act, outbuf = carry
        # past the last microbatch stage 0 feeds zeros (the drain ticks);
        # their outputs are never harvested
        inj = jnp.where(t < n_micro, micro[jnp.minimum(t, n_micro - 1)],
                        jnp.zeros_like(micro[0]))
        act = jnp.where(stage == 0, inj, act)
        out = f(local, act)
        oidx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        keep = jnp.logical_and(stage == n_stages - 1, t >= n_stages - 1)
        outbuf = outbuf.at[oidx].set(
            jnp.where(keep, out, outbuf[oidx]))
        nxt = jax.lax.ppermute(out, axis, fwd_perm) if n_stages > 1 else out
        return (nxt, outbuf), None

    act0 = jax.lax.pcast(jnp.zeros_like(micro[0]), (axis,), to="varying")
    outbuf0 = jax.lax.pcast(jnp.zeros_like(micro), (axis,), to="varying")
    (_, outbuf), _ = jax.lax.scan(tick, (act0, outbuf0),
                                  jnp.arange(n_ticks))
    # replicate the last stage's outputs to every pp rank so downstream
    # (loss, metrics) sees a pp-consistent value
    outbuf = jax.lax.psum(
        jnp.where(stage == n_stages - 1, outbuf, jnp.zeros_like(outbuf)),
        axis)
    return outbuf.reshape((n_micro * mb,) + x.shape[1:])


def pipeline_apply(mesh, stage_params, x, stage_fn: Callable, *,
                   n_micro: int, axis: str = "pp", remat: bool = True):
    """Run x through S pipeline stages laid over mesh axis `axis`.

    stage_params: pytree whose leaves have leading dim S (stack_stage_params)
    stage_fn: (params_one_stage, act) -> act, same act shape in/out
    x: [B, ...] global batch, B % n_micro == 0. Differentiable end to end.
    """
    n_stages = mesh.shape[axis]
    if x.shape[0] % n_micro:
        raise ValueError(f"batch {x.shape[0]} not divisible by "
                         f"n_micro {n_micro}")
    param_specs = jax.tree_util.tree_map(
        lambda a: P(axis, *([None] * (a.ndim - 1))), stage_params)
    fn = jax.shard_map(
        functools.partial(_pipeline_local, stage_fn=stage_fn,
                          n_stages=n_stages, n_micro=n_micro, axis=axis,
                          remat=remat),
        mesh=mesh, in_specs=(param_specs, P()), out_specs=P(),
        axis_names=frozenset({axis}))
    return fn(stage_params, x)


class LayerDesc:
    """ref: pp_layers.py LayerDesc — deferred layer construction so each
    stage only materialises its own sublayers."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build(self):
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_cls, *args, shared_weight_attr="weight",
                 **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.key = key
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    """ref: pp_layers.py PipelineLayer — takes a flat stack of equal-shape
    blocks and runs them pipelined over the 'pp' mesh axis.

    TPU-native: all blocks are materialised (single controller owns the
    logical model); forward stacks their params and calls pipeline_apply.
    Off-mesh (no 'pp' axis) it runs the blocks sequentially, which is the
    numerical reference for the tests.
    """

    def __init__(self, layers, num_stages=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=1,
                 num_virtual_pipeline_stages=None, topology=None):
        super().__init__()
        from ...nn.layers_common import LayerList
        built, shared = [], {}
        for l in layers:
            layer = l.build() if isinstance(l, LayerDesc) else l
            if isinstance(l, SharedLayerDesc):
                # ref pp_layers.py: same key => physically tied weight.
                # Later occurrences alias the first's parameter Tensor, so
                # both stages' param trees hold the SAME object and eager
                # backward accumulates both contributions onto it.
                if l.key in shared:
                    setattr(layer, l.shared_weight_attr,
                            getattr(shared[l.key], l.shared_weight_attr))
                else:
                    shared[l.key] = layer
            built.append(layer)
        self.shared_layers = shared
        self.blocks = LayerList(built)
        self.num_stages = num_stages
        self.loss_fn = loss_fn
        self.recompute = bool(recompute_interval)
        self._descs = layers

    def _stage_slices(self, n_stages):
        n = len(self.blocks)
        if n % n_stages:
            raise ValueError(
                f"{n} blocks not divisible into {n_stages} equal stages; "
                "equal-structure stages are required for the stacked "
                "pipeline (pad with Identity blocks)")
        per = n // n_stages
        return [list(range(i * per, (i + 1) * per))
                for i in range(n_stages)]

    def forward(self, x, n_micro=None, mesh=None):
        from ...tensor import Tensor
        from ..mesh import get_mesh
        from ...autograd import apply_op
        mesh = mesh or get_mesh()
        if mesh is None or "pp" not in mesh.axis_names or \
                mesh.shape["pp"] == 1:
            for blk in self.blocks:
                x = blk(x)
            return x
        n_stages = self.num_stages or mesh.shape["pp"]
        slices = self._stage_slices(n_stages)
        per = len(slices[0])

        # per-stage trees of the LIVE parameter Tensors — stacking happens
        # inside `run` (jnp.stack is differentiable), so eager backward
        # deposits grads on the blocks' own Parameters, and a weight
        # shared across stages (SharedLayerDesc) appears as one repeated
        # Tensor whose grads accumulate.
        per_stage_t = [[dict(self.blocks[i].named_parameters()) for i in s]
                       for s in slices]
        leaves_t, treedef = jax.tree_util.tree_flatten(
            per_stage_t, is_leaf=lambda t: isinstance(t, Tensor))
        blocks = self.blocks

        def stage_fn(params_list, act):
            from ...nn.layer import functional_call
            for j in range(per):
                out = functional_call(blocks[j], params_list[j], {},
                                      Tensor(act))
                act = out._value if isinstance(out, Tensor) else out
            return act

        def run(arr, *leaves):
            per_stage = jax.tree_util.tree_unflatten(treedef, leaves)
            stacked = stack_stage_params(per_stage)
            return pipeline_apply(mesh, stacked, arr, stage_fn,
                                  n_micro=n_micro or n_stages,
                                  remat=self.recompute)

        if isinstance(x, Tensor):
            return apply_op(run, x, *leaves_t)
        return run(x, *[t._value for t in leaves_t])
