"""Pipeline parallelism over the 'pp' mesh axis.

ref parity: python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py
(PipelineParallel with FThenB / 1F1B microbatch schedules and p2p.send/recv
of activations between stage ranks) and meta_parallel/parallel_layers/
pp_layers.py (PipelineLayer / LayerDesc stage partitioning).

TPU-native design — the whole pipeline is ONE jitted SPMD program:

- stages live along the 'pp' axis of the device Mesh; stage parameters are
  stacked on a leading [pp] dim and shard_map hands each device its slice
  (where the reference materialises only the local stage's Layers per rank).
- microbatches march through a lax.scan over T = n_micro + S - 1 ticks;
  activations hop stage i -> i+1 by lax.ppermute over ICI (the reference's
  p2p send/recv pairs). The S-1 extra ticks are the pipeline bubble —
  identical cost shape to the reference's warmup/drain; drained stages
  compute on zeros (SPMD lock-step means the FLOPs happen either way).
- num_virtual_pipeline_stages / pipeline_apply(n_virtual=v) selects the
  interleaved schedule: each device holds v chunks (global stage c*S + s)
  and activations ride a ring ppermute, shrinking the bubble fraction to
  (S-1)/(n_micro*v + S - 1) — see interleaved_schedule/pipeline_cost for
  the tick math, which is what the CPU accounting tests pin down.
- backward is jax.grad *through* the scan: ppermute transposes to the
  reverse shift. Schedule note: this compiles the FThenB dataflow; the
  reference's 1F1B is an op-ORDERING policy for memory, which under XLA
  belongs to the compiler's scheduler — its memory benefit is delivered
  here by per-microbatch jax.checkpoint (activations for at most one
  microbatch per stage are live at a time), not by hand-ordering ops.
- all other mesh axes (dp/mp/sp) stay *auto*: GSPMD keeps partitioning the
  batch and the tensor-parallel weights inside each stage, so dp x mp x pp
  hybrids compose with no extra code.
"""
from __future__ import annotations

import functools
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..mesh import pvary_compat

from ...nn.layer import Layer


def stack_stage_params(per_stage: Sequence):
    """Stack S equal-structure per-stage pytrees on a new leading [pp] dim."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *per_stage)


def unstack_stage_params(stacked, n_stages: int):
    return [jax.tree_util.tree_map(lambda a: a[i], stacked)
            for i in range(n_stages)]


def _pipeline_local(stage_params, x, *, stage_fn, n_stages, n_micro,
                    axis, remat, sharded_params=True):
    """Runs INSIDE shard_map over `axis`. With sharded_params (new-jax
    partial-auto path) stage_params leaves are the local [1, ...]
    shard; on the old-jax full-manual path they arrive REPLICATED
    ([S_total, ...] everywhere) and each rank dynamically slices its
    own stage — 0.4.x's partitioner mis-shards a jnp.stack product
    feeding a manual-region operand (see shard_map_compat), so the
    stacked tree must not cross the boundary with a sharded spec
    there. x is the full (pp-replicated) batch."""
    stage = jax.lax.axis_index(axis)
    if sharded_params:
        local = jax.tree_util.tree_map(lambda a: a[0], stage_params)
    else:
        local = jax.tree_util.tree_map(
            lambda a: jnp.take(a, stage, axis=0), stage_params)
    mb = x.shape[0] // n_micro
    micro = x.reshape((n_micro, mb) + x.shape[1:])
    f = jax.checkpoint(stage_fn) if remat else stage_fn

    fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]
    n_ticks = n_micro + n_stages - 1

    def tick(carry, t):
        act, outbuf = carry
        # past the last microbatch stage 0 feeds zeros (the drain ticks);
        # their outputs are never harvested
        inj = jnp.where(t < n_micro, micro[jnp.minimum(t, n_micro - 1)],
                        jnp.zeros_like(micro[0]))
        act = jnp.where(stage == 0, inj, act)
        out = f(local, act)
        oidx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        keep = jnp.logical_and(stage == n_stages - 1, t >= n_stages - 1)
        outbuf = outbuf.at[oidx].set(
            jnp.where(keep, out, outbuf[oidx]))
        nxt = jax.lax.ppermute(out, axis, fwd_perm) if n_stages > 1 else out
        return (nxt, outbuf), None

    act0 = pvary_compat(jnp.zeros_like(micro[0]), (axis,))
    outbuf0 = pvary_compat(jnp.zeros_like(micro), (axis,))
    (_, outbuf), _ = jax.lax.scan(tick, (act0, outbuf0),
                                  jnp.arange(n_ticks))
    # replicate the last stage's outputs to every pp rank so downstream
    # (loss, metrics) sees a pp-consistent value
    outbuf = jax.lax.psum(
        jnp.where(stage == n_stages - 1, outbuf, jnp.zeros_like(outbuf)),
        axis)
    return outbuf.reshape((n_micro * mb,) + x.shape[1:])


def interleaved_schedule(u: int, p: int, v: int):
    """The interleaved ('virtual pipeline') schedule as pure math.

    A device at tick t works on diagonal u = t - device_index; the same
    diagonal maps to the same (microbatch, chunk) on every device, so a
    microbatch's chunk-c pass flows device 0 -> p-1 on consecutive
    ticks, then wraps (ring ppermute) to device 0 as chunk c+1.
    Microbatches run in groups of p; a device's local timeline tiles one
    group's p*v chunk-slots back to back, so it is never double-booked.
    Returns (micro_index, chunk_index); micro_index may be out of
    [0, n_micro) — such slots are drain/warmup bubble.

    ref parity: Megatron-style interleaved schedule of
    fleet.meta_parallel pp_utils (num_virtual_pipeline_stages); total
    ticks = ceil(m/p)*p*v + p - 1, i.e. bubble (p-1)/(m*v + p - 1) of
    total at p | m — v times smaller than FThenB's (p-1)/(m + p - 1).
    """
    pv = p * v
    k, q = divmod(u, pv)            # group, phase (floor semantics)
    return k * p + (q % p), q // p


def pipeline_cost(n_stages: int, n_micro: int, n_virtual: int = 1):
    """Tick/FLOP accounting for the compiled schedules (CPU-checkable —
    the hardware-independent part of the pipeline's cost model).

    Returns ticks (scan length), chunk_time (fraction of a full stage
    per tick), total_time in stage-time units, ideal_time, and
    bubble_fraction = 1 - ideal/total."""
    p, v, m = n_stages, n_virtual, n_micro
    if v == 1:
        ticks = m + p - 1
    else:
        groups = -(-m // p)
        ticks = groups * p * v + p - 1
    chunk_time = 1.0 / v
    total = ticks * chunk_time
    ideal = float(m)                # m stage-times per device
    return {"ticks": ticks, "chunk_time": chunk_time,
            "total_time": total, "ideal_time": ideal,
            "bubble_fraction": 1.0 - ideal / total}


def _pipeline_local_interleaved(stage_params, x, *, stage_fn, n_stages,
                                n_chunks, n_micro, axis, remat,
                                sharded_params=True):
    """Interleaved virtual-stage schedule; runs INSIDE shard_map over
    `axis`. With sharded_params, stage_params leaves are the local
    [v, ...] chunk shards (device s holds global stages c*p + s, c in
    [0, v)); on the old-jax full-manual path they arrive replicated
    ([p*v, ...], device-major rows) and each rank slices rows
    [s*v, (s+1)*v) — see _pipeline_local."""
    p, v, m = n_stages, n_chunks, n_micro
    s = jax.lax.axis_index(axis)
    if not sharded_params:
        stage_params = jax.tree_util.tree_map(
            lambda a: jax.lax.dynamic_slice_in_dim(a, s * v, v, axis=0),
            stage_params)
    mb = x.shape[0] // m
    micro = x.reshape((m, mb) + x.shape[1:])
    f = jax.checkpoint(stage_fn) if remat else stage_fn

    ring = [(i, (i + 1) % p) for i in range(p)]
    # ONE formula governs the compiled scan length and the CPU-tested
    # cost model — they must not drift apart
    n_ticks = pipeline_cost(p, m, v)["ticks"]
    pv = p * v

    def tick(carry, t):
        act, outbuf = carry
        u = t - s                   # diagonal; <0 during this device's warmup
        k = jnp.floor_divide(u, pv)
        q = jnp.mod(u, pv)          # floor semantics keep q >= 0
        c = q // p                  # chunk this device runs now
        j = k * p + (q % p)         # microbatch on the diagonal
        live = jnp.logical_and(j >= 0, j < m)
        jc = jnp.clip(j, 0, m - 1)
        chunk = jax.tree_util.tree_map(
            lambda a: jnp.take(a, jnp.clip(c, 0, v - 1), axis=0),
            stage_params)
        inject = jnp.logical_and(jnp.logical_and(s == 0, c == 0), live)
        act = jnp.where(inject, micro[jc], act)
        out = f(chunk, act)
        harvest = jnp.logical_and(
            jnp.logical_and(s == p - 1, c == v - 1), live)
        outbuf = outbuf.at[jc].set(jnp.where(harvest, out, outbuf[jc]))
        nxt = jax.lax.ppermute(out, axis, ring) if p > 1 else out
        return (nxt, outbuf), None

    act0 = pvary_compat(jnp.zeros_like(micro[0]), (axis,))
    outbuf0 = pvary_compat(jnp.zeros_like(micro), (axis,))
    (_, outbuf), _ = jax.lax.scan(tick, (act0, outbuf0),
                                  jnp.arange(n_ticks))
    outbuf = jax.lax.psum(
        jnp.where(s == p - 1, outbuf, jnp.zeros_like(outbuf)), axis)
    return outbuf.reshape((m * mb,) + x.shape[1:])


def pipeline_apply(mesh, stage_params, x, stage_fn: Callable, *,
                   n_micro: int, axis: str = "pp", remat: bool = True,
                   n_virtual: int = 1):
    """Run x through the pipeline stages laid over mesh axis `axis`.

    stage_params: pytree whose leaves have leading dim S_total
    (stack_stage_params), where S_total = mesh.shape[axis] * n_virtual;
    stage g's params sit at row g (stage-major).
    stage_fn: (params_one_stage, act) -> act, same act shape in/out
    x: [B, ...] global batch, B % n_micro == 0. Differentiable end to end.
    n_virtual > 1 selects the interleaved schedule (each device holds
    n_virtual chunks; bubble shrinks ~n_virtual-fold — see
    pipeline_cost)."""
    p = mesh.shape[axis]
    if x.shape[0] % n_micro:
        raise ValueError(f"batch {x.shape[0]} not divisible by "
                         f"n_micro {n_micro}")
    if n_virtual > 1:
        lead = {a.shape[0] for a in
                jax.tree_util.tree_leaves(stage_params)}
        if lead != {p * n_virtual}:
            # jnp.take would silently clip out-of-range rows — a wrong
            # stack size must fail loudly, not duplicate stages
            raise ValueError(
                f"stage_params leading dim must be p*n_virtual = "
                f"{p * n_virtual} (p={p} devices x {n_virtual} chunks); "
                f"got {sorted(lead)}")
        # device-major re-rowing: shard_map splits the leading p*v dim
        # contiguously, so device s must own rows [s*v, (s+1)*v) =
        # its chunks (global stages c*p + s) in chunk order
        import numpy as _np
        perm = _np.asarray([c * p + s_ for s_ in range(p)
                            for c in range(n_virtual)])
        stage_params = jax.tree_util.tree_map(
            lambda a: jnp.take(a, perm, axis=0), stage_params)
        local_fn, local_kw = _pipeline_local_interleaved, dict(
            stage_fn=stage_fn, n_stages=p, n_chunks=n_virtual,
            n_micro=n_micro, axis=axis, remat=remat)
    else:
        local_fn, local_kw = _pipeline_local, dict(
            stage_fn=stage_fn, n_stages=p, n_micro=n_micro, axis=axis,
            remat=remat)
    from ..mesh import shard_map_compat
    # new jax: shard the stacked params over `axis` (each rank holds its
    # stage rows). old jax (no jax.shard_map): its partitioner
    # mis-shards a stack built inside the jit when it feeds a manual
    # region with a sharded spec — pass the stack REPLICATED and let
    # each rank slice its rows in-body instead (CPU-test path only).
    sharded_params = hasattr(jax, "shard_map")
    if sharded_params:
        param_specs = jax.tree_util.tree_map(
            lambda a: P(axis, *([None] * (a.ndim - 1))), stage_params)
    else:
        param_specs = jax.tree_util.tree_map(lambda a: P(), stage_params)
    local = functools.partial(local_fn, sharded_params=sharded_params,
                              **local_kw)
    fn = shard_map_compat(
        local, mesh, in_specs=(param_specs, P()), out_specs=P(),
        manual_axes={axis})
    return fn(stage_params, x)


class LayerDesc:
    """ref: pp_layers.py LayerDesc — deferred layer construction so each
    stage only materialises its own sublayers."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build(self):
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_cls, *args, shared_weight_attr="weight",
                 **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.key = key
        self.shared_weight_attr = shared_weight_attr


class PipelineLayer(Layer):
    """ref: pp_layers.py PipelineLayer — takes a flat stack of equal-shape
    blocks and runs them pipelined over the 'pp' mesh axis.

    TPU-native: all blocks are materialised (single controller owns the
    logical model); forward stacks their params and calls pipeline_apply.
    Off-mesh (no 'pp' axis) it runs the blocks sequentially, which is the
    numerical reference for the tests.
    """

    def __init__(self, layers, num_stages=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=1,
                 num_virtual_pipeline_stages=None, topology=None):
        super().__init__()
        from ...nn.layers_common import LayerList
        built, shared = [], {}
        for l in layers:
            layer = l.build() if isinstance(l, LayerDesc) else l
            if isinstance(l, SharedLayerDesc):
                # ref pp_layers.py: same key => physically tied weight.
                # Later occurrences alias the first's parameter Tensor, so
                # both stages' param trees hold the SAME object and eager
                # backward accumulates both contributions onto it.
                if l.key in shared:
                    setattr(layer, l.shared_weight_attr,
                            getattr(shared[l.key], l.shared_weight_attr))
                else:
                    shared[l.key] = layer
            built.append(layer)
        self.shared_layers = shared
        self.blocks = LayerList(built)
        self.num_stages = num_stages
        self.loss_fn = loss_fn
        self.recompute = bool(recompute_interval)
        self.num_virtual = int(num_virtual_pipeline_stages or 1)
        self._descs = layers

    def _stage_slices(self, n_stages):
        n = len(self.blocks)
        if n % n_stages:
            raise ValueError(
                f"{n} blocks not divisible into {n_stages} equal stages; "
                "equal-structure stages are required for the stacked "
                "pipeline (pad with Identity blocks)")
        per = n // n_stages
        return [list(range(i * per, (i + 1) * per))
                for i in range(n_stages)]

    def forward(self, x, n_micro=None, mesh=None):
        from ...tensor import Tensor
        from ..mesh import get_mesh
        from ...autograd import apply_op
        mesh = mesh or get_mesh()
        if mesh is None or "pp" not in mesh.axis_names or \
                mesh.shape["pp"] == 1:
            for blk in self.blocks:
                x = blk(x)
            return x
        p = mesh.shape["pp"]
        n_stages = (self.num_stages or p) * self.num_virtual
        slices = self._stage_slices(n_stages)
        per = len(slices[0])

        # per-stage trees of the LIVE parameter Tensors — stacking happens
        # inside `run` (jnp.stack is differentiable), so eager backward
        # deposits grads on the blocks' own Parameters, and a weight
        # shared across stages (SharedLayerDesc) appears as one repeated
        # Tensor whose grads accumulate.
        per_stage_t = [[dict(self.blocks[i].named_parameters()) for i in s]
                       for s in slices]
        leaves_t, treedef = jax.tree_util.tree_flatten(
            per_stage_t, is_leaf=lambda t: isinstance(t, Tensor))
        blocks = self.blocks

        def stage_fn(params_list, act):
            from ...nn.layer import functional_call
            for j in range(per):
                out = functional_call(blocks[j], params_list[j], {},
                                      Tensor(act))
                act = out._value if isinstance(out, Tensor) else out
            return act

        def run(arr, *leaves):
            per_stage = jax.tree_util.tree_unflatten(treedef, leaves)
            stacked = stack_stage_params(per_stage)
            return pipeline_apply(mesh, stacked, arr, stage_fn,
                                  n_micro=n_micro or p,
                                  remat=self.recompute,
                                  n_virtual=self.num_virtual)

        if isinstance(x, Tensor):
            return apply_op(run, x, *leaves_t)
        return run(x, *[t._value for t in leaves_t])
