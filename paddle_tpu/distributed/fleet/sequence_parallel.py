"""Sequence / context parallelism over the 'sp' mesh axis.

ref parity: python/paddle/distributed/fleet/meta_parallel/pp_utils and the
sep_parallel / context-parallel utilities (RingFlashAttention in
paddle.distributed.fleet.meta_parallel.sep_utils, and the DeepSpeed-Ulysses
style all-to-all sequence parallelism used by fleet's sep group) — the
reference moves KV blocks between GPUs with NCCL send/recv and reshuffles
heads with all-to-all.

TPU-native design: both strategies are pure SPMD programs inside shard_map
over the 'sp' mesh axis, using XLA collectives over ICI:

- ring_attention: Q stays put; KV blocks rotate around the ring with
  lax.ppermute while an online-softmax accumulator (flash-attention style
  m/l/acc carry in a lax.scan) merges per-block partial attention. Causal
  blocks are masked by comparing the source block index against this
  rank's block index, so late blocks cost (masked) compute but the program
  stays static — XLA overlaps the ppermute with the matmuls, which is the
  whole point of ring attention (arXiv:2310.01889).
- ulysses_attention: lax.all_to_all swaps the sharded axis from sequence to
  heads ([B, S/sp, H, D] -> [B, S, H/sp, D]), runs ordinary (flash)
  attention on full sequences with a head subset, and swaps back
  (DeepSpeed-Ulysses, arXiv:2309.14509). Cheaper collectives than ring for
  moderate sp, but requires heads % sp == 0.

Both differentiate through jax.grad (ppermute/all_to_all transpose to the
reverse shift), so no hand-written backward schedule is needed.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

__all__ = ["ring_attention", "ulysses_attention", "split_sequence",
           "gather_sequence", "ring_attention_spmd", "ulysses_attention_spmd"]

_NEG = -1e30  # finite "minus infinity": keeps exp() NaN-free on masked blocks


def ring_attention(q, k, v, *, axis_name, causal=False, sm_scale=None):
    """Ring attention over sequence shards. Call INSIDE shard_map.

    q, k, v: [B, S_local, H, D] — this rank's sequence chunk; chunks are laid
    out in mesh-axis order (rank r holds positions [r*S_local, (r+1)*S_local)).
    Returns [B, S_local, H, D].
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    sp = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    orig_dtype = q.dtype

    # [B, H, S, D] with fp32 softmax state, MXU matmuls stay in input dtype
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    b, h, s_q, d = qh.shape

    tril = jnp.tril(jnp.ones((s_q, s_q), dtype=bool))

    def step(carry, t):
        k_t, v_t, m, l, acc = carry
        src = (idx - t) % sp  # which global block this rank holds at tick t
        logits = jnp.einsum("bhqd,bhkd->bhqk", qh, k_t,
                            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            bias = jnp.where(src < idx, 0.0,
                             jnp.where((src == idx) & tril, 0.0, _NEG))
            logits = logits + bias
        m_new = jnp.maximum(m, logits.max(axis=-1))
        p = jnp.exp(logits - m_new[..., None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        acc_new = acc * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p.astype(k_t.dtype), v_t,
            preferred_element_type=jnp.float32)
        perm = [(i, (i + 1) % sp) for i in range(sp)]
        k_t = lax.ppermute(k_t, axis_name, perm)
        v_t = lax.ppermute(v_t, axis_name, perm)
        return (k_t, v_t, m_new, l_new, acc_new), None

    m0 = jnp.full((b, h, s_q), _NEG, dtype=jnp.float32)
    l0 = jnp.zeros((b, h, s_q), dtype=jnp.float32)
    acc0 = jnp.zeros((b, h, s_q, d), dtype=jnp.float32)
    (_, _, _, l, acc), _ = lax.scan(
        step, (kh, vh, m0, l0, acc0), jnp.arange(sp))
    out = acc / l[..., None]
    return jnp.swapaxes(out, 1, 2).astype(orig_dtype)


def ulysses_attention(q, k, v, *, axis_name, causal=False, sm_scale=None,
                      attn_fn=None):
    """All-to-all (DeepSpeed-Ulysses) sequence parallelism. Call INSIDE
    shard_map.

    q, k, v: [B, S_local, H, D] with H % sp == 0. Swaps the sharded axis to
    heads, runs full-sequence attention (flash-capable via attn_fn), swaps
    back. Returns [B, S_local, H, D].
    """
    sp = lax.psum(1, axis_name)
    n_heads = q.shape[2]
    if n_heads % sp != 0:
        raise ValueError(
            f"ulysses needs heads ({n_heads}) divisible by sp ({sp})")
    if attn_fn is None:
        from ...ops.attention import flash_attention
        attn_fn = functools.partial(flash_attention, sm_scale=sm_scale)

    def seq_to_heads(x):  # [B, S/sp, H, D] -> [B, S, H/sp, D]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):  # [B, S, H/sp, D] -> [B, S/sp, H, D]
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    out = attn_fn(seq_to_heads(q), seq_to_heads(k), seq_to_heads(v),
                  causal=causal)
    return heads_to_seq(out)


def split_sequence(x, axis_name, seq_axis=1):
    """Take this rank's sequence chunk of a replicated array (inside
    shard_map). ref: fleet's ScatterOp for sequence parallel."""
    sp = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    chunk = x.shape[seq_axis] // sp
    return lax.dynamic_slice_in_dim(x, idx * chunk, chunk, axis=seq_axis)


def gather_sequence(x, axis_name, seq_axis=1):
    """all_gather chunks back to the full sequence (inside shard_map).
    ref: fleet's GatherOp."""
    return lax.all_gather(x, axis_name, axis=seq_axis, tiled=True)


def _spmd(local_fn, mesh, axis):
    """shard_map over `axis` only; any OTHER mesh axes (dp/mp) stay
    *auto* so GSPMD keeps partitioning batch/heads inside the manual
    sequence-sharded body — this is what lets a dp x sp (or dp x mp x
    sp) train step compose with no extra code."""
    spec = P(None, axis, None, None)
    from ..mesh import shard_map_compat
    # manual over `axis` only; dp/mp stay auto for GSPMD
    return shard_map_compat(
        local_fn, mesh, in_specs=(spec, spec, spec),
        out_specs=spec, manual_axes={axis})


def ring_attention_spmd(q, k, v, mesh, *, axis="sp", causal=False,
                        sm_scale=None):
    """Top-level entry: q/k/v [B, S, H, D] (sharded or not) -> ring attention
    with S sharded over `axis`."""
    fn = functools.partial(ring_attention, axis_name=axis, causal=causal,
                           sm_scale=sm_scale)
    return _spmd(fn, mesh, axis)(q, k, v)


def ulysses_attention_spmd(q, k, v, mesh, *, axis="sp", causal=False,
                           sm_scale=None, attn_fn=None):
    fn = functools.partial(ulysses_attention, axis_name=axis, causal=causal,
                           sm_scale=sm_scale, attn_fn=attn_fn)
    return _spmd(fn, mesh, axis)(q, k, v)
