"""Fleet API (ref: python/paddle/distributed/fleet/__init__.py).

fleet.init(strategy) builds the hybrid mesh (dp × pp × mp [× sp]) from
DistributedStrategy.hybrid_configs; distributed_model / distributed_optimizer
wrap the user's model/optimizer so existing Fleet training scripts run
unchanged — the parallelism itself is NamedSharding + shard_map under the
hood (see paddle_tpu/distributed/hybrid.py).
"""
from .base import (  # noqa: F401
    DistributedStrategy, Fleet, PaddleCloudRoleMaker, UserDefinedRoleMaker,
)
from .base import _fleet_singleton as fleet_obj
from ..mesh import get_mesh, set_mesh  # noqa: F401
from . import utils  # noqa: F401
from . import mpu  # noqa: F401
from .mpu import (  # noqa: F401
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    ParallelCrossEntropy, shard_model, param_specs, get_rng_state_tracker,
)

# reference exposes these under fleet.meta_parallel
class meta_parallel:
    ColumnParallelLinear = ColumnParallelLinear
    RowParallelLinear = RowParallelLinear
    VocabParallelEmbedding = VocabParallelEmbedding
    ParallelCrossEntropy = ParallelCrossEntropy
    get_rng_state_tracker = staticmethod(get_rng_state_tracker)

init = fleet_obj.init
is_first_worker = fleet_obj.is_first_worker
worker_index = fleet_obj.worker_index
worker_num = fleet_obj.worker_num
get_hybrid_communicate_group = fleet_obj.get_hybrid_communicate_group
distributed_model = fleet_obj.distributed_model
distributed_optimizer = fleet_obj.distributed_optimizer
distributed_scaler = fleet_obj.distributed_scaler
from . import sequence_parallel  # noqa: F401
from . import sharding as group_sharded  # noqa: F401
