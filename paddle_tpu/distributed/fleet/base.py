"""Fleet core (ref: python/paddle/distributed/fleet/base/*).

DistributedStrategy carries the same knobs as the reference
(hybrid_configs dp/mp/pp degrees, sharding stage, amp, recompute); fleet.init
turns them into a named jax Mesh. HybridCommunicateGroup answers the same
topology queries the reference's does, backed by mesh axes instead of NCCL
communicators.
"""
from __future__ import annotations

import numpy as np

import jax

from ..env import get_rank, get_world_size, init_parallel_env
from ..mesh import build_mesh, get_mesh, set_mesh


class DistributedStrategy:
    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "sep_degree": 1,
        }
        self.amp = False
        self.amp_configs = {"init_loss_scaling": 65536.0, "use_pure_bf16": False}
        self.recompute = False
        self.recompute_configs = {}
        self.sharding = False
        self.sharding_configs = {"stage": 1}
        self.pipeline = False
        self.pipeline_configs = {"accumulate_steps": 1,
                                 "schedule_mode": "1F1B"}
        self.tensor_parallel = False
        self.tensor_parallel_configs = {}
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1}
        self.lamb = False
        self.dgc = False
        # r3 TPU lever: store Adam moments in bf16 with stochastic
        # rounding (halves optimizer HBM state traffic; see
        # optimizer.py moment_dtype)
        self.bf16_moments = False
        self.find_unused_parameters = False

    def __repr__(self):
        return f"DistributedStrategy(hybrid={self.hybrid_configs})"


class HybridCommunicateGroup:
    """ref: python/paddle/distributed/fleet/base/topology.py — answers
    'which dp/mp/pp rank am I' from the mesh shape. Single-controller JAX:
    per-chip ranks exist inside programs (axis_index); host-level queries
    return the process view."""

    def __init__(self, mesh):
        self._mesh = mesh
        self._shape = dict(mesh.shape)

    # degrees
    def get_data_parallel_world_size(self):
        return self._shape.get("dp", 1)

    def get_model_parallel_world_size(self):
        return self._shape.get("mp", 1)

    def get_pipe_parallel_world_size(self):
        return self._shape.get("pp", 1)

    def get_sharding_parallel_world_size(self):
        return self._shape.get("sharding", self._shape.get("dp", 1))

    def get_sep_parallel_world_size(self):
        return self._shape.get("sp", 1)

    # ranks (host view: single controller drives all, rank 0 semantics)
    def get_data_parallel_rank(self):
        return 0

    def get_model_parallel_rank(self):
        return 0

    def get_stage_id(self):
        return 0

    def get_sharding_parallel_rank(self):
        return 0

    # groups == axes
    def get_data_parallel_group(self):
        from ..collective import Group
        return Group(axis_name="dp")

    def get_model_parallel_group(self):
        from ..collective import Group
        return Group(axis_name="mp")

    def get_pipe_parallel_group(self):
        from ..collective import Group
        return Group(axis_name="pp")

    def get_sharding_parallel_group(self):
        from ..collective import Group
        return Group(axis_name="dp")

    def get_check_parallel_group(self, *a):
        from ..collective import Group
        return Group()

    def get_data_parallel_group_src_rank(self):
        return 0

    def get_model_parallel_group_src_rank(self):
        return 0

    @property
    def mesh(self):
        return self._mesh

    def topology(self):
        return self._shape


class Fleet:
    def __init__(self):
        self._strategy = None
        self._hcg = None
        self._is_initialized = False

    def init(self, role_maker=None, is_collective=True, strategy=None,
             log_level="INFO"):
        # a role_maker is fine only when it's explicitly the collective
        # idiom (PaddleCloudRoleMaker(is_collective=True)); anything else —
        # including custom role makers without the attribute — is treated
        # as PS intent and gated loudly rather than silently running the
        # wrong training mode
        rm_collective = getattr(role_maker, "_is_collective", None)
        if (role_maker is not None and rm_collective is not True) or \
                not is_collective:
            # ref: paddle/fluid/distributed/ps/ — the parameter-server mode
            # (CPU PS hosting TB-scale sparse embeddings for recsys).
            # Deliberately descoped on TPU (SURVEY §2.6): a CPU-side PS
            # would bypass the ICI fabric entirely.
            raise NotImplementedError(
                "fleet parameter-server mode (role_maker / "
                "is_collective=False) is not supported on the TPU backend. "
                "Migration: shard embedding tables over the mesh instead — "
                "paddle_tpu.distributed.fleet.mpu.VocabParallelEmbedding "
                "for tensor-parallel vocab sharding, or ZeRO-3 "
                "(group_sharded_parallel(level='p_g_os')) to partition "
                "all parameters including embeddings over dp.")
        init_parallel_env()
        self._strategy = strategy or DistributedStrategy()
        hc = self._strategy.hybrid_configs
        shape = {}
        for axis, key in (("pp", "pp_degree"), ("dp", "dp_degree"),
                          ("sp", "sep_degree"), ("mp", "mp_degree")):
            deg = int(hc.get(key, 1) or 1)
            if deg != 1 or axis in ("dp", "mp", "pp"):
                shape[axis] = deg
        n_dev = len(jax.devices())
        declared = int(np.prod([max(v, 1) for v in shape.values()]))
        if declared != n_dev:
            # absorb the remainder into dp like the reference's default
            rest = n_dev // max(declared // max(shape.get("dp", 1), 1), 1)
            shape["dp"] = max(n_dev // max(
                int(np.prod([v for k, v in shape.items() if k != "dp"])), 1), 1)
        mesh = build_mesh(shape)
        set_mesh(mesh)
        self._hcg = HybridCommunicateGroup(mesh)
        self._is_initialized = True
        return self

    def is_first_worker(self):
        return get_rank() == 0

    def worker_index(self):
        return get_rank()

    def worker_num(self):
        return get_world_size()

    def get_hybrid_communicate_group(self):
        if self._hcg is None:
            self._hcg = HybridCommunicateGroup(get_mesh())
        return self._hcg

    def distributed_model(self, model):
        """Places params on the mesh (replicated over dp, tensor-parallel
        layers already carry their mp shardings from hybrid.py)."""
        from ..hybrid import place_model_on_mesh
        return place_model_on_mesh(model, get_mesh())

    def distributed_optimizer(self, optimizer, strategy=None):
        strategy = strategy or self._strategy
        optimizer._fleet_strategy = strategy
        if strategy is not None and getattr(strategy, "bf16_moments", False):
            import jax.numpy as jnp
            from ...optimizer.optimizer import Adam
            # NAdam/RAdam subclass Adam but override update() without the
            # stochastic-rounding store path — a hasattr probe would
            # accept them and silently keep fp32 moments after step 1
            if not (isinstance(optimizer, Adam)
                    and type(optimizer).update is Adam.update):
                raise ValueError(
                    f"strategy.bf16_moments: {type(optimizer).__name__} "
                    "has no reduced-precision moment support (Adam/AdamW "
                    "only)")
            if optimizer._func_state is not None:
                raise RuntimeError(
                    "strategy.bf16_moments must be applied before the "
                    "first optimizer step (state already materialized)")
            optimizer._moment_dtype = jnp.dtype(jnp.bfloat16)
        if strategy is not None and strategy.sharding:
            # fleet sharding stage 1/2/3 → GroupSharded/ZeRO placement
            # (ref: DygraphShardingOptimizer selection in fleet.init)
            from .sharding import group_sharded_parallel
            stage = int(strategy.sharding_configs.get("stage", 1))
            level = {1: "os", 2: "os_g", 3: "p_g_os"}.get(stage)
            if level is None:
                raise ValueError(
                    f"sharding_configs stage must be 1, 2 or 3, got {stage}")
            group_sharded_parallel(None, optimizer, level=level,
                                   mesh=get_mesh())
        return optimizer

    def distributed_scaler(self, scaler):
        return scaler


class PaddleCloudRoleMaker:
    def __init__(self, is_collective=True, **kwargs):
        self._is_collective = is_collective


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    pass


_fleet_singleton = Fleet()
