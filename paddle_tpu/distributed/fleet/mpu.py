"""Tensor-parallel (model-parallel) layers.

ref parity: python/paddle/distributed/fleet/layers/mpu/mp_layers.py
(ColumnParallelLinear / RowParallelLinear / VocabParallelEmbedding) and
mp_ops.py (ParallelCrossEntropy). The reference shards weights across an
NCCL mp group and calls c_allreduce/c_concat by hand.

TPU-native design — the same layer works in BOTH partitioning regimes:

- **GSPMD (primary)**: the layer holds the full logical weight whose
  Parameter carries a `sharding_spec` over the `mp` mesh axis.
  `shard_model(model, mesh)` places the weights; inside `jit` the matmul is
  partitioned by XLA, which inserts the all-reduce / all-gather over ICI
  itself (the compiler plays the role of the reference's hand-written
  c_ops). Activations are pinned with `with_sharding_constraint` so the
  compiler cannot undo the intended layout.
- **shard_map (explicit)**: when the surrounding program entered
  `shard_map` over the mp axis (pipeline stages, custom kernels), each
  device sees the *local* weight shard; the layers then emit `lax.psum`
  exactly where the reference emits c_allreduce.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...nn import functional as F
from ...nn.initializer import XavierUniform, Normal
from ...nn.layer import Layer
from ...tensor import Tensor
from ...autograd import apply_op
from ..mesh import get_mesh, set_mesh


def axis_bound(name: str) -> bool:
    """True iff `name` is a bound mesh axis here (i.e. we are inside a
    shard_map/pmap program over that axis)."""
    try:
        jax.lax.axis_index(name)
        return True
    except (NameError, Exception):
        return False


def _explicit_mesh():
    """The global mesh, only if one was explicitly set (never the implicit
    single-axis default — annotating against that would constrain plain
    single-chip runs)."""
    from .. import mesh as mesh_mod
    return mesh_mod._global_mesh


def annotate(x, *spec):
    """with_sharding_constraint against the global mesh. `None` dims are
    left UNCONSTRAINED (GSPMD chooses) — pinning them replicated would
    force an all-gather of e.g. the dp-sharded batch dim at every tp layer.
    No-op when no mesh was set, no named axis survives, the axis isn't in
    the mesh, we're inside shard_map (arrays are local shards there), or a
    dim isn't divisible by its axis size."""
    mesh = _explicit_mesh()
    if mesh is None:
        return x
    names = [s for s in spec if s is not None]
    if not names or any(s not in mesh.axis_names for s in names):
        return x
    if any(axis_bound(s) for s in names):
        return x
    shape = x.shape
    for dim, s in zip(shape, spec):
        if s is not None and int(dim) % mesh.shape[s] != 0:
            return x
    spec = [P.UNCONSTRAINED if s is None else s for s in spec]
    # inside a PARTIAL shard_map (e.g. manual over 'pp', auto over dp/mp)
    # the constraint must be built on the trace's abstract mesh so axis
    # types line up (pp: Manual); the concrete mesh types everything Auto
    # and jax rejects the mismatch
    try:
        am = jax.sharding.get_abstract_mesh()
        if am is not None and am.axis_names == mesh.axis_names and any(
                "Manual" in str(t) for t in getattr(am, "axis_types", ())):
            mesh = am
    except Exception:
        pass
    sharding = NamedSharding(mesh, P(*spec))
    if isinstance(x, Tensor):
        return apply_op(
            lambda a: jax.lax.with_sharding_constraint(a, sharding), x)
    return jax.lax.with_sharding_constraint(x, sharding)


def shard_model(model: Layer, mesh=None):
    """Place every parameter on `mesh` per its `sharding_spec` (replicated
    when unset). The GSPMD analogue of fleet.distributed_model()."""
    mesh = mesh or get_mesh()
    set_mesh(mesh)
    for _, p in model.named_parameters():
        spec = getattr(p, "sharding_spec", None) or P()
        spec = P(*[s if (s is None or (s in mesh.axis_names
                                       and dim % mesh.shape[s] == 0))
                   else None
                   for dim, s in zip(p._value.shape, spec)])
        p._value = jax.device_put(p._value, NamedSharding(mesh, spec))
    for _, b in model.named_buffers():
        b._value = jax.device_put(b._value, NamedSharding(mesh, P()))
    return model


def param_specs(model: Layer):
    """name -> PartitionSpec pytree for Engine/pjit in_shardings."""
    return {n: (getattr(p, "sharding_spec", None) or P())
            for n, p in model.named_parameters()}


class ColumnParallelLinear(Layer):
    """Linear whose OUTPUT dim is split over the mp axis.

    ref: fleet/layers/mpu/mp_layers.py ColumnParallelLinear — weight
    [in, out/mp] per rank, optional all-gather of the output. Here the
    logical weight is [in, out] with spec P(None, 'mp').
    """

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None, mp_axis="mp"):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.mp_axis = mp_axis
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=None if weight_attr else XavierUniform())
        self.weight.sharding_spec = P(None, mp_axis)
        if has_bias:
            self.bias = self.create_parameter((out_features,), is_bias=True)
            self.bias.sharding_spec = P(mp_axis)
        else:
            self.bias = None
            self._parameters["bias"] = None

    def forward(self, x):
        y = F.linear(x, self.weight, self.bias)
        if axis_bound(self.mp_axis):
            if self.gather_output:
                y = apply_op(lambda a: jax.lax.all_gather(
                    a, self.mp_axis, axis=a.ndim - 1, tiled=True), y)
            return y
        if self.gather_output:
            # all-None annotate is a no-op by design: GSPMD already keeps
            # the gathered output unconstrained, no pin needed
            return y
        return annotate(y, *([None] * (len(y.shape) - 1)), self.mp_axis)


class RowParallelLinear(Layer):
    """Linear whose INPUT dim is split over the mp axis; output needs a
    sum-reduce across mp (ref: RowParallelLinear's c_allreduce_sum — GSPMD
    derives the same psum from the contraction over a sharded dim)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None,
                 mp_axis="mp"):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.mp_axis = mp_axis
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=None if weight_attr else XavierUniform())
        self.weight.sharding_spec = P(mp_axis, None)
        if has_bias:
            # bias is added AFTER the reduce -> replicated
            self.bias = self.create_parameter((out_features,), is_bias=True)
        else:
            self.bias = None
            self._parameters["bias"] = None

    def forward(self, x):
        if axis_bound(self.mp_axis):
            y = F.linear(x, self.weight, None)
            y = apply_op(lambda a: jax.lax.psum(a, self.mp_axis), y)
            if self.bias is not None:
                y = y + self.bias
            return y
        if not self.input_is_parallel:
            x = annotate(x, *([None] * (len(x.shape) - 1)), self.mp_axis)
        # output left unconstrained: GSPMD inserts the mp reduce itself
        # (the Megatron c_allreduce equivalent) when x's last dim is sharded
        return F.linear(x, self.weight, self.bias)


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim split over mp.

    ref: VocabParallelEmbedding masks out-of-range ids, gathers locally and
    all-reduces. GSPMD: gather from a vocab-sharded table lowers to the
    same collective pattern automatically.
    """

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None, mp_axis="mp"):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self.mp_axis = mp_axis
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=None if weight_attr else Normal(0.0, 0.02))
        self.weight.sharding_spec = P(mp_axis, None)

    def forward(self, x):
        if axis_bound(self.mp_axis):
            # explicit Megatron-style local gather + psum
            def local_embed(ids, w):
                size = jax.lax.psum(1, self.mp_axis)
                rank = jax.lax.axis_index(self.mp_axis)
                per = self._num_embeddings // size
                start = rank * per
                local = ids - start
                ok = (local >= 0) & (local < per)
                safe = jnp.clip(local, 0, per - 1)
                out = w[safe]
                out = jnp.where(ok[..., None], out, 0.0)
                return jax.lax.psum(out, self.mp_axis)
            return apply_op(local_embed, x, self.weight)
        return F.embedding(x, self.weight)


def parallel_matmul(x, weight, transpose_y=False, mp_axis="mp",
                    gather_output=True):
    """Logits projection against a vocab-parallel table (lm head weight
    tying). ref: fleet.layers.mpu.mp_ops._c_lookup/_Linear paths.

    gather_output=False keeps the vocab dim sharded under shard_map —
    required when the result feeds ParallelCrossEntropy, which expects
    vocab-LOCAL logits (gathering first would double-count the partition
    function mp_size times)."""
    def fn(a, w):
        wt = w.T if transpose_y else w
        return jnp.matmul(a, wt)
    y = apply_op(fn, x, weight)
    if axis_bound(mp_axis):
        if gather_output:
            return apply_op(lambda a: jax.lax.all_gather(
                a, mp_axis, axis=a.ndim - 1, tiled=True), y)
        return y
    return annotate(y, *([None] * (len(y.shape) - 1)), mp_axis)


class ParallelCrossEntropy(Layer):
    """Softmax CE over mp-sharded logits.

    ref: mp_ops.ParallelCrossEntropy (c_softmax_with_cross_entropy): local
    max -> pmax, local sum-exp -> psum, local target logit -> psum. Under
    GSPMD the plain stable CE compiles to the same pattern, so the explicit
    path is only taken inside shard_map.
    """

    def __init__(self, mp_group=None, name=None, ignore_index=-100,
                 mp_axis="mp"):
        super().__init__()
        self.mp_axis = mp_axis
        self.ignore_index = ignore_index

    def forward(self, logits, label):
        ax = self.mp_axis
        ii = self.ignore_index

        if axis_bound(ax):
            def ce(lg, lb):
                lg = lg.astype(jnp.float32)  # fp32-stable partition function
                size = jax.lax.psum(1, ax)
                rank = jax.lax.axis_index(ax)
                v_local = lg.shape[-1]
                start = rank * v_local
                # max shift cancels in the lse; stop_gradient keeps the
                # (non-differentiable) pmax out of the vjp
                m = jax.lax.pmax(
                    jax.lax.stop_gradient(jnp.max(lg, axis=-1)), ax)
                z = jax.lax.psum(
                    jnp.sum(jnp.exp(lg - m[..., None]), axis=-1), ax)
                local = lb - start
                ok = (local >= 0) & (local < v_local)
                safe = jnp.clip(local, 0, v_local - 1)
                tgt = jnp.take_along_axis(lg, safe[..., None], axis=-1)[..., 0]
                tgt = jnp.where(ok, tgt - m, 0.0)
                tgt = jax.lax.psum(tgt, ax)
                loss = jnp.log(z) - tgt
                return jnp.where(lb == ii, 0.0, loss)
            return apply_op(ce, logits, label)

        def ce_full(lg, lb):
            lg32 = lg.astype(jnp.float32)
            m = jnp.max(lg32, axis=-1, keepdims=True)
            lse = jnp.log(jnp.sum(jnp.exp(lg32 - m), axis=-1)) + m[..., 0]
            safe = jnp.clip(lb, 0, lg.shape[-1] - 1)
            tgt = jnp.take_along_axis(
                lg32, safe[..., None], axis=-1)[..., 0]
            loss = lse - tgt
            return jnp.where(lb == ii, 0.0, loss)
        return apply_op(ce_full, logits, label)


def _stream_tag(name: str) -> int:
    import zlib
    return zlib.crc32(name.encode()) & 0x7FFFFFFF


class _StreamScope:
    """Wraps an active traced RNG scope: every key drawn inside a
    tracker.rng_state(name) block is folded with the stream's tag and —
    for non-global streams — with the index of any bound mp/sp axis, so
    shard_map ranks draw decorrelated dropout masks."""

    def __init__(self, inner, tag, fold_axes):
        self._inner = inner
        self._tag = tag
        self._fold_axes = fold_axes

    def next_key(self):
        k = jax.random.fold_in(self._inner.next_key(), self._tag)
        for ax in self._fold_axes:
            if axis_bound(ax):
                k = jax.random.fold_in(k, jax.lax.axis_index(ax))
        return k


class RNGStatesTracker:
    """ref: fleet.meta_parallel rng_state tracker — named dropout RNG
    streams so tensor-parallel ranks draw decorrelated local dropout while
    sharing the global stream.

    TPU-native semantics per execution regime:

    - eager: each named stream is its own Generator (seed it with
      ``add(name, seed)``; the reference seeds "local_seed" with
      seed+mp_rank — here a per-name default derived from the name tag is
      used if not added).
    - traced (functional/jit path): keys keep flowing from the step's
      rng_scope (so they remain proper jit inputs) but are folded with the
      stream tag; non-"global_seed" streams additionally fold the bound
      mp/sp axis index inside shard_map, which is the moment ranks actually
      run distinct programs. Under pure GSPMD a single logical dropout mask
      is partitioned by XLA, which already matches the reference's
      semantics for sharded activations.
    """

    GLOBAL = "global_seed"

    def __init__(self):
        self._gens = {}

    def add(self, name, seed):
        from ... import framework
        g = framework.Generator(int(seed))
        g._tracker_stream = True
        self._gens[name] = g

    def reset(self):
        self._gens.clear()
        self._base_seed = None

    def _eager_gen(self, name):
        from ... import framework
        if name not in self._gens:
            # derive from the NON-stream global seed, never from a stream
            # generator that happens to be swapped in (nested rng_state
            # blocks must not change a lazily-created stream's sequence)
            base = getattr(self, "_base_seed", None)
            if base is None:
                base = framework.default_generator().initial_seed()
            g = framework.Generator(base ^ _stream_tag(name))
            g._tracker_stream = True
            self._gens[name] = g
        return self._gens[name]

    def rng_state(self, name="local_seed"):
        import contextlib
        from ... import framework

        @contextlib.contextmanager
        def _cm():
            st = framework._state
            scope = getattr(st, "rng_scope", None)
            if scope is not None:
                fold = () if name == self.GLOBAL else ("mp", "sp")
                st.rng_scope = _StreamScope(scope, _stream_tag(name), fold)
                try:
                    yield
                finally:
                    st.rng_scope = scope
            else:
                prev = framework._default_generator
                if not getattr(prev, "_tracker_stream", False):
                    self._base_seed = prev.initial_seed()
                gen = self._eager_gen(name)
                framework._default_generator = gen
                try:
                    yield
                finally:
                    framework._default_generator = prev
        return _cm()

    def fold_axis(self, key, axis="mp"):
        if axis_bound(axis):
            return jax.random.fold_in(key, jax.lax.axis_index(axis))
        return key


_RNG_TRACKER = RNGStatesTracker()


def get_rng_state_tracker():
    return _RNG_TRACKER
