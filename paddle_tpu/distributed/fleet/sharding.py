"""GroupSharded (ZeRO stage 1/2/3) over the 'dp' mesh axis.

ref parity: python/paddle/distributed/sharding/group_sharded.py
(`group_sharded_parallel(model, optimizer, level='os'|'os_g'|'p_g_os')`)
and fleet's DygraphShardingOptimizer — the reference partitions optimizer
state / gradients / parameters across dp ranks with hand-written
broadcast/reduce-scatter/all-gather choreography.

TPU-native design: ZeRO is a *placement* decision, not a communication
schedule. Each stage is a set of GSPMD sharding annotations on the train
step's pytrees, and XLA emits the reduce-scatter / all-gather pattern
itself (this is exactly how GSPMD papers describe ZeRO):

- 'os'     (stage 1): optimizer state leaves sharded over 'dp'.
- 'os_g'   (stage 2): + gradients constrained to the same sharding, so the
  grad psum lowers to reduce-scatter and each rank updates its shard.
- 'p_g_os' (stage 3, = fleet sharding stage 3 / FSDP): + parameters stored
  sharded; XLA all-gathers them just-in-time inside the fused step.

Specs compose with tensor-parallel ('mp') shardings: the ZeRO axis is laid
on the largest dimension not already claimed by another mesh axis.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["group_sharded_parallel", "save_group_sharded_model",
           "GroupShardedConfig", "zero_spec"]

_LEVELS = ("os", "os_g", "p_g_os")


@dataclass
class GroupShardedConfig:
    level: str = "os"
    axis: str = "dp"
    mesh: object = None

    @property
    def shard_grads(self):
        return self.level in ("os_g", "p_g_os")

    @property
    def shard_params(self):
        return self.level == "p_g_os"


def zero_spec(arr, mesh, axis, base_spec=None):
    """PartitionSpec sharding `arr`'s largest free dim over `axis`, keeping
    any existing (e.g. 'mp') placements in base_spec. Falls back to the
    base spec (replicated over `axis`) when no dim divides evenly."""
    ndim = arr.ndim
    base = list(base_spec) if base_spec is not None else []
    base += [None] * (ndim - len(base))
    size = mesh.shape[axis]
    used = {a for e in base if e is not None
            for a in ((e,) if isinstance(e, str) else tuple(e))}
    if axis in used or size == 1:
        return P(*base)
    for d in sorted(range(ndim), key=lambda d: arr.shape[d], reverse=True):
        if base[d] is None and arr.shape[d] % size == 0 \
                and arr.shape[d] >= size:
            base[d] = axis
            return P(*base)
    return P(*base)


def _base_spec(a):
    sh = getattr(a, "sharding", None)
    return getattr(sh, "spec", None) if isinstance(sh, NamedSharding) else None


def shard_tree(tree, mesh, axis, like=None):
    """device_put every array leaf to its zero_spec placement. `like`:
    optional same-structure tree whose leaves' existing specs to preserve
    (used for opt-state moments mirroring their parameter's mp spec)."""
    like = like if like is not None else tree

    def place(a, ref):
        if not hasattr(a, "ndim") or a.ndim == 0:
            return a
        spec = zero_spec(a, mesh, axis, _base_spec(ref))
        return jax.device_put(a, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(place, tree, like)


def constraint_specs(tree, mesh, axis, like=None):
    """Same placement logic as shard_tree but returns a pytree of
    PartitionSpecs for lax.with_sharding_constraint inside jit."""
    like = like if like is not None else tree
    return jax.tree_util.tree_map(
        lambda a, ref: zero_spec(a, mesh, axis, _base_spec(ref))
        if hasattr(a, "ndim") and a.ndim > 0 else P(),
        tree, like)


def group_sharded_parallel(model, optimizer, level="os", scaler=None,
                           group=None, mesh=None, axis="dp",
                           sync_buffers=False, buffer_max_size=None,
                           segment_size=None, sync_comm=False):
    """ref: paddle.distributed.sharding.group_sharded_parallel — returns
    (model, optimizer, scaler). Extra knobs (buffer_max_size, segment_size,
    sync_comm) are NCCL scheduling details with no TPU equivalent; accepted
    and ignored for API parity."""
    if level not in _LEVELS:
        raise ValueError(f"level must be one of {_LEVELS}, got {level!r}")
    if mesh is None:
        from ..mesh import get_mesh
        mesh = get_mesh()
    if axis not in mesh.axis_names:
        raise ValueError(f"mesh has no {axis!r} axis: {mesh.axis_names}")
    optimizer._group_sharded = GroupShardedConfig(level, axis, mesh)
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    """ref: paddle.distributed.sharding.save_group_sharded_model —
    consolidates sharded state to a full checkpoint. On TPU jax.device_get
    already materialises the unsharded logical array."""
    from ... import serialization
    base = str(output)
    if base.endswith(".pdparams"):
        base = base[:-len(".pdparams")]
    state = {k: jax.device_get(v._value)
             for k, v in model.state_dict().items()}
    serialization.save(state, base + ".pdparams")
    if optimizer is not None:
        opt_state = None
        eng_ref = getattr(optimizer, "_engine_ref", None)
        eng = eng_ref() if eng_ref is not None else None
        if eng is not None and eng._opt_state is not None:
            opt_state = eng.opt_state_dict()
        elif getattr(optimizer, "_func_state", None) is not None:
            opt_state = {"state": optimizer._func_state,
                         "step": optimizer._step_count}
        if opt_state is not None:
            serialization.save(jax.device_get(opt_state), base + ".pdopt")
