"""fleet.utils (ref: python/paddle/distributed/fleet/utils/__init__.py)."""
from __future__ import annotations

import jax


def recompute(function, *args, **kwargs):
    """ref: fleet.utils.recompute — activation rematerialization. Under the
    functional/jit path this is jax.checkpoint; called eagerly it just runs
    the function (nothing to save eagerly)."""
    preserve = kwargs.pop("preserve_rng_state", True)
    use_reentrant = kwargs.pop("use_reentrant", True)
    from ...tensor import Tensor

    def unwrapped(*arrs):
        from ...nn.layer import Layer
        wrapped = [Tensor(a) if not isinstance(a, Tensor) else a for a in arrs]
        out = function(*wrapped, **kwargs)
        return jax.tree_util.tree_map(
            lambda t: t._value if isinstance(t, Tensor) else t, out,
            is_leaf=lambda t: isinstance(t, Tensor))

    try:
        from jax.core import trace_state_clean
        tracing = not trace_state_clean()
    except Exception:
        tracing = False
    if tracing:
        arrs = [a._value if isinstance(a, Tensor) else a for a in args]
        out = jax.checkpoint(unwrapped)(*arrs)
        return jax.tree_util.tree_map(Tensor, out)
    return function(*args, **kwargs)
