"""paddle.device namespace (ref: python/paddle/device/__init__.py).

Device management maps to jax's device list: `set_device`/`get_device`
select the default placement; the cuda submodule exposes the reference
names against the accelerator actually present (TPU here) so ported
scripts keep working — `paddle.device.cuda.synchronize()` on TPU
synchronizes the async dispatch queue.
"""
from __future__ import annotations

import jax

from .framework import get_device, set_device  # noqa: F401

__all__ = ["get_device", "set_device", "get_all_device_type",
           "get_available_device", "get_available_custom_device",
           "is_compiled_with_cuda", "is_compiled_with_rocm",
           "is_compiled_with_custom_device", "cuda", "synchronize",
           "device_count"]


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()})


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()
            if d.platform not in ("cpu", "gpu")]


def is_compiled_with_cuda():
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_custom_device(device_type="tpu"):
    return any(d.platform == device_type for d in jax.devices())


def device_count():
    return len(jax.devices())


def synchronize(device=None):
    """Block until all dispatched work on the device is done."""
    import jax.numpy as jnp
    # a trivial computation + sync flushes the async queue
    jnp.zeros(()).block_until_ready()


class _CudaNamespace:
    """`paddle.device.cuda` parity against the accelerator present."""

    @staticmethod
    def device_count():
        return len([d for d in jax.devices() if d.platform != "cpu"])

    @staticmethod
    def synchronize(device=None):
        return synchronize(device)

    @staticmethod
    def empty_cache():
        # XLA's allocator manages HBM; nothing to flush
        return None

    @staticmethod
    def max_memory_allocated(device=None):
        stats = jax.devices()[0].memory_stats() or {}
        return int(stats.get("peak_bytes_in_use", 0))

    @staticmethod
    def memory_allocated(device=None):
        stats = jax.devices()[0].memory_stats() or {}
        return int(stats.get("bytes_in_use", 0))

    @staticmethod
    def get_device_properties(device=None):
        d = jax.devices()[0]
        return {"name": str(d), "platform": d.platform,
                "memory_stats": d.memory_stats() or {}}


cuda = _CudaNamespace()
