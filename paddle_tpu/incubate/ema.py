"""Exponential moving average of model weights
(ref: python/paddle/static's ExponentialMovingAverage; the dygraph
pattern in PaddleDetection ppdet/optimizer/ema.py).

Eager API mirrors the reference (update/apply/restore); the functional
pair (ema_init / ema_update) slots into jitted training loops so the EMA
update fuses into the train step.
"""
from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp


def ema_init(params):
    # copy so the shadow never aliases live (possibly donated) buffers
    return jax.tree_util.tree_map(lambda p: jnp.array(p, copy=True), params)


def ema_update(ema, params, decay=0.999, step=None):
    """One EMA step. With `step`, uses the reference's warmup-corrected
    decay min(decay, (1+step)/(10+step))."""
    if step is not None:
        d = jnp.minimum(decay, (1.0 + step) / (10.0 + step))
    else:
        d = decay
    return jax.tree_util.tree_map(
        lambda e, p: e * d + p.astype(e.dtype) * (1.0 - d), ema, params)


class ExponentialMovingAverage:
    def __init__(self, parameters=None, decay=0.999, use_warmup=False,
                 name=None):
        self._params = list(parameters or [])
        self.decay = float(decay)
        self.use_warmup = bool(use_warmup)
        self._step = 0
        self._ema = None
        self._backup = None

    def update(self):
        vals = [p._value for p in self._params]
        if self._ema is None:
            self._ema = ema_init(vals)
        self._step += 1
        self._ema = ema_update(
            self._ema, vals, self.decay,
            step=jnp.float32(self._step) if self.use_warmup else None)

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        if self._ema is None:
            yield
            return
        self._backup = [p._value for p in self._params]
        for p, e in zip(self._params, self._ema):
            p._value = e.astype(p._value.dtype)
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        if self._backup is not None:
            for p, v in zip(self._params, self._backup):
                p._value = v
            self._backup = None

    def state_dict(self):
        return {"ema": self._ema, "step": self._step}

    def set_state_dict(self, d):
        self._ema = d.get("ema")
        self._step = d.get("step", 0)
