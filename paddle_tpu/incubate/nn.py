"""paddle.incubate.nn fused layers (ref: python/paddle/incubate/nn/
{fused_transformer,layer/fused_transformer}.py).

On the reference these exist because CUDA needs hand-fused kernels
(fused_attention/fused_feedforward ops). On TPU, XLA fuses the epilogues
automatically and the attention core routes to the Pallas flash kernel —
so these layers are the SAME math with the reference's fused-layer
parameter names and layouts (packed qkv weight, flat `pre_ln_scale`-style
LayerNorm params — state dicts migrate key-for-key), and fusion itself is
the compiler's job.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..autograd import apply_op
from ..nn import functional as F
from ..nn.initializer import Constant
from ..nn.layer import Layer
from ..nn.layers_common import Dropout

__all__ = ["FusedLinear", "FusedDropoutAdd", "FusedMultiHeadAttention",
           "FusedFeedForward", "FusedTransformerEncoderLayer"]


def _bias_param(layer, shape, attr):
    if attr is False:
        return None
    return layer.create_parameter(shape, attr=attr, is_bias=True)


class FusedLinear(Layer):
    """ref: paddle.incubate.nn.FusedLinear — plain GEMM+bias; on TPU the
    'fusion' is XLA's epilogue fusion, so this is Linear with the fused
    layer's name."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, transpose_weight=False, name=None):
        super().__init__()
        self._transpose = transpose_weight
        shape = ((out_features, in_features) if transpose_weight
                 else (in_features, out_features))
        self.weight = self.create_parameter(shape, attr=weight_attr)
        self.bias = _bias_param(self, (out_features,), bias_attr)

    def forward(self, x):
        w = self.weight
        if self._transpose:
            w = apply_op(lambda a: a.T, w)
        return F.linear(x, w, self.bias)


class FusedDropoutAdd(Layer):
    """ref: paddle.incubate.nn.FusedDropoutAdd — dropout(x) + y in one
    fused pass (XLA fuses the mask-scale-add chain)."""

    def __init__(self, p=0.5, mode="upscale_in_train", name=None):
        super().__init__()
        self._dropout = Dropout(p, mode=mode)

    def forward(self, x, y):
        return self._dropout(x) + y


class FusedMultiHeadAttention(Layer):
    """ref: paddle.incubate.nn.FusedMultiHeadAttention — packed qkv weight
    [3, num_heads, head_dim, embed_dim], flat LN params
    (pre_ln_scale/pre_ln_bias/ln_scale/ln_bias), pre/post-LN, residual
    add. The attention core routes through
    F.scaled_dot_product_attention (Pallas flash on TPU).

    Unsupported reference corners raise rather than silently diverge:
    kdim/vdim != embed_dim, need_weights, and cache-based incremental
    decoding (use nlp.generation's KV-cache path for that).
    """

    def __init__(self, embed_dim, num_heads, dropout_rate=0.5,
                 attn_dropout_rate=0.5, kdim=None, vdim=None,
                 normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None,
                 linear_weight_attr=None, linear_bias_attr=None,
                 pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, name=None):
        super().__init__()
        assert embed_dim % num_heads == 0
        if kdim is not None and kdim != embed_dim:
            raise NotImplementedError("FusedMultiHeadAttention: kdim != "
                                      "embed_dim is not supported")
        if vdim is not None and vdim != embed_dim:
            raise NotImplementedError("FusedMultiHeadAttention: vdim != "
                                      "embed_dim is not supported")
        if need_weights:
            raise NotImplementedError(
                "FusedMultiHeadAttention: need_weights=True is not "
                "supported (the flash kernel never materializes the "
                "attention matrix); use nn.MultiHeadAttention")
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate
        self.attn_dropout_rate = attn_dropout_rate
        self._epsilon = epsilon
        # reference layout: qkv_weight [3, num_heads, head_dim, embed_dim]
        self.qkv_weight = self.create_parameter(
            (3, num_heads, self.head_dim, embed_dim), attr=qkv_weight_attr)
        self.qkv_bias = _bias_param(self, (3, num_heads, self.head_dim),
                                    qkv_bias_attr)
        self.linear_weight = self.create_parameter(
            (embed_dim, embed_dim), attr=linear_weight_attr)
        self.linear_bias = _bias_param(self, (embed_dim,), linear_bias_attr)
        # flat LN params, reference names (state dicts migrate key-for-key)
        self.pre_ln_scale = self.create_parameter(
            (embed_dim,), attr=pre_ln_scale_attr,
            default_initializer=Constant(1.0))
        self.pre_ln_bias = _bias_param(self, (embed_dim,), pre_ln_bias_attr)
        self.ln_scale = self.create_parameter(
            (embed_dim,), attr=ln_scale_attr,
            default_initializer=Constant(1.0))
        self.ln_bias = _bias_param(self, (embed_dim,), ln_bias_attr)
        self._dropout = Dropout(dropout_rate)

    def forward(self, x, attn_mask=None, cache=None):
        if cache is not None:
            raise NotImplementedError(
                "FusedMultiHeadAttention: cache-based incremental decoding "
                "is not supported here — use the KV-cache generation path "
                "(paddle_tpu.nlp.generation)")
        residual = x
        if self.normalize_before:
            x = F.layer_norm(x, self.embed_dim, self.pre_ln_scale,
                             self.pre_ln_bias, self._epsilon)
        b, s = x.shape[0], x.shape[1]
        h, d = self.num_heads, self.head_dim

        if self.qkv_bias is not None:
            def qkv(xv, wv, bv):
                # [B,S,E] @ [3,H,D,E]^T -> [B,S,3,H,D]
                return jnp.einsum("bse,khde->bskhd", xv, wv) + bv[None, None]
            packed = apply_op(qkv, x, self.qkv_weight, self.qkv_bias)
        else:
            packed = apply_op(
                lambda xv, wv: jnp.einsum("bse,khde->bskhd", xv, wv),
                x, self.qkv_weight)
        q = packed[:, :, 0]
        k = packed[:, :, 1]
        v = packed[:, :, 2]
        attn = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self.attn_dropout_rate, training=self.training)
        attn = attn.reshape([b, s, h * d])
        out = F.linear(attn, self.linear_weight, self.linear_bias)
        out = residual + self._dropout(out)
        if not self.normalize_before:
            out = F.layer_norm(out, self.embed_dim, self.ln_scale,
                               self.ln_bias, self._epsilon)
        return out


class FusedFeedForward(Layer):
    """ref: paddle.incubate.nn.FusedFeedForward — LN + linear + act +
    dropout + linear + dropout + residual with the reference's flat
    parameter names (linear1_weight/..., ln1_scale/ln2_scale; ln1 is the
    pre-LN, ln2 the post-LN — both exist like the reference)."""

    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1,
                 epsilon=1e-5, activation="relu", act_dropout_rate=None,
                 normalize_before=False, linear1_weight_attr=None,
                 linear1_bias_attr=None, linear2_weight_attr=None,
                 linear2_bias_attr=None, ln1_scale_attr=None,
                 ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.normalize_before = normalize_before
        self.activation = activation
        self._d_model = d_model
        self._epsilon = epsilon
        self.linear1_weight = self.create_parameter(
            (d_model, dim_feedforward), attr=linear1_weight_attr)
        self.linear1_bias = _bias_param(self, (dim_feedforward,),
                                        linear1_bias_attr)
        self.linear2_weight = self.create_parameter(
            (dim_feedforward, d_model), attr=linear2_weight_attr)
        self.linear2_bias = _bias_param(self, (d_model,), linear2_bias_attr)
        self.ln1_scale = self.create_parameter(
            (d_model,), attr=ln1_scale_attr,
            default_initializer=Constant(1.0))
        self.ln1_bias = _bias_param(self, (d_model,), ln1_bias_attr)
        self.ln2_scale = self.create_parameter(
            (d_model,), attr=ln2_scale_attr,
            default_initializer=Constant(1.0))
        self.ln2_bias = _bias_param(self, (d_model,), ln2_bias_attr)
        self._dropout = Dropout(dropout_rate)
        self._act_dropout = Dropout(
            dropout_rate if act_dropout_rate is None else act_dropout_rate)

    def forward(self, x):
        residual = x
        if self.normalize_before:
            x = F.layer_norm(x, self._d_model, self.ln1_scale,
                             self.ln1_bias, self._epsilon)
        act = getattr(F, self.activation)
        x = self._act_dropout(act(
            F.linear(x, self.linear1_weight, self.linear1_bias)))
        x = self._dropout(F.linear(x, self.linear2_weight,
                                   self.linear2_bias))
        out = residual + x
        if not self.normalize_before:
            out = F.layer_norm(out, self._d_model, self.ln2_scale,
                               self.ln2_bias, self._epsilon)
        return out


class FusedTransformerEncoderLayer(Layer):
    """ref: paddle.incubate.nn.FusedTransformerEncoderLayer."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout_rate=0.1,
                 activation="relu", attn_dropout_rate=None,
                 act_dropout_rate=None, normalize_before=False):
        super().__init__()
        self.fused_attn = FusedMultiHeadAttention(
            d_model, nhead, dropout_rate=dropout_rate,
            attn_dropout_rate=(dropout_rate if attn_dropout_rate is None
                               else attn_dropout_rate),
            normalize_before=normalize_before)
        self.ffn = FusedFeedForward(
            d_model, dim_feedforward, dropout_rate=dropout_rate,
            activation=activation, act_dropout_rate=act_dropout_rate,
            normalize_before=normalize_before)

    def forward(self, src, src_mask=None, cache=None):
        if cache is not None:
            raise NotImplementedError(
                "FusedTransformerEncoderLayer: cache is not supported — "
                "use the KV-cache generation path (paddle_tpu.nlp"
                ".generation)")
        return self.ffn(self.fused_attn(src, attn_mask=src_mask))
