"""Incubate optimizers (ref: python/paddle/incubate/optimizer/lookahead.py,
modelaverage.py).

Both follow this package's optimizer design: a *functional core*
(``init_state`` / ``update`` over pytrees, branch-free so it jits into the
Engine's single fused train step) plus the reference's eager API
(``step()`` / ``apply()`` / ``restore()``).
"""
from __future__ import annotations

import contextlib

import jax
import jax.numpy as jnp

from ..optimizer.optimizer import Optimizer
from ..tensor import Tensor


class LookAhead(Optimizer):
    """ref: incubate/optimizer/lookahead.py — wraps an inner (fast)
    optimizer; every k steps the slow weights catch up by
    slow += alpha * (fast - slow) and the fast weights reset to slow.

    The k-step branch is a ``jnp.where`` on ``step % k`` so one compiled
    step serves every iteration (no retrace, TPU-friendly).
    """

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if not isinstance(inner_optimizer, Optimizer):
            raise TypeError("inner_optimizer must be a paddle_tpu Optimizer")
        inner = inner_optimizer
        super().__init__(learning_rate=inner._lr,
                         parameters=inner._parameter_list,
                         grad_clip=inner._grad_clip)
        self.inner_optimizer = inner
        self.alpha = float(alpha)
        self.k = int(k)

    # functional core --------------------------------------------------
    def init_state(self, params):
        # slow weights must be a COPY: sharing buffers with the live params
        # breaks the Engine's donation (same buffer donated as both params
        # and opt_state)
        return {"inner": self.inner_optimizer.init_state(params),
                "slow": jax.tree_util.tree_map(
                    lambda p: jnp.array(p, copy=True), params)}

    def update(self, params, grads, state, lr, step, lr_mult=None):
        fast, inner_state = self.inner_optimizer.update(
            params, grads, state["inner"], lr, step)
        sync = (step % self.k) == 0
        new_slow = jax.tree_util.tree_map(
            lambda s, f: jnp.where(sync, s + self.alpha * (f - s), s),
            state["slow"], fast)
        new_fast = jax.tree_util.tree_map(
            lambda s, f: jnp.where(sync, s, f), new_slow, fast)
        return new_fast, {"inner": inner_state, "slow": new_slow}

    # eager API ---------------------------------------------------------
    def step(self):
        params = {i: p for i, p in enumerate(self._parameter_list)}
        grads = {i: (p.grad._value if p.grad is not None else None)
                 for i, p in enumerate(self._parameter_list)}
        live = {i: p._value for i, p in params.items()
                if grads[i] is not None}
        g = {i: grads[i] for i in live}
        if self._func_state is None:
            self._func_state = self.init_state(live)
        self._step_count += 1
        new_p, self._func_state = self.update(
            live, g, self._func_state, jnp.float32(self.get_lr()),
            jnp.int32(self._step_count))
        for i, v in new_p.items():
            params[i]._value = v

    def clear_grad(self, set_to_zero=True):
        for p in self._parameter_list:
            p.clear_grad()

    def state_dict(self):
        return {"func_state": self._func_state,
                "step": self._step_count}

    def set_state_dict(self, d):
        self._func_state = d.get("func_state")
        self._step_count = d.get("step", 0)


class ModelAverage(Optimizer):
    """ref: incubate/optimizer/modelaverage.py — maintains a running
    average of parameter values over a trailing window; ``apply()`` swaps
    the averaged weights in for evaluation, ``restore()`` swaps back.

    The reference tracks sum_1/sum_2/sum_3 blocks to bound the window on
    GPU memory; a single (sum, count) pair with the same min/max window
    clamping is equivalent math and one less state tensor per param.
    """

    def __init__(self, average_window_rate=0.15, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        super().__init__(learning_rate=0.0, parameters=parameters)
        self.rate = float(average_window_rate)
        self.min_w = int(min_average_window)
        self.max_w = int(max_average_window)
        self._sum = None
        self._count = 0
        self._backup = None

    def _params(self):
        return list(self._parameter_list or [])

    def accumulate(self):
        """Call once per optimizer step (the reference hooks this into
        minimize())."""
        ps = self._params()
        if self._sum is None:
            self._sum = [jnp.zeros_like(p._value) for p in ps]
        window = max(self.min_w, min(self.max_w,
                                     int(self._count * self.rate) + 1))
        if self._count >= window:
            # decay old contributions so the average tracks the trailing
            # window (exponential forget with the same horizon)
            keep = 1.0 - 1.0 / window
            self._sum = [s * keep for s in self._sum]
            self._count = int(self._count * keep)
        self._sum = [s + p._value for s, p in zip(self._sum, ps)]
        self._count += 1

    step = accumulate

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        """Swap averaged weights in (context manager, ref: apply())."""
        ps = self._params()
        if self._sum is None or self._count == 0:
            yield
            return
        self._backup = [p._value for p in ps]
        for p, s in zip(ps, self._sum):
            p._value = (s / self._count).astype(p._value.dtype)
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        if self._backup is not None:
            for p, v in zip(self._params(), self._backup):
                p._value = v
            self._backup = None

    def minimize(self, loss=None):
        self.accumulate()

    def clear_grad(self, set_to_zero=True):
        pass
