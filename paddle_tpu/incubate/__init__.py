"""paddle.incubate parity (ref: python/paddle/incubate/__init__.py).

Currently the optimizer extensions: LookAhead, ModelAverage, EMA.
"""
from .optimizer import LookAhead, ModelAverage  # noqa: F401
from .ema import ExponentialMovingAverage  # noqa: F401

EMA = ExponentialMovingAverage

__all__ = ["LookAhead", "ModelAverage", "ExponentialMovingAverage", "EMA",
           "optimizer"]
