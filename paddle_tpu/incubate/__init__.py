"""paddle.incubate parity (ref: python/paddle/incubate/__init__.py).

Optimizer extensions (LookAhead, ModelAverage, EMA) + incubate.nn fused
layers.
"""
from .optimizer import LookAhead, ModelAverage  # noqa: F401
from .ema import ExponentialMovingAverage  # noqa: F401
from . import nn  # noqa: F401


class autograd:  # noqa: N801  (namespace parity: paddle.incubate.autograd)
    from ..autograd import hessian, jacobian, jvp, vjp

EMA = ExponentialMovingAverage

from .fuse import fuse_conv_bn  # noqa: E402

__all__ = ["LookAhead", "ModelAverage", "ExponentialMovingAverage", "EMA",
           "optimizer", "nn", "fuse_conv_bn"]
