"""Conv+BatchNorm folding for serving.

ref parity: the reference's inference-time `conv_bn_fuse_pass`
(paddle/fluid/framework/ir/conv_bn_fuse_pass.cc) — at serving time a
frozen BatchNorm is an affine transform per output channel, so it
folds into the preceding conv's weights and bias:

    scale_c = gamma_c / sqrt(var_c + eps)
    W'[c]   = W[c] * scale_c
    b'_c    = (b_c - mean_c) * scale_c + beta_c

TPU-native shape of the same idea: there is no Program pass pipeline —
the fold is a module-tree transform (`fuse_conv_bn`) you apply to an
eval-mode model before jit/`jit.save`; XLA then compiles the folded
conv exactly like any other (one fewer elementwise HBM pass per conv,
and the BN buffers disappear from the serving artifact).
"""
from __future__ import annotations

import numpy as np

__all__ = ["fuse_conv_bn"]


def _fold_pair(conv, bn):
    import jax.numpy as jnp

    from ..nn.layer import Parameter
    gamma = (np.asarray(bn.weight._value) if bn.weight is not None
             else np.ones(bn._num_features, np.float32))
    beta = (np.asarray(bn.bias._value) if bn.bias is not None
            else np.zeros(bn._num_features, np.float32))
    mean = np.asarray(bn._mean._value)
    var = np.asarray(bn._variance._value)
    scale = gamma / np.sqrt(var + bn._epsilon)

    w = np.asarray(conv.weight._value)
    # non-transpose convs store [out, in/groups, *k]; the channels-last
    # stack (layers_conv.to_channels_last) stores HWIO [*k, in/g, out].
    # scale is per-out either way
    if getattr(conv, "_weight_format", "OIHW") == "HWIO":
        w = w * scale
    else:
        w = w * scale.reshape((-1,) + (1,) * (w.ndim - 1))
    b = (np.asarray(conv.bias._value) if conv.bias is not None
         else np.zeros(scale.shape[0], np.float32))
    b = (b - mean) * scale + beta

    conv.weight._value = jnp.asarray(w, conv.weight._value.dtype)
    if conv.bias is None:
        p = Parameter(jnp.asarray(b, w.dtype))
        conv.bias = p
        conv._parameters["bias"] = p
    else:
        conv.bias._value = jnp.asarray(b, conv.bias._value.dtype)


def fuse_conv_bn(model):
    """Fold every (Conv, BatchNorm) pair in `model` IN PLACE; the BN
    layers become Identity. Eval-mode only (training BN uses batch
    statistics — folding would change semantics). Recognised shapes:

    - `nn.Sequential` with a BatchNorm directly following a conv
    - sibling attributes named `conv*` / `bn*` where the names match
      after the prefix (`conv1`/`bn1`, `conv`/`bn`, ...) — the layer
      zoo convention (ResNet/VGG/MobileNet blocks)

    Returns (model, n_folded)."""
    from ..nn.layers_common import Identity, Sequential
    from ..nn.layers_conv import Conv1D, Conv2D, Conv3D
    from ..nn.layers_norm import _BatchNormBase

    if model.training:
        raise ValueError(
            "fuse_conv_bn folds the running statistics of FROZEN "
            "BatchNorms: call model.eval() first (training-mode BN "
            "normalizes by batch stats, which cannot fold)")
    conv_types = (Conv1D, Conv2D, Conv3D)
    n = 0

    def walk(layer):
        nonlocal n
        if isinstance(layer, Sequential):
            kids = list(layer._sub_layers.items())
            for (k1, a), (k2, b) in zip(kids, kids[1:]):
                if isinstance(a, conv_types) and \
                        isinstance(b, _BatchNormBase):
                    _fold_pair(a, b)
                    layer._sub_layers[k2] = Identity()
                    setattr(layer, k2, layer._sub_layers[k2])
                    n += 1
        names = list(layer._sub_layers)
        for cname in names:
            child = layer._sub_layers[cname]
            if isinstance(child, conv_types) and cname.startswith("conv"):
                bname = "bn" + cname[len("conv"):]
                sib = layer._sub_layers.get(bname)
                if isinstance(sib, _BatchNormBase):
                    _fold_pair(child, sib)
                    ident = Identity()
                    layer._sub_layers[bname] = ident
                    setattr(layer, bname, ident)
                    n += 1
        for child in layer._sub_layers.values():
            walk(child)

    walk(model)
    return model, n
