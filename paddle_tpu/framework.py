"""Core framework state: dtypes, default device, RNG, global flags.

TPU-native rebuild of the reference's framework layer
(ref: python/paddle/base/framework.py, python/paddle/base/core dtype enum).
Instead of a C++ VarType enum we alias numpy/jax dtypes directly; instead of
CUDAPlace/CPUPlace device contexts we use jax devices and let XLA manage
streams.
"""
from __future__ import annotations

import contextlib
import os
import threading

# int64 / float64 parity with the reference requires x64 mode. All creation
# ops still default to float32 (see creation.py) so the TPU hot path never
# sees f64 unless the user asks for it.
import jax

jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402

# ---------------------------------------------------------------------------
# dtypes (ref: paddle.float32 etc. map to VarType; here straight to numpy)
# ---------------------------------------------------------------------------
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
uint8 = jnp.uint8
uint16 = jnp.uint16
uint32 = jnp.uint32
uint64 = jnp.uint64
bool_ = jnp.bool_
complex64 = jnp.complex64
complex128 = jnp.complex128

_DTYPE_ALIASES = {
    "float16": float16, "fp16": float16, "half": float16,
    "bfloat16": bfloat16, "bf16": bfloat16,
    "float32": float32, "fp32": float32, "float": float32,
    "float64": float64, "fp64": float64, "double": float64,
    "int8": int8, "int16": int16, "int32": int32, "int64": int64,
    "uint8": uint8, "uint16": uint16, "uint32": uint32, "uint64": uint64,
    "bool": bool_, "complex64": complex64, "complex128": complex128,
}

FLOAT_DTYPES = (jnp.float16, jnp.bfloat16, jnp.float32, jnp.float64)


def convert_dtype(dtype):
    """Normalise any dtype spec (str, np.dtype, jnp type) to a numpy dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype not in _DTYPE_ALIASES:
            raise ValueError(f"unknown dtype {dtype!r}")
        dtype = _DTYPE_ALIASES[dtype]
    return np.dtype(dtype)


def is_floating_dtype(dtype) -> bool:
    return np.dtype(dtype) in (np.dtype(d) for d in FLOAT_DTYPES)


_state = threading.local()


def get_default_dtype():
    return getattr(_state, "default_dtype", np.dtype("float32"))


def set_default_dtype(dtype):
    _state.default_dtype = convert_dtype(dtype)


# ---------------------------------------------------------------------------
# global flags (ref: FLAGS_* gflags read by the C++ runtime)
# ---------------------------------------------------------------------------
_FLAGS = {
    "matmul_precision": "default",   # 'default' | 'high' | 'highest'
    "deterministic": False,
    "check_nan_inf": False,
}


def set_flags(flags: dict):
    for k, v in flags.items():
        key = k.replace("FLAGS_", "")
        if key not in _FLAGS:
            raise KeyError(f"unknown flag {k}")
        _FLAGS[key] = v
        if key == "matmul_precision":
            jax.config.update("jax_default_matmul_precision",
                              None if v == "default" else v)


def get_flags(keys=None):
    if keys is None:
        return dict(_FLAGS)
    if isinstance(keys, str):
        keys = [keys]
    return {k: _FLAGS[k.replace("FLAGS_", "")] for k in keys}


# ---------------------------------------------------------------------------
# devices (ref: CPUPlace / CUDAPlace / XPUPlace -> jax devices)
# ---------------------------------------------------------------------------
class Place:
    def __init__(self, kind: str, index: int = 0):
        self.kind, self.index = kind, index

    def __repr__(self):
        return f"Place({self.kind}:{self.index})"

    def __eq__(self, other):
        return (isinstance(other, Place)
                and (self.kind, self.index) == (other.kind, other.index))

    def __hash__(self):
        return hash((self.kind, self.index))


def CPUPlace():
    return Place("cpu", 0)


def TPUPlace(idx: int = 0):
    return Place("tpu", idx)


# alias so scripts written against the CUDA reference run unmodified
def CUDAPlace(idx: int = 0):
    return Place("tpu", idx)


def get_device() -> str:
    d = jax.devices()[0]
    return f"{d.platform}:{d.id}"


def set_device(device):
    # Device selection is handled by JAX/PJRT at process start; accept and
    # validate for API parity.
    return get_device()


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return any(d.platform == "tpu" for d in jax.devices())


def disable_static(place=None):
    """ref: paddle.disable_static — enter dygraph. This framework is
    always dynamic-over-XLA, so this is a no-op kept for the countless
    reference scripts that call it at startup."""
    return None


def enable_static():
    """ref: paddle.enable_static — the static Program/Executor mode.
    Deliberately not supported (SURVEY §2.12 static shim): trace with
    @paddle.jit.to_static and export StableHLO via paddle.jit.save
    instead; paddle.static.InputSpec works unchanged."""
    raise NotImplementedError(
        "static-graph mode is not supported on the TPU backend. "
        "Migration: decorate with @paddle.jit.to_static (InputSpec "
        "supported) and use paddle.jit.save/load for deployment — "
        "see paddle_tpu.static for the shim and recipes.")


def device_count() -> int:
    return jax.device_count()


# ---------------------------------------------------------------------------
# RNG (ref: Generator per place + paddle.seed). A single root key plus a
# fold-in counter gives deterministic, splittable eager randomness; traced
# code must use rng_scope (see nn/layer.py) so keys are explicit jit inputs.
# ---------------------------------------------------------------------------
class Generator:
    def __init__(self, seed: int = 0):
        self.manual_seed(seed)

    def manual_seed(self, seed: int):
        self._seed = int(seed)
        # LAZY key creation: PRNGKey allocates a device array, and the
        # module-level default generator must not touch the device at
        # `import paddle_tpu` time (a wedged remote backend would hang
        # the import; also keeps array-only imports fast)
        self._key = None
        self._counter = 0
        return self

    def initial_seed(self) -> int:
        return self._seed

    @property
    def key(self):
        if self._key is None:
            self._key = jax.random.PRNGKey(self._seed)
        return self._key

    def next_key(self):
        self._counter += 1
        return jax.random.fold_in(self.key, self._counter)


_default_generator = Generator(int(os.environ.get("PADDLE_TPU_SEED", "0")))


def seed(s: int):
    """ref: paddle.seed — reseeds the global generator."""
    _default_generator.manual_seed(s)
    return _default_generator


def default_generator() -> Generator:
    return _default_generator


def next_rng_key():
    """Next eager PRNG key. Inside a traced rng_scope, pulls from the scope
    instead so the key is a proper jit input (see nn/layer.py)."""
    scope = getattr(_state, "rng_scope", None)
    if scope is not None:
        return scope.next_key()
    return _default_generator.next_key()


@contextlib.contextmanager
def _rng_scope_ctx(scope):
    prev = getattr(_state, "rng_scope", None)
    _state.rng_scope = scope
    try:
        yield scope
    finally:
        _state.rng_scope = prev


class RNGScope:
    """Deterministic key stream derived from one root key by fold-in."""

    def __init__(self, key):
        self._key = key
        self._counter = 0

    def next_key(self):
        self._counter += 1
        return jax.random.fold_in(self._key, self._counter)

    def scope(self):
        return _rng_scope_ctx(self)


def rng_scope(key):
    """Route all framework randomness below this context to `key`."""
    return RNGScope(key).scope()


def in_dynamic_mode() -> bool:
    """ref: paddle.in_dynamic_mode — eager unless inside a jax trace."""
    try:
        from jax.core import trace_state_clean
        return trace_state_clean()
    except Exception:
        return True


class _DtypeInfo:
    __slots__ = ("min", "max", "bits", "dtype", "eps", "tiny", "smallest_normal")

    def __repr__(self):
        return f"{type(self).__name__}(dtype={self.dtype})"


def iinfo(dtype):
    """ref: paddle.iinfo."""
    import numpy as np
    d = convert_dtype(dtype)
    inf = np.iinfo(np.dtype(str(jnp.dtype(d))))
    out = _DtypeInfo()
    out.min, out.max, out.bits = int(inf.min), int(inf.max), int(inf.bits)
    out.dtype = str(inf.dtype)
    return out


def finfo(dtype):
    """ref: paddle.finfo."""
    d = convert_dtype(dtype)
    inf = jnp.finfo(d)
    out = _DtypeInfo()
    out.min, out.max, out.bits = float(inf.min), float(inf.max), int(inf.bits)
    out.eps = float(inf.eps)
    out.tiny = float(inf.tiny)
    out.smallest_normal = float(inf.smallest_normal)
    out.dtype = str(inf.dtype)
    return out


def get_rng_state(device=None):
    """ref: paddle.get_rng_state — snapshot of the global generator."""
    g = _default_generator
    return {"seed": g.initial_seed(), "counter": g._counter}


def set_rng_state(state, device=None):
    """ref: paddle.set_rng_state."""
    g = _default_generator
    g.manual_seed(int(state["seed"]))
    g._counter = int(state.get("counter", 0))


# the reference's CUDA-specific variants map to the same global generator
get_cuda_rng_state = get_rng_state
set_cuda_rng_state = set_rng_state


class LazyGuard:
    """ref: paddle.LazyGuard gate — delayed parameter materialization is a
    Program-era feature for CPU-bound giant-model init. The TPU path
    constructs params as jax arrays whose initializers are already lazy
    device computations (no host round trip), and sharded construction
    belongs to `shard_model` + the Engine's placement; a distinct lazy
    mode would add staging complexity with no TPU win. Using it raises
    with that recipe."""

    def __enter__(self):
        raise NotImplementedError(
            "LazyGuard: construct the model normally (param init is "
            "already device-lazy under XLA) and use "
            "paddle_tpu.distributed.fleet.mpu.shard_model(model, mesh) "
            "for sharded placement of large models")

    def __exit__(self, *a):
        return False
